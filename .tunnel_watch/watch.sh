#!/bin/bash
# Probes the axon tunnel every 10 min; writes status lines to status.log.
# On first success, writes LIVE marker file and keeps watching.
while true; do
  ts=$(date -u +%H:%M:%S)
  out=$(timeout 150 python -c "import jax; jax.numpy.zeros(8).block_until_ready(); print('OK', [d.platform for d in jax.devices()])" 2>&1)
  rc=$?
  if [ $rc -eq 0 ] && echo "$out" | grep -q "OK"; then
    echo "$ts LIVE $out" >> /root/repo/.tunnel_watch/status.log
    touch /root/repo/.tunnel_watch/LIVE
  else
    echo "$ts DOWN rc=$rc $(echo "$out" | tail -1 | head -c 120)" >> /root/repo/.tunnel_watch/status.log
    rm -f /root/repo/.tunnel_watch/LIVE
  fi
  sleep 600
done
