"""Benchmark: BASELINE.json configs on one chip.

Configs (BASELINE.md, scaled to BENCH_ROWS total rows each):
  q1  SSB Q1.1-style range filter + SUM           (1 segment)
  q2  SSB Q2-style dict filter + GROUP BY 2 dims  (1 segment)   ← headline
  q3  high-cardinality GROUP BY (sparse sort-based device path)
  q4  16-segment combine of q2 (batched async dispatch)
  q5  NYC-Taxi-style COUNT DISTINCT + PERCENTILE_TDIGEST GROUP BY day

The CPU baseline is this repo's host (numpy) engine running segments on a
worker pool sized to the machine's cores (the reference publishes no
absolute numbers — BASELINE.md — so the ratio is measured against the
parallel vectorized CPU path on the same machine). Roofline: bytes/s is
the column-plane bytes each query must read from HBM divided by p50,
reported against the v5e peak of ~819 GB/s.

Prints ONE JSON line:
  {"metric": ..., "value": rows/sec/chip, "unit": "rows/s", "vs_baseline": x}

Env knobs: BENCH_ROWS (default 100M), BENCH_ITERS (default 10),
BENCH_PLATFORM (e.g. cpu for local runs), BENCH_CONFIGS (csv, default all).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 100_000_000))
ITERS = int(os.environ.get("BENCH_ITERS", 10))
# global wall budget: emit whatever finished instead of being timed out by
# the harness with NOTHING (round 1 lost its whole artifact that way)
TIME_BUDGET_S = float(os.environ.get("BENCH_TIME_BUDGET_S", 2400))
_START = time.monotonic()
CONFIGS = os.environ.get("BENCH_CONFIGS", "q1,q2,q3,q4,q5,q6").split(",")
CACHE = Path(__file__).parent / ".bench_cache"
V5E_HBM_PEAK = 819e9  # bytes/s

Q1 = ("SELECT SUM(lo_extendedprice) FROM {t} WHERE d_year = 1993 "
      "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25")
Q2 = ("SELECT d_year, p_brand, SUM(lo_revenue) FROM {t} "
      "WHERE s_region = 'ASIA' GROUP BY d_year, p_brand LIMIT 10000")
Q3 = ("SET numGroupsLimit = 20000000; "
      "SELECT lo_orderkey, SUM(lo_revenue), COUNT(*) FROM {t} "
      "GROUP BY lo_orderkey ORDER BY lo_orderkey LIMIT 100000")
# numGroupsLimit = the reference default (100K): the device sort-trim keeps
# the smallest 100K keys per segment, which is exact for ORDER BY key ASC
# LIMIT 100K, and bounds the host-side state decode
Q6 = ("SET numGroupsLimit = 100000; "
      "SELECT lo_orderkey, DISTINCTCOUNT(lo_discount), SUM(lo_revenue) "
      "FROM {t} GROUP BY lo_orderkey ORDER BY lo_orderkey LIMIT 100000")
Q5 = ("SELECT pickup_day, DISTINCTCOUNT(passenger_count), "
      "PERCENTILETDIGEST(fare, 95) FROM taxi GROUP BY pickup_day LIMIT 1000")


def _gen_ssb(rows: int, seed: int = 2024):
    rng = np.random.default_rng(seed)
    return {
        "d_year": rng.integers(1992, 1999, rows).astype(np.int32),
        "p_brand": (rng.integers(0, 1000, rows)).astype(np.int32),
        "s_region": np.asarray(["AMERICA", "ASIA", "EUROPE", "AFRICA", "MIDDLE EAST"],
                               dtype=object)[rng.integers(0, 5, rows)],
        "lo_discount": rng.integers(0, 11, rows).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, rows).astype(np.int32),
        "lo_extendedprice": rng.integers(1, 55_001, rows).astype(np.int32),
        "lo_revenue": rng.integers(1, 600_000, rows).astype(np.int32),
        # high-card key for the sparse group-by config (~rows/10 distinct)
        "lo_orderkey": rng.integers(0, max(1 << 22, rows // 10), rows).astype(np.int32),
    }


def _ssb_schema(name: str):
    from pinot_tpu.spi.data_types import Schema

    return Schema.build(
        name,
        dimensions=[("d_year", "INT"), ("p_brand", "INT"), ("s_region", "STRING"),
                    ("lo_discount", "INT"), ("lo_quantity", "INT"),
                    ("lo_orderkey", "INT")],
        metrics=[("lo_extendedprice", "INT"), ("lo_revenue", "INT")],
    )


def _taxi_schema():
    from pinot_tpu.spi.data_types import Schema

    return Schema.build(
        "taxi",
        dimensions=[("pickup_day", "INT"), ("passenger_count", "INT")],
        metrics=[("fare", "DOUBLE")],
    )


def _build(schema, cols, out_dir, seg_name, no_dict=()):
    from pinot_tpu.segment.builder import SegmentBuilder
    from pinot_tpu.spi.table_config import IndexingConfig, TableConfig

    cfg = TableConfig(table_name=schema.schema_name, indexing=IndexingConfig(
        no_dictionary_columns=list(no_dict)))
    t0 = time.perf_counter()
    SegmentBuilder(schema, cfg, seg_name).build(cols, out_dir)
    print(f"[bench] built {seg_name} ({len(next(iter(cols.values()))):,} rows) "
          f"in {time.perf_counter()-t0:.1f}s", file=sys.stderr)


def _load_table(qe_list, schema, seg_dirs):
    from pinot_tpu.segment.loader import load_segment

    segs = [load_segment(d) for d in seg_dirs]
    for qe in qe_list:
        qe.add_table(schema, segs)
    return segs


def prepare_tables(need_ssb, need_ssb16, need_taxi):
    """Build (once, cached on disk) and return {table: (schema, seg_dirs)}."""
    out = {}
    ssb_cols = None
    if need_ssb or need_ssb16:
        schema = _ssb_schema("ssb")
        d = CACHE / f"ssb_{ROWS}_v2"
        if not (d / "metadata.json").exists():
            ssb_cols = _gen_ssb(ROWS)
            print(f"[bench] generating ssb {ROWS:,} rows", file=sys.stderr)
            _build(schema, ssb_cols, d, "ssb_0",
                   no_dict=["lo_extendedprice", "lo_revenue"])
        out["ssb"] = (schema, [d])
    if need_ssb16:
        schema16 = _ssb_schema("ssb16")
        dirs = [CACHE / f"ssb16_{ROWS}" / f"s{i}" for i in range(16)]
        if not (dirs[-1] / "metadata.json").exists():
            if ssb_cols is None:
                ssb_cols = _gen_ssb(ROWS)
            bounds = np.linspace(0, ROWS, 17, dtype=np.int64)
            for i in range(16):
                sl = slice(int(bounds[i]), int(bounds[i + 1]))
                _build(schema16, {k: v[sl] for k, v in ssb_cols.items()},
                       dirs[i], f"ssb16_{i}",
                       no_dict=["lo_extendedprice", "lo_revenue"])
        out["ssb16"] = (schema16, dirs)
    del ssb_cols
    if need_taxi:
        schema = _taxi_schema()
        d = CACHE / f"taxi_{ROWS}"
        if not (d / "metadata.json").exists():
            rng = np.random.default_rng(7)
            print(f"[bench] generating taxi {ROWS:,} rows", file=sys.stderr)
            cols = {
                "pickup_day": rng.integers(0, 730, ROWS).astype(np.int32),
                "passenger_count": rng.integers(1, 9, ROWS).astype(np.int32),
                "fare": np.round(rng.gamma(3.0, 9.0, ROWS), 2),
            }
            _build(schema, cols, d, "taxi_0", no_dict=["fare"])
        out["taxi"] = (schema, [d])
    return out


def _probe_accelerator(probe_s: float) -> bool:
    """True iff a throwaway subprocess can run one device op within
    probe_s. Transient init ERRORS get a second attempt (round-1 failure
    mode); a TIMEOUT doesn't — a held lease won't heal in seconds. stderr
    goes to a temp FILE, not a pipe: a wedged tunnel's helper process can
    inherit a pipe fd and keep it open, which would block the parent in
    communicate() past the timeout. The probe runs in its own session so
    the timeout kill takes the whole process group with it."""
    import signal
    import subprocess
    import tempfile

    for attempt in range(2):
        with tempfile.TemporaryFile() as ef:
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; jax.numpy.zeros(8).block_until_ready()"],
                stdout=subprocess.DEVNULL, stderr=ef,
                start_new_session=True)
            try:
                if proc.wait(timeout=probe_s) == 0:
                    return True
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except Exception:
                    proc.kill()
                proc.wait()
                print(f"[bench] accelerator probe hung (> {probe_s:.0f}s)",
                      file=sys.stderr)
                return False
            ef.seek(0)
            tail = ef.read()[-2000:].decode(errors="replace").strip()
            print(f"[bench] probe attempt {attempt + 1} failed:\n{tail}",
                  file=sys.stderr)
    return False


def _init_backend():
    """Initialize a jax backend with retry + CPU fallback.

    Round 1 died here: one transient axon/TPU init error at jax.devices()
    crashed the whole bench (BENCH_r01.json rc=1). Retry with backoff; if the
    accelerator never comes up, fall back to CPU so the round still produces
    a parseable (clearly-labelled) number.
    """
    # a wedged accelerator tunnel HANGS at first device use rather than
    # erroring (observed: axon lease held by a killed process) — probe in a
    # disposable subprocess with a hard timeout BEFORE importing jax here,
    # so a hang costs probe_s (per attempt), not the whole bench budget.
    # Cost on a healthy accelerator: one extra backend init (~10-20s of the
    # 2400s budget). BENCH_INIT_PROBE_S=0 disables the probe.
    probe_note = None
    probe_s = float(os.environ.get("BENCH_INIT_PROBE_S", 180))
    if not os.environ.get("BENCH_PLATFORM") and probe_s > 0:
        if not _probe_accelerator(probe_s):
            print(f"[bench] accelerator probe failed/hung; forcing CPU",
                  file=sys.stderr)
            probe_note = "accelerator probe failed or hung, ran on cpu"
            os.environ["BENCH_PLATFORM"] = "cpu"
            os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    from jax.extend import backend as jex_backend

    try:  # persist compiles across bench runs (no-op for remote compile).
        # NOT shared with the test suite's cache: pytest compiles under
        # different XLA flags and the AOT loader warns cross-loading could
        # SIGILL on mismatched machine-feature sets
        jax.config.update("jax_compilation_cache_dir",
                          str(Path(__file__).parent / ".jax_cache_bench"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass

    if os.environ.get("BENCH_PLATFORM"):  # e.g. cpu for local runs; axon default
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    last_err = None
    attempts = 4
    for attempt in range(attempts):
        if attempt:
            time.sleep(min(5 * 2 ** (attempt - 1), 20))
        try:
            devs = jax.devices()
            print(f"[bench] devices: {devs}", file=sys.stderr)
            return jax, devs[0].platform, probe_note
        except Exception as e:  # backend init is the flaky part
            last_err = e
            print(f"[bench] backend init attempt {attempt + 1} failed: {e}",
                  file=sys.stderr)
            try:
                jex_backend.clear_backends()
            except Exception:
                pass
    print("[bench] falling back to CPU platform", file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    try:
        jex_backend.clear_backends()
    except Exception:
        pass
    devs = jax.devices()
    if devs[0].platform != "cpu":  # partial-cache left an accelerator backend
        return jax, devs[0].platform, None
    return jax, "cpu", f"accelerator init failed, ran on cpu: {last_err}"


def _plan_bytes(qe, sql, segments):
    """Column-plane bytes one execution must read (device roofline input)."""
    from pinot_tpu.query.parser.sql import parse_sql

    try:
        query = parse_sql(sql)
        total = 0
        for seg in segments:
            plan = qe.tpu.plan(query, seg)
            view = qe.tpu.cache.view(seg)
            arrays, _ = plan.gather_arrays_packed(view)
            total += sum(int(np.asarray(a).nbytes) if not hasattr(a, "nbytes")
                         else int(a.nbytes) for a in arrays)
        return total
    except Exception:
        return None


def _time_query(qe, sql, iters):
    r = qe.execute_sql(sql)  # warmup / compile / HBM residency
    if r.exceptions:
        raise RuntimeError(f"{sql}: {r.exceptions}")
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        r = qe.execute_sql(sql)
        times.append(time.perf_counter() - t0)
    if r.exceptions:
        raise RuntimeError(f"{sql}: {r.exceptions}")
    return float(np.median(times)), r


def _rows_match(a, b, rel_tol=0.0) -> bool:
    if len(a) != len(b):
        return False
    if rel_tol == 0.0:
        return sorted(map(repr, a)) == sorted(map(repr, b))

    def key(row):
        return tuple(x for x in row if not isinstance(x, float))

    bm = {key(r): r for r in b}
    for r in a:
        other = bm.get(key(r))
        if other is None:
            return False
        for x, y in zip(r, other):
            if isinstance(x, float) and isinstance(y, float):
                if abs(x - y) > rel_tol * max(1.0, abs(x), abs(y)):
                    return False
    return True


def main():
    jax, platform, backend_note = _init_backend()
    from pinot_tpu.engine.query_executor import QueryExecutor

    need_ssb = any(c in CONFIGS for c in ("q1", "q2", "q3", "q6"))
    need_ssb16 = "q4" in CONFIGS
    need_taxi = "q5" in CONFIGS
    tables = prepare_tables(need_ssb, need_ssb16, need_taxi)

    ncpu = os.cpu_count() or 1
    tpu = QueryExecutor(backend="tpu")
    host = QueryExecutor(backend="host", num_threads=ncpu)
    loaded = {}
    for name, (schema, dirs) in tables.items():
        loaded[name] = _load_table([tpu, host], schema, dirs)

    runs = {
        "q1_filter_sum": ("q1", Q1.format(t="ssb"), "ssb", ITERS, 0.0),
        "q2_groupby": ("q2", Q2.format(t="ssb"), "ssb", ITERS, 0.0),
        "q3_highcard_groupby": ("q3", Q3.format(t="ssb"), "ssb",
                                max(3, ITERS // 3), 0.0),
        "q4_combine16": ("q4", Q2.format(t="ssb16"), "ssb16", ITERS, 0.0),
        # device tdigest is a fixed-bin histogram approximation; compare the
        # host exact percentile within 1%
        # 2%: PERCENTILETDIGEST is approximate on BOTH paths (value-fed vs
        # histogram-fed digests); a p95 falling in a sparse tail gap of
        # cent-rounded fares interpolates across the same gap from
        # different cum positions — observed 1.2% on 1/730 groups
        "q5_distinct_tdigest": ("q5", Q5, "taxi", max(3, ITERS // 3), 0.02),
        # sparse (sort-based) COUNT DISTINCT inside a high-card group-by —
        # the device pair-dedup path (VERDICT weak #5)
        "q6_sparse_distinct": ("q6", Q6.format(t="ssb"), "ssb",
                               max(3, ITERS // 3), 0.0),
    }

    results = {}
    skipped = []
    for name, (cfg, sql, tname, iters, tol) in runs.items():
        if cfg not in CONFIGS:
            continue
        if time.monotonic() - _START > TIME_BUDGET_S:
            skipped.append(name)
            print(f"[bench] SKIP {name}: time budget exhausted", file=sys.stderr)
            continue
        segs = loaded[tname]
        p50, r = _time_query(tpu, sql, iters)
        host_p50, rh = _time_query(host, sql, max(1, min(3, iters)))
        match = _rows_match(r.result_table.rows, rh.result_table.rows, tol)
        nbytes = _plan_bytes(tpu, sql, segs)
        results[name] = {
            "tpu_p50_s": p50,
            "rows_per_sec": ROWS / p50,
            "host_parallel_s": host_p50,
            "speedup": host_p50 / p50,
            "match": match,
        }
        if nbytes:
            results[name]["hbm_bytes"] = nbytes
            results[name]["hbm_bytes_per_sec"] = nbytes / p50
            results[name]["hbm_peak_frac"] = (nbytes / p50) / V5E_HBM_PEAK
        print(f"[bench] {name}: p50 {p50*1000:.1f}ms "
              f"({ROWS/p50/1e9:.2f}B rows/s), host({ncpu}thr) "
              f"{host_p50*1000:.0f}ms, speedup {host_p50/p50:.1f}x, "
              f"match={match}"
              + (f", {nbytes/p50/1e9:.0f} GB/s "
                 f"({100*(nbytes/p50)/V5E_HBM_PEAK:.0f}% v5e peak)"
                 if nbytes else ""),
              file=sys.stderr)

    if not results:
        raise RuntimeError(f"no benchmark configs ran (BENCH_CONFIGS={CONFIGS})")
    if "q2_groupby" in results:
        hname, metric = "q2_groupby", "ssb_100m_q2_filter_groupby_rows_per_sec_per_chip"
    else:
        hname = next(iter(results))
        metric = f"{hname}_rows_per_sec_per_chip"
    headline = results[hname]
    out = {
        "metric": metric,
        "value": round(headline["rows_per_sec"]),
        "unit": "rows/s",
        "vs_baseline": round(headline["speedup"], 2),
        "detail": {k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                       for kk, vv in v.items()} for k, v in results.items()},
        "rows": ROWS,
        "host_threads": ncpu,
        "platform": platform,
    }
    if backend_note:
        out["warning"] = backend_note
    if skipped:
        out["skipped_configs"] = skipped
    print(json.dumps(out))


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # still emit ONE parseable JSON line for the driver
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "ssb_100m_q2_filter_groupby_rows_per_sec_per_chip",
            "value": 0,
            "unit": "rows/s",
            "vs_baseline": 0,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(0)
