"""Benchmark: SSB-style filter + group-by on one chip.

Reproduces BASELINE.json configs #2/#3 (SSB 100M rows, 1 segment): Q1.1-style
range-filter + SUM, and Q2-style dictionary filter + GROUP BY 2 dims. The CPU
baseline is this repo's host (numpy) engine — the reference publishes no
absolute numbers (BASELINE.md), so the ratio is measured against the
vectorized CPU path on this machine, per BASELINE.md's instruction to
generate our own CPU reference numbers.

Prints ONE JSON line:
  {"metric": ..., "value": rows/sec/chip, "unit": "rows/s", "vs_baseline": x}

Env knobs: BENCH_ROWS (default 100M), BENCH_ITERS (default 10).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 100_000_000))
ITERS = int(os.environ.get("BENCH_ITERS", 10))
CACHE_DIR = Path(__file__).parent / ".bench_cache" / f"ssb_{ROWS}"

Q1 = ("SELECT SUM(lo_extendedprice) FROM ssb WHERE d_year = 1993 "
      "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25")
Q2 = ("SELECT d_year, p_brand, SUM(lo_revenue) FROM ssb "
      "WHERE s_region = 'ASIA' GROUP BY d_year, p_brand LIMIT 10000")


def build_segment():
    from pinot_tpu.segment.builder import SegmentBuilder
    from pinot_tpu.spi.data_types import Schema
    from pinot_tpu.spi.table_config import IndexingConfig, TableConfig

    rng = np.random.default_rng(2024)
    print(f"[bench] generating {ROWS:,} rows", file=sys.stderr)
    cols = {
        "d_year": rng.integers(1992, 1999, ROWS).astype(np.int32),
        "p_brand": (rng.integers(0, 1000, ROWS)).astype(np.int32),
        "s_region": np.asarray(["AMERICA", "ASIA", "EUROPE", "AFRICA", "MIDDLE EAST"],
                               dtype=object)[rng.integers(0, 5, ROWS)],
        "lo_discount": rng.integers(0, 11, ROWS).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, ROWS).astype(np.int32),
        "lo_extendedprice": rng.integers(1, 55_001, ROWS).astype(np.int32),
        "lo_revenue": rng.integers(1, 600_000, ROWS).astype(np.int32),
    }
    schema = Schema.build(
        "ssb",
        dimensions=[("d_year", "INT"), ("p_brand", "INT"), ("s_region", "STRING"),
                    ("lo_discount", "INT"), ("lo_quantity", "INT")],
        metrics=[("lo_extendedprice", "INT"), ("lo_revenue", "INT")],
    )
    cfg = TableConfig(table_name="ssb", indexing=IndexingConfig(
        no_dictionary_columns=["lo_extendedprice", "lo_revenue"]))
    print("[bench] building segment", file=sys.stderr)
    t0 = time.perf_counter()
    SegmentBuilder(schema, cfg, "ssb_0").build(cols, CACHE_DIR)
    print(f"[bench] built in {time.perf_counter()-t0:.1f}s", file=sys.stderr)
    return schema


def _init_backend():
    """Initialize a jax backend with retry + CPU fallback.

    Round 1 died here: one transient axon/TPU init error at jax.devices()
    crashed the whole bench (BENCH_r01.json rc=1). Retry with backoff; if the
    accelerator never comes up, fall back to CPU so the round still produces
    a parseable (clearly-labelled) number.
    """
    import jax
    from jax.extend import backend as jex_backend

    if os.environ.get("BENCH_PLATFORM"):  # e.g. cpu for local runs; axon default
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    last_err = None
    attempts = 4
    for attempt in range(attempts):
        if attempt:
            time.sleep(min(5 * 2 ** (attempt - 1), 20))
        try:
            devs = jax.devices()
            print(f"[bench] devices: {devs}", file=sys.stderr)
            return jax, devs[0].platform, None
        except Exception as e:  # backend init is the flaky part
            last_err = e
            print(f"[bench] backend init attempt {attempt + 1} failed: {e}",
                  file=sys.stderr)
            try:
                jex_backend.clear_backends()
            except Exception:
                pass
    print("[bench] falling back to CPU platform", file=sys.stderr)
    jax.config.update("jax_platforms", "cpu")
    try:
        jex_backend.clear_backends()
    except Exception:
        pass
    devs = jax.devices()
    if devs[0].platform != "cpu":  # partial-cache left an accelerator backend
        return jax, devs[0].platform, None
    return jax, "cpu", f"accelerator init failed, ran on cpu: {last_err}"


def main():
    jax, platform, backend_note = _init_backend()
    from pinot_tpu.engine.query_executor import QueryExecutor
    from pinot_tpu.segment.loader import load_segment
    from pinot_tpu.spi.data_types import Schema

    if not (CACHE_DIR / "metadata.json").exists():
        schema = build_segment()
    else:
        print("[bench] using cached segment", file=sys.stderr)
        schema = None
    segment = load_segment(CACHE_DIR)
    if schema is None:
        schema = Schema.build(
            "ssb",
            dimensions=[("d_year", "INT"), ("p_brand", "INT"), ("s_region", "STRING"),
                        ("lo_discount", "INT"), ("lo_quantity", "INT")],
            metrics=[("lo_extendedprice", "INT"), ("lo_revenue", "INT")],
        )

    tpu = QueryExecutor(backend="tpu")
    tpu.add_table(schema, [segment])
    host = QueryExecutor(backend="host")
    host.add_table(schema, [segment])

    results = {}
    for name, sql in [("q1_filter_sum", Q1), ("q2_groupby", Q2)]:
        # warmup / compile (also pushes planes to HBM once)
        r = tpu.execute_sql(sql)
        if r.exceptions:
            raise RuntimeError(f"{name}: {r.exceptions}")
        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            r = tpu.execute_sql(sql)
            times.append(time.perf_counter() - t0)
        p50 = float(np.median(times))
        t0 = time.perf_counter()
        rh = host.execute_sql(sql)
        host_s = time.perf_counter() - t0
        if rh.exceptions:
            raise RuntimeError(f"host {name}: {rh.exceptions}")
        assert r.result_table.rows is not None
        match = _rows_match(r.result_table.rows, rh.result_table.rows)
        results[name] = {
            "tpu_p50_s": p50,
            "rows_per_sec": ROWS / p50,
            "host_s": host_s,
            "speedup": host_s / p50,
            "match": match,
        }
        print(f"[bench] {name}: p50 {p50*1000:.1f}ms "
              f"({ROWS/p50/1e9:.2f}B rows/s), host {host_s*1000:.0f}ms, "
              f"speedup {host_s/p50:.1f}x, match={match}", file=sys.stderr)

    q2 = results["q2_groupby"]
    out = {
        "metric": "ssb_100m_q2_filter_groupby_rows_per_sec_per_chip",
        "value": round(q2["rows_per_sec"]),
        "unit": "rows/s",
        "vs_baseline": round(q2["speedup"], 2),
        "detail": {k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                       for kk, vv in v.items()} for k, v in results.items()},
        "rows": ROWS,
        "platform": platform,
    }
    if backend_note:
        out["warning"] = backend_note
    print(json.dumps(out))


def _rows_match(a, b) -> bool:
    if len(a) != len(b):
        return False
    sa = sorted(map(repr, a))
    sb = sorted(map(repr, b))
    return sa == sb


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # still emit ONE parseable JSON line for the driver
        import traceback

        traceback.print_exc()
        print(json.dumps({
            "metric": "ssb_100m_q2_filter_groupby_rows_per_sec_per_chip",
            "value": 0,
            "unit": "rows/s",
            "vs_baseline": 0,
            "error": f"{type(e).__name__}: {e}",
        }))
        sys.exit(0)
