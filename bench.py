"""Benchmark: BASELINE.json configs on one chip.

Configs (BASELINE.md, scaled to BENCH_ROWS total rows each):
  q1  SSB Q1.1-style range filter + SUM           (1 segment)
  q2  SSB Q2-style dict filter + GROUP BY 2 dims  (1 segment)   ← headline
  q3  high-cardinality GROUP BY (sparse device path)
  q4  16-segment combine of q2 (batched async dispatch)
  q5  NYC-Taxi-style COUNT DISTINCT + PERCENTILE_TDIGEST GROUP BY day
  q6  sparse COUNT DISTINCT inside a high-card group-by
  q7  LOOKUP star join    q8  MSE equi-join    q9  3-SUM group-by
  q9j MSE LEFT join (residual ON filter)   q10  MSE 2-join chain

Architecture (hardened after rounds 1-2 produced zero TPU artifacts):
  * The PARENT process never touches the accelerator. It probes it in a
    disposable subprocess, builds/caches segments on CPU, then runs each
    config in its OWN subprocess (`bench.py --config qN --out FILE`).
  * Each child enforces an INTERNAL deadline (checked between iterations)
    and exits cleanly, releasing the TPU lease. Nothing is ever externally
    killed mid-device-op: killing a process holding the axon lease wedges
    the tunnel for hours (round-2 failure mode). A child that outlives its
    deadline + grace is abandoned (orphaned, not killed) and remaining
    configs are skipped.
  * The parent RE-PRINTS the full summary JSON line after every config
    completes (flushing stdout), so even if the driver times the bench out,
    the last parseable line carries every config that finished. Partials
    also land in .bench_partial/*.json.

The CPU baseline is this repo's host (numpy) engine on the same machine
(the reference publishes no absolute numbers — BASELINE.md). Roofline:
bytes/s is the column-plane bytes each query must read from HBM divided
by p50, reported against the v5e peak of ~819 GB/s.

Prints ONE JSON line (repeatedly, updated as configs finish):
  {"metric": ..., "value": rows/sec/chip, "unit": "rows/s", "vs_baseline": x}

Env knobs: BENCH_ROWS (default 100M), BENCH_ITERS (default 10),
BENCH_PLATFORM (e.g. cpu for local runs), BENCH_CONFIGS (csv, default all),
BENCH_TIME_BUDGET_S (default 2040 — below the driver's external timeout so
the parent always gets to emit).
"""

from __future__ import annotations

import json
import os
import sys
import time
from pathlib import Path

import numpy as np

ROWS = int(os.environ.get("BENCH_ROWS", 100_000_000))
ITERS = int(os.environ.get("BENCH_ITERS", 10))
TIME_BUDGET_S = float(os.environ.get("BENCH_TIME_BUDGET_S", 2040))
_START = time.monotonic()
# q6 runs LAST: its sparse-distinct program has the slowest cold compile,
# and a hung/abandoned child skips every config after it
CONFIGS = [c for c in os.environ.get(
    "BENCH_CONFIGS",
    "q1,q2,q9,q3,q4,q5,q7,q8,q9j,q10,q3m,q6m,q11r,q6").split(",") if c]
ROOT = Path(__file__).parent
CACHE = ROOT / ".bench_cache"
# smoke/dev runs point this elsewhere (BENCH_PARTIAL_DIR) so they never
# overwrite the committed record of the last real TPU run
PARTIAL = Path(os.environ.get("BENCH_PARTIAL_DIR", ROOT / ".bench_partial"))
V5E_HBM_PEAK = 819e9  # bytes/s

Q1 = ("SELECT SUM(lo_extendedprice) FROM {t} WHERE d_year = 1993 "
      "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25")
Q2 = ("SELECT d_year, p_brand, SUM(lo_revenue) FROM {t} "
      "WHERE s_region = 'ASIA' GROUP BY d_year, p_brand LIMIT 10000")
Q3 = ("SET numGroupsLimit = 20000000; "
      "SELECT lo_orderkey, SUM(lo_revenue), COUNT(*) FROM {t} "
      "GROUP BY lo_orderkey ORDER BY lo_orderkey LIMIT 100000")
# numGroupsLimit = the reference default (100K): the device sort-trim keeps
# the smallest 100K keys per segment, which is exact for ORDER BY key ASC
# LIMIT 100K, and bounds the host-side state decode
Q6 = ("SET numGroupsLimit = 100000; "
      "SELECT lo_orderkey, DISTINCTCOUNT(lo_discount), SUM(lo_revenue) "
      "FROM {t} GROUP BY lo_orderkey ORDER BY lo_orderkey LIMIT 100000")
Q5 = ("SELECT pickup_day, DISTINCTCOUNT(passenger_count), "
      "PERCENTILETDIGEST(fare, 95) FROM taxi GROUP BY pickup_day LIMIT 1000")
# SSB Q4-style dimension join: filter + group on LOOKUP'd dim attributes —
# the TPU-first broadcast join (dim attrs ride the fact kernel as LUT
# gathers; reference pattern: LookupTransformFunction star joins)
Q7 = ("SELECT d_year, LOOKUP('brands', 'b_category', 'b_id', p_brand), "
      "SUM(lo_revenue) FROM {t} "
      "WHERE LOOKUP('brands', 'b_region', 'b_id', p_brand) = 'ASIA' "
      "GROUP BY d_year, LOOKUP('brands', 'b_category', 'b_id', p_brand) "
      "LIMIT 1000")
# MSE equi-join (the full V2 pipeline: device leaf selections → shuffle →
# sort-merge join, device-side when the key volume clears the gate —
# mse/device_join.py; reference pattern: HashJoinOperator two-table query).
# Filters keep the pair count bounded: ~4%·N ⋈ ~9%·N on a N/10-key space
# ≈ 0.036·N expected output pairs.
Q8 = ("SELECT a.d_year, COUNT(*), SUM(b.lo_revenue) FROM {t} a "
      "JOIN {t} b ON a.lo_orderkey = b.lo_orderkey "
      "WHERE a.lo_quantity < 3 AND b.lo_discount = 0 "
      "GROUP BY a.d_year ORDER BY a.d_year LIMIT 100")
# BASELINE config 3 verbatim shape: 3 SUM measures through one MXU pass
# (1 count + 3x3 limb planes with int8 limbs)
Q9 = ("SELECT d_year, p_brand, SUM(lo_revenue), SUM(lo_extendedprice), "
      "SUM(lo_quantity) FROM {t} WHERE s_region = 'ASIA' "
      "GROUP BY d_year, p_brand LIMIT 10000")
# LEFT outer variant of q8: the build-side ON conjunct must stay join
# residual (a WHERE would flip the semantics to INNER), exercising the
# fused kernel's masked-count path; unmatched probe rows keep COUNT(*)=1
# and NULL SUM. Selectivities match q8 → same ~0.036·N pair bound.
Q9J = ("SELECT a.d_year, COUNT(*), SUM(b.lo_revenue) FROM {t} a "
       "LEFT JOIN {t} b ON a.lo_orderkey = b.lo_orderkey "
       "AND b.lo_discount = 0 WHERE a.lo_quantity < 3 "
       "GROUP BY a.d_year ORDER BY a.d_year LIMIT 100")
# 2-join chain: the middle join is absorbed into the top fused stage
# (runtime chain absorption) so the whole pipeline crosses the host once.
# c's filter multiplies q8's pair bound by ~0.2 → ~0.007·N output pairs.
Q10 = ("SELECT a.d_year, COUNT(*), SUM(c.lo_revenue) FROM {t} a "
       "JOIN {t} b ON a.lo_orderkey = b.lo_orderkey "
       "JOIN {t} c ON b.lo_orderkey = c.lo_orderkey "
       "WHERE a.lo_quantity < 3 AND b.lo_discount = 0 "
       "AND c.lo_quantity < 2 "
       "GROUP BY a.d_year ORDER BY a.d_year LIMIT 100")
# live-ingest config: a CONSUMING (mutable) segment executed on the
# realtime device planes (realtime/device_plane.py). The timed loop runs
# against a plane-resident snapshot; the config additionally records the
# delta-upload economics (rt_full_bytes vs rt_delta_bytes vs
# rt_warm_bytes) that the bench gate pins.
Q11R = ("SELECT site, SUM(clicks), SUM(revenue), COUNT(*) FROM rt "
        "GROUP BY site ORDER BY site LIMIT 100")

RUNS = {
    "q1": ("q1_filter_sum", Q1.format(t="ssb"), "ssb", 1.0, 0.0),
    "q2": ("q2_groupby", Q2.format(t="ssb"), "ssb", 1.0, 0.0),
    "q3": ("q3_highcard_groupby", Q3.format(t="ssb"), "ssb", 1 / 3, 0.0),
    "q4": ("q4_combine16", Q2.format(t="ssb16"), "ssb16", 1.0, 0.0),
    # PERCENTILETDIGEST is approximate on BOTH paths. The device side is
    # bounded by the adaptive histogram's refined bucket width —
    # range/bins^2 around the asked quantile (~0.05% here, ops/kernels.py
    # "hist_adaptive"); the residual is the HOST oracle's own t-digest
    # tail error (value-fed digest, compression 100: observed ~1% at p95
    # on gamma fares — consistent with t-digest's q(1-q)/compression rank
    # bound mapped through the tail density). 2% covers the host digest.
    "q5": ("q5_distinct_tdigest", Q5, "taxi", 1 / 3, 0.02),
    "q6": ("q6_sparse_distinct", Q6.format(t="ssb"), "ssb", 1 / 3, 0.0),
    "q7": ("q7_lookup_join", Q7.format(t="ssb"), "ssb", 1.0, 0.0),
    "q8": ("q8_mse_join", Q8.format(t="ssb"), "ssb", 1 / 3, 0.0),
    "q9": ("q9_groupby_3sums", Q9.format(t="ssb"), "ssb", 1.0, 0.0),
    "q9j": ("q9j_mse_left_join", Q9J.format(t="ssb"), "ssb", 1 / 3, 0.0),
    "q10": ("q10_mse_join_chain", Q10.format(t="ssb"), "ssb", 1 / 3, 0.0),
    # multi-segment (16) variants: the stacked segment-batching configs —
    # num_device_dispatches should track batch FAMILIES, not segments
    "q3m": ("q3m_highcard_groupby16", Q3.format(t="ssb16"), "ssb16",
            1 / 3, 0.0),
    "q6m": ("q6m_sparse_distinct16", Q6.format(t="ssb16"), "ssb16",
            1 / 3, 0.0),
    # live-ingest table built in-process (tname "rt" needs no prebuilt
    # table dirs); run_single short-circuits into _run_realtime_single
    "q11r": ("q11r_realtime_ingest", Q11R, "rt", 1 / 3, 0.0),
}

N_BRANDS = 1000
BRAND_CATEGORIES = 40
BRAND_REGIONS = ["AMERICA", "ASIA", "EUROPE", "AFRICA", "MIDDLE EAST"]


def _register_brands_dim():
    """In-process dimension table for q7 (reference: isDimTable tables are
    fully replicated; here the registry is process-local)."""
    from pinot_tpu.engine.dim_tables import register_dimension_table

    register_dimension_table("brands", "b_id", {
        "b_id": np.arange(N_BRANDS, dtype=np.int32),
        "b_category": np.asarray(
            [f"MFGR#{i % BRAND_CATEGORIES}" for i in range(N_BRANDS)],
            dtype=object),
        "b_region": np.asarray(
            [BRAND_REGIONS[i % len(BRAND_REGIONS)] for i in range(N_BRANDS)],
            dtype=object),
    })


def _gen_ssb(rows: int, seed: int = 2024):
    rng = np.random.default_rng(seed)
    return {
        "d_year": rng.integers(1992, 1999, rows).astype(np.int32),
        "p_brand": (rng.integers(0, 1000, rows)).astype(np.int32),
        "s_region": np.asarray(["AMERICA", "ASIA", "EUROPE", "AFRICA", "MIDDLE EAST"],
                               dtype=object)[rng.integers(0, 5, rows)],
        "lo_discount": rng.integers(0, 11, rows).astype(np.int32),
        "lo_quantity": rng.integers(1, 51, rows).astype(np.int32),
        "lo_extendedprice": rng.integers(1, 55_001, rows).astype(np.int32),
        "lo_revenue": rng.integers(1, 600_000, rows).astype(np.int32),
        # high-card key for the sparse group-by config (~rows/10 distinct),
        # SORTED in ingestion order like real SSB lineorder (rows arrive in
        # orderkey order) — the segment builder records is_sorted and q3/q6
        # ride the sparse-presorted (zero-sort) kernel path. Only the
        # marginal distribution matters to the other configs, so sorting
        # this one column changes nothing else.
        "lo_orderkey": np.sort(
            rng.integers(0, max(1 << 22, rows // 10), rows)).astype(np.int32),
    }


def _ssb_schema(name: str):
    from pinot_tpu.spi.data_types import Schema

    return Schema.build(
        name,
        dimensions=[("d_year", "INT"), ("p_brand", "INT"), ("s_region", "STRING"),
                    ("lo_discount", "INT"), ("lo_quantity", "INT"),
                    ("lo_orderkey", "INT")],
        metrics=[("lo_extendedprice", "INT"), ("lo_revenue", "INT")],
    )


def _taxi_schema():
    from pinot_tpu.spi.data_types import Schema

    return Schema.build(
        "taxi",
        dimensions=[("pickup_day", "INT"), ("passenger_count", "INT")],
        metrics=[("fare", "DOUBLE")],
    )


def _build(schema, cols, out_dir, seg_name, no_dict=()):
    from pinot_tpu.segment.builder import SegmentBuilder
    from pinot_tpu.spi.table_config import IndexingConfig, TableConfig

    cfg = TableConfig(table_name=schema.schema_name, indexing=IndexingConfig(
        no_dictionary_columns=list(no_dict)))
    t0 = time.perf_counter()
    SegmentBuilder(schema, cfg, seg_name).build(cols, out_dir)
    print(f"[bench] built {seg_name} ({len(next(iter(cols.values()))):,} rows) "
          f"in {time.perf_counter()-t0:.1f}s", file=sys.stderr)


def prepare_tables(need_ssb, need_ssb16, need_taxi):
    """Build (once, cached on disk) and return {table: (schema, seg_dirs)}."""
    out = {}
    ssb_cols = None
    if need_ssb or need_ssb16:
        schema = _ssb_schema("ssb")
        d = CACHE / f"ssb_{ROWS}_v4"
        if not (d / "metadata.json").exists():
            ssb_cols = _gen_ssb(ROWS)
            print(f"[bench] generating ssb {ROWS:,} rows", file=sys.stderr)
            _build(schema, ssb_cols, d, "ssb_0",
                   no_dict=["lo_extendedprice", "lo_revenue",
                            "lo_quantity"])
        out["ssb"] = (schema, [d])
    if need_ssb16:
        schema16 = _ssb_schema("ssb16")
        dirs = [CACHE / f"ssb16_{ROWS}_v4" / f"s{i}" for i in range(16)]
        if not (dirs[-1] / "metadata.json").exists():
            if ssb_cols is None:
                ssb_cols = _gen_ssb(ROWS)
            bounds = np.linspace(0, ROWS, 17, dtype=np.int64)
            for i in range(16):
                sl = slice(int(bounds[i]), int(bounds[i + 1]))
                _build(schema16, {k: v[sl] for k, v in ssb_cols.items()},
                       dirs[i], f"ssb16_{i}",
                       no_dict=["lo_extendedprice", "lo_revenue",
                                "lo_quantity"])
        out["ssb16"] = (schema16, dirs)
    del ssb_cols
    if need_taxi:
        schema = _taxi_schema()
        d = CACHE / f"taxi_{ROWS}"
        if not (d / "metadata.json").exists():
            rng = np.random.default_rng(7)
            print(f"[bench] generating taxi {ROWS:,} rows", file=sys.stderr)
            cols = {
                "pickup_day": rng.integers(0, 730, ROWS).astype(np.int32),
                "passenger_count": rng.integers(1, 9, ROWS).astype(np.int32),
                "fare": np.round(rng.gamma(3.0, 9.0, ROWS), 2),
            }
            _build(schema, cols, d, "taxi_0", no_dict=["fare"])
        out["taxi"] = (schema, [d])
    return out


def _remaining() -> float:
    return TIME_BUDGET_S - (time.monotonic() - _START)


# --------------------------------------------------------------------------
# parent: probe + orchestrate per-config children
# --------------------------------------------------------------------------

def _probe_accelerator():
    """(ok, report) — ok iff a throwaway subprocess can run one device op.

    ``report`` distinguishes the two failure modes round reports kept
    conflating ("no TPU available" vs "our code broke on TPU"):
      {"status": "ok" | "hung" | "errored" | "skipped",
       "env": {"JAX_PLATFORMS": ..., "PJRT_DEVICE": ...},
       "devices": str,    # jax.devices() of the successful probe
       "attempts": [{"rc": int, "stderr_tail": str, "stderr": str}, ...]}
    ``stderr`` is the subprocess's FULL stderr (the ..._tail truncation
    kept discarding the one line that named the real init failure);
    ``env`` records the probe's effective platform-selection variables.
    It rides into the BENCH json (probe field + warning) and is persisted
    to PROBE_REPORT_PATH for the multichip dryrun to pick up.

    Retries failed (errored) probes with backoff across the probe budget
    (round-1 failure: ONE transient init error killed the bench). A HUNG
    probe is ABANDONED after an explicit per-attempt timeout
    (BENCH_PROBE_ATTEMPT_S, default half the budget so one hang leaves
    room for exactly one retry) — never killed: killing a process
    mid-lease-acquisition is what wedged the round-2 tunnel. stderr goes
    to a temp FILE, not a pipe, so a wedged tunnel's helper child can't
    block us by inheriting the pipe fd.
    """
    import subprocess
    import tempfile

    report = {"status": "skipped", "attempts": []}
    # probe budget sized so a DEAD tunnel (two hung attempts consume the
    # whole budget) still leaves room for all nine cpu-fallback configs:
    # observed init latencies are ~30s when the tunnel is healthy, and
    # fail-fast errors retry with backoff well inside 360s
    budget = float(os.environ.get(
        "BENCH_INIT_PROBE_S", min(360.0, TIME_BUDGET_S * 0.25)))
    if budget <= 0:
        return True, report
    budget = min(budget, max(_remaining() - 120, 30))
    # per-attempt cap: budget/2 means a hung first attempt still leaves
    # budget for ONE retry (a transiently wedged tunnel often recovers)
    attempt_s = float(os.environ.get("BENCH_PROBE_ATTEMPT_S", budget / 2))
    deadline = time.monotonic() + budget
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    report["env"] = {"JAX_PLATFORMS": env.get("JAX_PLATFORMS"),
                     "PJRT_DEVICE": env.get("PJRT_DEVICE")}
    attempt = 0
    hung_attempts = 0
    while time.monotonic() < deadline:
        attempt += 1
        attempt_deadline = min(deadline, time.monotonic() + attempt_s)
        with tempfile.TemporaryFile() as ef, tempfile.TemporaryFile() as of:
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; jax.numpy.zeros(8).block_until_ready(); "
                 "print(jax.devices())"],
                stdout=of, stderr=ef, env=env,
                start_new_session=True)
            while time.monotonic() < attempt_deadline and proc.poll() is None:
                time.sleep(1.0)
            rc = proc.poll()
            if rc == 0:
                of.seek(0)
                devices = of.read()[-2000:].decode(
                    errors="replace").strip()
                report["devices"] = devices
                if "CpuDevice" in devices and "TpuDevice" not in devices:
                    # jax quietly fell back to CPU inside the probe
                    # (r06 false positive: rc=0, devices=[CpuDevice(id=0)]
                    # → the bench ran 100M rows with every child burning
                    # its budget on doomed libtpu init retries). A
                    # CPU-only device list is a FAILED accelerator probe.
                    print(f"[bench] probe attempt {attempt} came back "
                          f"CPU-only ({devices}); no accelerator",
                          file=sys.stderr)
                    report["status"] = "cpu_only"
                    report["attempts"].append(
                        {"rc": 0, "stderr_tail": f"cpu-only: {devices}"})
                    return False, report
                report["status"] = "ok"
                return True, report
            if rc is None:  # hung: abandon (no kill — lease-wedge hazard)
                hung_attempts += 1
                print(f"[bench] probe attempt {attempt} still hung after "
                      f"{attempt_s:.0f}s per-attempt timeout; abandoning it",
                      file=sys.stderr)
                report["status"] = "hung"
                report["attempts"].append(
                    {"rc": None, "stderr_tail":
                     f"hung past the {attempt_s:.0f}s per-attempt timeout; "
                     f"abandoned"})
                if hung_attempts >= 2:  # one retry after a hang, then give up
                    return False, report
                continue
            ef.seek(0)
            full = ef.read().decode(errors="replace").strip()
            tail = full[-2000:]
            print(f"[bench] probe attempt {attempt} failed (rc={rc}):\n{tail}",
                  file=sys.stderr)
            report["status"] = "errored"
            report["attempts"].append({"rc": rc, "stderr_tail": tail[-500:],
                                       "stderr": full})
        time.sleep(min(5 * 2 ** (attempt - 1), 60))
    return False, report


# the last probe's verdict, readable by the multichip dryrun
# (__graft_entry__.dryrun_multichip) so round reports can tell a missing
# accelerator from broken accelerator code
PROBE_REPORT_PATH = Path(os.environ.get(
    "BENCH_PROBE_REPORT", ROOT / ".bench_partial" / "probe_report.json"))


def _persist_probe_report(report) -> None:
    try:
        PROBE_REPORT_PATH.parent.mkdir(exist_ok=True)
        PROBE_REPORT_PATH.write_text(json.dumps(report))
    except Exception:
        pass


def _record_dir(platform) -> Path:
    """Where a run's artifacts belong: accelerator runs own the committed
    record dir; a cpu run lands in a sibling so it can never overwrite the
    record of the last REAL accelerator run (rounds 1-2 lost their only
    TPU evidence exactly this way)."""
    if platform != "cpu":
        return PARTIAL
    try:
        prev = json.loads((PARTIAL / "summary.json").read_text())
        if prev.get("platform") not in ("cpu", None):
            return PARTIAL.parent / (PARTIAL.name + "_cpu")
    except Exception:
        pass
    return PARTIAL


def _runner_shape(results=None) -> dict:
    """Self-describing runner-shape block for the round payload: physical
    and logical core counts plus the mesh device count any config actually
    ran with. Rounds recorded on differently-shaped machines are not
    timing-comparable (the r05→r06 q5/q7 'regressions' tracked a core-count
    change, not the code) — bench_gate downgrades same-platform timing
    FAILs to WARNs when these blocks differ."""
    logical = os.cpu_count() or 1
    physical = None
    try:
        pairs = set()
        for block in Path("/proc/cpuinfo").read_text().split("\n\n"):
            phys = core = None
            for line in block.splitlines():
                if line.startswith("physical id"):
                    phys = line.split(":", 1)[1].strip()
                elif line.startswith("core id"):
                    core = line.split(":", 1)[1].strip()
            if phys is not None and core is not None:
                pairs.add((phys, core))
        physical = len(pairs) or None
    except OSError:
        pass
    shape = {"logicalCores": logical, "physicalCores": physical or logical}
    mesh = None
    for v in (results or {}).values():
        if isinstance(v, dict) and v.get("mesh_devices"):
            mesh = v["mesh_devices"]
    if mesh:
        shape["meshDevices"] = mesh
    return shape


def _emit(results, platform, notes, skipped, final=False, statuses=None,
          probe=None):
    """(Re-)print the one-line summary JSON; also persist to the record
    dir (_record_dir). ALWAYS emits — a probe or per-config failure must
    never leave the driver with rc!=0 and no JSON line (the BENCH_r01
    failure shape): with zero completed configs the line carries value 0,
    the per-config statuses, and the probe attempts instead of vanishing."""
    if "q2_groupby" in results:
        hname = "q2_groupby"
        # row count rides in the name so scaled (cpu-fallback) runs
        # never masquerade as the 100M-row series
        metric = f"ssb_{ROWS // 1_000_000}m_q2_filter_groupby_rows_per_sec_per_chip"
    elif results:
        hname = next(iter(results))
        metric = f"{hname}_rows_per_sec_per_chip"
    else:
        hname = None
        metric = f"ssb_{ROWS // 1_000_000}m_q2_filter_groupby_rows_per_sec_per_chip"
    headline = results.get(hname) if hname else None
    speedup = headline.get("speedup") if headline else None
    out = {
        "metric": metric,
        "value": round(headline["rows_per_sec"]) if headline else 0,
        "unit": "rows/s",
        # null (not 0) when the baseline was skipped — 0 would read as a
        # measured 0x speedup
        "vs_baseline": round(speedup, 2) if speedup is not None else None,
        "detail": {k: {kk: (round(vv, 6) if isinstance(vv, float) else vv)
                       for kk, vv in v.items()} for k, v in results.items()},
        "rows": ROWS,
        "host_threads": os.cpu_count() or 1,
        # this machine exposes ONE core to Python (os.cpu_count()=1), so
        # the numpy host engine baseline is inherently single-threaded
        # here — compare rows/s + roofline fractions, not just speedup
        "host_baseline": f"numpy engine, {os.cpu_count() or 1} core(s)",
        "platform": platform,
        "runner": _runner_shape(results),
        "final": final,
    }
    if not results:
        out["error"] = "no benchmark config completed"
    if notes:
        out["warning"] = "; ".join(notes)
    if skipped:
        out["skipped_configs"] = skipped
    if statuses:
        # one status per requested config: ok / hung / skipped:<why> /
        # failed:rc=<n> — the per-config audit trail for partial runs
        out["configs"] = statuses
    if probe and probe.get("status") not in (None, "skipped"):
        out["probe"] = probe
    line = json.dumps(out)
    print(line, flush=True)
    try:
        target = _record_dir(platform)
        target.mkdir(exist_ok=True)
        (target / "summary.json").write_text(line)
    except Exception:
        pass


def orchestrate():
    global ROWS, PARTIAL
    import subprocess

    # the parent must NEVER initialize the accelerator backend (it would
    # hold the single axon lease and starve the children) — pin it to CPU
    # before any pinot_tpu import can pull jax in.
    os.environ["JAX_PLATFORMS"] = "cpu"

    platform_req = os.environ.get("BENCH_PLATFORM", "")
    notes = []
    probe_report = {"status": "skipped", "attempts": []}
    if not platform_req:
        probe_ok, probe_report = _probe_accelerator()
        if probe_report.get("status") != "skipped":
            # classify the probe outcome with the doctor's taxonomy so the
            # bench JSON says WHY the accelerator was unusable (satellite:
            # tools/doctor.py --classify-report shares this code path)
            try:
                from pinot_tpu.tools.doctor import classify_report

                cls = classify_report(probe_report)
                probe_report["classification"] = cls.get("classification")
                probe_report["remedy"] = cls.get("remedy")
            except Exception:
                pass  # classification is advisory; never block the bench
        _persist_probe_report(probe_report)
        if probe_ok:
            platform_req = ""  # default backend (axon/TPU)
        else:
            print("[bench] accelerator probe failed/hung; forcing CPU",
                  file=sys.stderr)
            # say WHICH failure mode: a hung probe means no accelerator
            # was reachable; an errored probe carries the last stderr tail
            # (our code / toolchain broke on the device)
            why = probe_report.get("status", "failed")
            last = (probe_report.get("attempts") or [{}])[-1]
            tail = (last.get("stderr_tail") or "").splitlines()
            notes.append(
                f"accelerator probe {why}"
                + (f" (last stderr: {tail[-1][:200]})" if tail else "")
                + ", ran on cpu")
            platform_req = "cpu"
    if platform_req == "cpu" and ROWS > 20_000_000 \
            and not os.environ.get("BENCH_ROWS"):
        # fallback CPU run: 100M rows would blow every per-config budget
        # (rounds 1-2 died exactly here, rc=124). 20M keeps the artifact
        # meaningful (platform/rows are recorded) and finishable.
        ROWS = 20_000_000
        os.environ["BENCH_ROWS"] = str(ROWS)
        notes.append("cpu fallback: rows scaled to 20M")
        print("[bench] cpu fallback: ROWS -> 20M", file=sys.stderr)

    need_ssb = any(RUNS[c][2] == "ssb" for c in CONFIGS if c in RUNS)
    need_ssb16 = any(RUNS[c][2] == "ssb16" for c in CONFIGS if c in RUNS)
    prepare_tables(need_ssb, need_ssb16, "q5" in CONFIGS)

    PARTIAL.mkdir(exist_ok=True)
    stage = PARTIAL.parent / (PARTIAL.name + "_stage")
    stage.mkdir(exist_ok=True)
    results, skipped = {}, []
    statuses: dict = {}
    platform_seen = None
    configs = [c for c in CONFIGS if c in RUNS]
    hung = False
    for i, cfg in enumerate(configs):
        name = RUNS[cfg][0]
        rem = _remaining()
        if hung or rem < 60:
            skipped.append(name)
            statuses[cfg] = ("skipped:previous config hung" if hung
                             else "skipped:time budget exhausted")
            print(f"[bench] SKIP {name}: "
                  + ("previous config hung" if hung else "time budget exhausted"),
                  file=sys.stderr)
            continue
        # fair share of the remaining budget, floor 120s (if we have it)
        share = max(min(120.0, rem - 30), rem / (len(configs) - i))
        outfile = stage / f"{cfg}.json"
        outfile.unlink(missing_ok=True)
        env = dict(os.environ)
        env["BENCH_DEADLINE_S"] = str(share)
        if platform_req:
            env["BENCH_PLATFORM"] = platform_req
            env["JAX_PLATFORMS"] = platform_req
        else:
            env.pop("BENCH_PLATFORM", None)
            env.pop("JAX_PLATFORMS", None)
        if platform_req == "cpu":
            # a CPU child can still exercise the mesh-sharded dispatch path
            # by splitting the host platform into N virtual devices — the
            # mesh round then measures real cross-chip-combine mechanics
            try:
                mesh_n = int(os.environ.get("BENCH_MESH_DEVICES", "8"))
            except ValueError:
                mesh_n = 8
            flag = f"--xla_force_host_platform_device_count={mesh_n}"
            xla = env.get("XLA_FLAGS", "")
            if mesh_n > 1 and "xla_force_host_platform_device_count" not in xla:
                env["XLA_FLAGS"] = (xla + " " + flag).strip()
        print(f"[bench] -> {cfg} (budget {share:.0f}s)", file=sys.stderr,
              flush=True)
        proc = subprocess.Popen(
            [sys.executable, __file__, "--config", cfg, "--out", str(outfile)],
            stdout=sys.stderr, stderr=sys.stderr, env=env,
            start_new_session=True)
        grace = share + 240  # child self-limits; grace covers init+build+host
        t0 = time.monotonic()
        while proc.poll() is None and time.monotonic() - t0 < grace \
                and _remaining() > 20:
            time.sleep(2.0)
        if proc.poll() is None:
            # abandon, never kill (axon lease-wedge hazard); skip the rest
            print(f"[bench] {cfg} unresponsive after {grace:.0f}s; abandoning",
                  file=sys.stderr)
            notes.append(f"{cfg} hung and was abandoned")
            hung = True
            statuses[cfg] = "hung"
            skipped.append(name)
            continue
        if outfile.exists():
            try:
                payload = json.loads(outfile.read_text())
                # a child may fall back to cpu mid-run even when the probe
                # succeeded — place each config's record by the platform
                # the child ACTUALLY ran on
                rec = _record_dir(payload.get("platform"))
                rec.mkdir(exist_ok=True)
                (rec / f"{cfg}.json").write_text(outfile.read_text())
                platform_seen = payload.pop("platform", platform_seen)
                note = payload.pop("note", None)
                if note:
                    notes.append(note)
                results[name] = payload
                statuses[cfg] = "ok"
            except Exception as e:
                notes.append(f"{cfg} result unreadable: {e}")
                statuses[cfg] = f"failed:unreadable result ({e})"
                skipped.append(name)
        else:
            notes.append(f"{cfg} child exited rc={proc.returncode} "
                         f"with no result")
            statuses[cfg] = f"failed:rc={proc.returncode}"
            skipped.append(name)
        _emit(results, platform_seen or platform_req or "unknown", notes,
              skipped, statuses=statuses, probe=probe_report)

    # always emit the final line — even a fully-failed run must leave the
    # driver one parseable JSON record of WHAT failed and on which platform
    _emit(results, platform_seen or platform_req or "unknown", notes, skipped,
          final=True, statuses=statuses, probe=probe_report)
    return len(results)


# --------------------------------------------------------------------------
# child: run exactly one config, bounded by an internal deadline
# --------------------------------------------------------------------------

def _set_compile_cache(jax, platform: str) -> None:
    """Persist compiles across bench runs (no-op for remote compile).

    NOT shared with the test suite's cache: pytest compiles under
    different XLA flags and the AOT loader warns cross-loading could
    SIGILL on mismatched machine-feature sets. Keyed per RESOLVED platform
    for the same reason: CPU AOT entries are machine-feature-sensitive
    while TPU entries are not — a cpu-fallback run must never write into
    (or load from) the TPU-keyed cache."""
    try:
        jax.config.update("jax_compilation_cache_dir",
                          str(ROOT / f".jax_cache_bench_{platform}"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass


def _init_backend():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])
    last_err = None
    for attempt in range(3):
        if attempt:
            time.sleep(min(5 * 2 ** (attempt - 1), 20))
        try:
            devs = jax.devices()
            print(f"[bench] devices: {devs}", file=sys.stderr)
            _set_compile_cache(jax, devs[0].platform)
            return jax, devs[0].platform, None
        except Exception as e:
            last_err = e
            print(f"[bench] backend init attempt {attempt + 1} failed: {e}",
                  file=sys.stderr)
            try:
                from jax.extend import backend as jex_backend
                jex_backend.clear_backends()
            except Exception:
                pass
    jax.config.update("jax_platforms", "cpu")
    try:
        from jax.extend import backend as jex_backend
        jex_backend.clear_backends()
    except Exception:
        pass
    _set_compile_cache(jax, "cpu")
    return jax, "cpu", f"accelerator init failed, ran on cpu: {last_err}"


def _plan_bytes(qe, sql, segments):
    """Column-plane bytes one execution must read (device roofline input)."""
    from pinot_tpu.query.parser.sql import parse_sql

    try:
        query = parse_sql(sql)
        total = 0
        for seg in segments:
            plan = qe.tpu.plan(query, seg)
            view = qe.tpu.cache.view(seg)
            arrays, _ = plan.gather_arrays_packed(view)
            total += sum(int(np.asarray(a).nbytes) if not hasattr(a, "nbytes")
                         else int(a.nbytes) for a in arrays)
        return total
    except Exception:
        return None


def _rows_match(a, b, rel_tol=0.0) -> bool:
    if len(a) != len(b):
        return False
    if rel_tol == 0.0:
        return sorted(map(repr, a)) == sorted(map(repr, b))

    def key(row):
        return tuple(x for x in row if not isinstance(x, float))

    bm = {key(r): r for r in b}
    for r in a:
        other = bm.get(key(r))
        if other is None:
            return False
        for x, y in zip(r, other):
            if isinstance(x, float) and isinstance(y, float):
                if abs(x - y) > rel_tol * max(1.0, abs(x), abs(y)):
                    return False
    return True


def _plan_first_segment(qe, sql, segs):
    """(executor, seg0, compiled plan) for the single-stage device path,
    or None when the shape doesn't ride it (e.g. the MSE join config)."""
    from pinot_tpu.query.parser.sql import parse_sql

    try:
        query = parse_sql(sql)
        ex = qe.tpu
        seg = segs[0]
        return ex, seg, ex.plan(query, seg)
    except Exception:
        return None


def _kernel_time_est(planned, deadline, iters: int = 5):
    """Pure device-kernel seconds for one segment's program: median of
    (dispatch TWO kernels + one fetch) minus (ONE kernel + one fetch).
    The device executes in order, so the last output materializes after
    both kernels; the delta is the second kernel's compute with every
    fixed tunnel/dispatch cost cancelled. Residual bias: the second
    dispatch's HOST-side work (~1ms of plan/pack per dispatch) overlaps
    kernel #1 only partially, so for sub-millisecond kernels kernel_s is
    an UPPER bound on device compute, not an exact reading. Deadline-aware
    (measurement is OPTIONAL — it must never eat the host baseline's
    budget); returns None without at least 2+2 clean rounds or a positive
    delta."""
    if planned is None:
        return None
    ex, seg, plan = planned

    def run(k):
        t0 = time.perf_counter()
        outs = None
        for _ in range(k):
            outs = ex.dispatch_plan(seg, plan)
        if hasattr(outs, "flat"):
            np.asarray(outs.flat)
        else:
            for o in outs:
                np.asarray(o)
        return time.perf_counter() - t0

    singles, doubles = [], []
    try:
        run(1)  # warm
        for _ in range(iters):
            if time.monotonic() > deadline:
                break
            singles.append(run(1))
        for _ in range(iters):
            if time.monotonic() > deadline:
                break
            doubles.append(run(2))
    except Exception:
        return None
    if len(singles) < 2 or len(doubles) < 2:
        return None
    delta = float(np.median(doubles) - np.median(singles))
    # a non-positive delta is measurement noise — suppress rather than
    # emit absurd derived rates
    return delta if delta > 0 else None


def _measure_rtt(jax) -> float:
    """Median blocking round trip for a trivial fetch — the tunnel's fixed
    per-query latency floor, reported so kernel time can be read out of
    end-to-end p50 (on a directly-attached TPU this is ~0)."""
    import jax.numpy as jnp

    f = jax.jit(lambda s: s + 1)
    ts = []
    for s in range(4):
        t0 = time.perf_counter()
        np.asarray(f(jnp.int32(s)))
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts[1:]))


def _run_realtime_single(outpath: str):
    """q11r: a CONSUMING (mutable) segment executed on the realtime device
    planes. Beyond the usual cold/warm p50s the payload records the
    delta-upload economics the bench gate pins:

      rt_full_bytes  — bytes uploaded by the FIRST query (cold: the whole
                       snapshot crosses to the device),
      rt_delta_bytes — bytes uploaded by the first query AFTER appending
                       ~1% more rows (only the new tail may cross;
                       rt_delta_bytes >= rt_full_bytes means the
                       incremental path is gone),
      rt_warm_bytes  — bytes uploaded by a repeat on an unchanged
                       generation (must stay 0: plane-resident fast path).

    The row count is deliberately modest (BENCH_RT_ROWS, default 200k):
    MutableSegment.index() is per-row host-side work, and the quantity
    under test is upload BYTES, which scale linearly anyway.
    """
    name = RUNS["q11r"][0]
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE_S", 600))
    jax, platform, note = _init_backend()
    from pinot_tpu.engine.query_executor import QueryExecutor
    from pinot_tpu.ingestion.transform import build_transform_pipeline
    from pinot_tpu.realtime.device_plane import (realtime_stats,
                                                 reset_realtime_stats)
    from pinot_tpu.segment.mutable import MutableSegment
    from pinot_tpu.spi.data_types import Schema

    n = int(os.environ.get("BENCH_RT_ROWS", 200_000))
    delta_n = max(256, n // 100)
    total = n + delta_n
    schema = Schema.build(
        "rt",
        dimensions=[("site", "STRING"), ("code", "INT")],
        metrics=[("clicks", "INT"), ("revenue", "LONG")])
    rng = np.random.default_rng(7)
    sites = [f"site{i:02d}" for i in range(64)]
    site_idx = rng.integers(0, 64, total)
    code = rng.integers(0, 1000, total)
    clicks = rng.integers(0, 100, total)
    revenue = rng.integers(0, 10_000, total)
    seg = MutableSegment(schema, "rt_live_0")
    pipe = build_transform_pipeline(schema)

    def feed(lo: int, hi: int):
        for i in range(lo, hi):
            seg.index(pipe.transform({
                "site": sites[site_idx[i]], "code": int(code[i]),
                "clicks": int(clicks[i]), "revenue": int(revenue[i])}))

    feed(0, n)
    tpu = QueryExecutor(backend="tpu")
    host = QueryExecutor(backend="host")
    for qe in (tpu, host):
        qe.add_table(schema, [seg], name="rt")
    sql = RUNS["q11r"][1]
    # caches off so every timed iteration exercises the device execution
    # path; the planes themselves are NOT a cache tier — they persist
    # across iterations, so only the first run uploads
    nocache = "SET segmentCache = false; SET resultCache = false; " + sql

    reset_realtime_stats()
    r = tpu.execute_sql(nocache)  # cold: full snapshot upload + compile
    if r.exceptions:
        raise RuntimeError(f"{nocache}: {r.exceptions}")
    rt_full_bytes = int(realtime_stats()["deltaBytes"])

    # steady-state loop: generation unchanged → plane-resident, 0 uploads
    target_iters = max(3, round(ITERS / 3))
    times = []
    while len(times) < target_iters and (
            not times or time.monotonic() + min(times) < deadline):
        t0 = time.perf_counter()
        r = tpu.execute_sql(nocache)
        times.append(time.perf_counter() - t0)
    if r.exceptions:
        raise RuntimeError(f"{nocache}: {r.exceptions}")
    p50 = float(np.median(times))

    # warm repeat with caching at defaults on the SAME generation: the
    # partial tiers serve it and the planes must upload nothing
    warm_p50 = warm_match = None
    rt_warm_bytes = None
    try:
        rw = tpu.execute_sql(sql)  # populate
        reset_realtime_stats()
        warm_times = []
        while len(warm_times) < min(target_iters, 5) and (
                not warm_times
                or time.monotonic() + min(warm_times) < deadline):
            t0 = time.perf_counter()
            rw = tpu.execute_sql(sql)
            warm_times.append(time.perf_counter() - t0)
        if not rw.exceptions:
            warm_p50 = float(np.median(warm_times))
            warm_match = _rows_match(r.result_table.rows,
                                     rw.result_table.rows, 0.0)
            rt_warm_bytes = int(realtime_stats()["deltaBytes"])
    except Exception:
        pass  # warm numbers are additive; never fail the config

    # ingest ~1% more rows, query again with caches off: only the new
    # tail should cross (delta upload, generation bump)
    feed(n, total)
    reset_realtime_stats()
    t0 = time.perf_counter()
    rd = tpu.execute_sql(nocache)
    delta_query_s = time.perf_counter() - t0
    if rd.exceptions:
        raise RuntimeError(f"post-delta {nocache}: {rd.exceptions}")
    rt_delta_bytes = int(realtime_stats()["deltaBytes"])

    # host baseline at the SAME generation: live-ingest bit-identity
    rh = host.execute_sql(sql)
    if rh.exceptions:
        raise RuntimeError(f"host {sql}: {rh.exceptions}")
    match = _rows_match(rd.result_table.rows, rh.result_table.rows, 0.0)

    payload = {
        "tpu_p50_s": p50,
        "rows_per_sec": n / p50,
        "cold_p50_s": p50,
        "warm_p50_s": warm_p50,
        "warm_speedup": (p50 / warm_p50) if warm_p50 else None,
        "warm_match": warm_match,
        "match": match,
        "iters": len(times),
        "platform": platform,
        "num_device_dispatches": getattr(rd, "num_device_dispatches", 0),
        "num_compiles": getattr(rd, "num_compiles", 0),
        "rt_rows": n,
        "rt_delta_rows": delta_n,
        "rt_full_bytes": rt_full_bytes,
        "rt_delta_bytes": rt_delta_bytes,
        "rt_warm_bytes": rt_warm_bytes,
        "rt_delta_query_s": delta_query_s,
    }
    if note:
        payload["note"] = note
    print(f"[bench] {name}: p50 {p50*1000:.1f}ms, full upload "
          f"{rt_full_bytes}B, +{delta_n} rows → delta {rt_delta_bytes}B, "
          f"warm {rt_warm_bytes}B, match={match}, warm_match={warm_match}",
          file=sys.stderr)
    tmp = Path(outpath + ".tmp")
    tmp.write_text(json.dumps(payload))
    tmp.replace(outpath)


def run_single(cfg: str, outpath: str):
    if cfg == "q11r":
        return _run_realtime_single(outpath)
    name, sql, tname, iter_frac, tol = RUNS[cfg]
    deadline = time.monotonic() + float(os.environ.get("BENCH_DEADLINE_S", 600))
    jax, platform, note = _init_backend()
    from pinot_tpu.engine.query_executor import QueryExecutor
    from pinot_tpu.segment.loader import load_segment

    tables = prepare_tables(tname in ("ssb",), tname == "ssb16",
                            tname == "taxi")
    schema, dirs = tables[tname]
    segs = [load_segment(d) for d in dirs]
    ncpu = os.cpu_count() or 1
    tpu = QueryExecutor(backend="tpu")
    host = QueryExecutor(backend="host", num_threads=ncpu)
    for qe in (tpu, host):
        qe.add_table(schema, segs)
    if cfg == "q7":
        _register_brands_dim()

    target_iters = max(3, round(ITERS * iter_frac)) if iter_frac < 1 else ITERS

    r = tpu.execute_sql(sql)  # warmup / compile / HBM residency
    if r.exceptions:
        raise RuntimeError(f"{sql}: {r.exceptions}")
    # COLD loop: segment-cache off, so tpu_p50_s keeps measuring the
    # device execution path across rounds (cache/partial.py would
    # otherwise zero it from the second iteration on). Shapes whose engine
    # rejects the SET (e.g. the MSE join) time the plain SQL instead.
    # resultCache also off: the MSE stage-plan cache would serve every
    # iteration after the first and zero out the cold p50
    cold_sql = "SET segmentCache = false; SET resultCache = false; " + sql
    # MESH mode: with >1 local device the engine shards batch families by
    # default, so the solo baseline must force meshExecution=false to keep
    # tpu_p50_s comparable across rounds; the mesh-on variant is timed in
    # its own loop below and emitted as mesh_p50_s / mesh_speedup.
    try:
        mesh_ndev = len(jax.devices())
    except Exception:
        mesh_ndev = 1
    mesh_sql = None
    if mesh_ndev > 1:
        mesh_sql = cold_sql
        cold_sql = "SET meshExecution = false; " + cold_sql
    probe = tpu.execute_sql(cold_sql)
    if probe.exceptions:
        cold_sql = sql
        mesh_sql = None
    times = []
    while len(times) < target_iters and (
            not times or time.monotonic() + min(times) < deadline):
        t0 = time.perf_counter()
        r = tpu.execute_sql(cold_sql)
        times.append(time.perf_counter() - t0)
    if r.exceptions:
        raise RuntimeError(f"{cold_sql}: {r.exceptions}")
    p50 = float(np.median(times))

    # mesh-on loop: same cold semantics (segmentCache=false), sharded
    # dispatch across all local devices; match is bit-identity (tol 0.0)
    mesh_p50 = mesh_match = None
    if mesh_sql is not None:
        try:
            rm = tpu.execute_sql(mesh_sql)
            if not rm.exceptions:
                mesh_times = []
                while len(mesh_times) < min(target_iters, 5) and (
                        not mesh_times
                        or time.monotonic() + min(mesh_times) < deadline):
                    t0 = time.perf_counter()
                    rm = tpu.execute_sql(mesh_sql)
                    mesh_times.append(time.perf_counter() - t0)
                if not rm.exceptions and mesh_times:
                    mesh_p50 = float(np.median(mesh_times))
                    mesh_match = _rows_match(r.result_table.rows,
                                             rm.result_table.rows, 0.0)
        except Exception:
            mesh_p50 = None  # mesh numbers are additive; never fail

    # WARM repeat loop: default caching on — the first run populates the
    # partial tiers, the timed repeats should hit with zero dispatches.
    warm_p50 = warm_match = None
    rw = None
    try:
        rw = tpu.execute_sql(sql)  # populate
        warm_times = []
        while len(warm_times) < min(target_iters, 5) and (
                not warm_times
                or time.monotonic() + min(warm_times) < deadline):
            t0 = time.perf_counter()
            rw = tpu.execute_sql(sql)
            warm_times.append(time.perf_counter() - t0)
        if rw.exceptions:
            rw = None
        else:
            warm_p50 = float(np.median(warm_times))
            warm_match = _rows_match(r.result_table.rows,
                                     rw.result_table.rows, tol)
    except Exception:
        rw = None  # warm numbers are additive; never fail the config
    rtt = _measure_rtt(jax) if platform != "cpu" else 0.0

    # one traced run OUTSIDE the timed loop (tracing blocks on every
    # family dispatch to split compile vs device-execute, so it must not
    # pollute p50): per-phase attribution for the BENCH json
    phases = None
    try:
        rt = tpu.execute_sql("SET trace = true; " + sql)
        if not rt.exceptions and rt.trace_info:
            from pinot_tpu.spi.trace import phase_breakdown

            phases = phase_breakdown(rt.trace_info)
    except Exception:
        pass  # tracing is diagnostics; never fail the bench numbers

    # host baseline: the FIRST run is bounded by the remaining deadline —
    # an unbounded host run on a slow/fallback platform would blow the
    # child's share and make the parent abandon every later config (the
    # round-2 rc=124 death spiral). On timeout the TPU numbers still land,
    # with match=None + a note instead of a hung child.
    host_holder: dict = {}

    def _host_once():
        t0 = time.perf_counter()
        try:
            resp = host.execute_sql(sql)
        except BaseException as e:  # noqa: BLE001 — surfaced to the child
            host_holder["result"] = ("exc", e, None)
            return
        host_holder["result"] = ("ok", resp, time.perf_counter() - t0)

    import threading

    th = threading.Thread(target=_host_once, daemon=True)
    th.start()
    th.join(timeout=max(5.0, deadline - time.monotonic()))
    status, rh, host_first_s = host_holder.get("result") or ("timeout",) * 3
    if status == "exc":
        raise rh  # a real host-engine failure must fail the config loudly
    host_p50 = match = None
    if status == "ok":
        if rh.exceptions:
            raise RuntimeError(f"host {sql}: {rh.exceptions}")
        host_times = [host_first_s]
        while len(host_times) < 2 and \
                time.monotonic() + host_times[0] < deadline:
            t0 = time.perf_counter()
            rh = host.execute_sql(sql)
            host_times.append(time.perf_counter() - t0)
        host_p50 = float(np.median(host_times))
        match = _rows_match(r.result_table.rows, rh.result_table.rows, tol)
    else:
        note = "; ".join(filter(None, [
            note, f"{name}: host baseline exceeded deadline, skipped"]))

    # kernel-only measurement LAST: optional, never at the expense of the
    # host-verified numbers above
    kernel_s = None
    if platform != "cpu":
        kernel_s = _kernel_time_est(
            _plan_first_segment(tpu, sql, segs), deadline)

    nbytes = _plan_bytes(tpu, sql, segs)
    # device-side time estimate: end-to-end p50 minus the tunnel's fixed
    # round trip (the fetch RPC). On a directly-attached TPU rtt≈0 and
    # device_est == p50.
    device_est = max(0.0, p50 - rtt)
    payload = {
        "tpu_p50_s": p50,
        "rows_per_sec": ROWS / p50,
        "tunnel_rtt_s": rtt,
        "device_est_s": device_est,
        "device_rows_per_sec": ROWS / max(device_est, 1e-9),
        "host_parallel_s": host_p50,
        "speedup": host_p50 / p50 if host_p50 is not None else None,
        "match": match,
        "iters": len(times),
        "platform": platform,
        # device-dispatch economics of the LAST timed run: dispatches
        # should track batch families (not segments) and steady-state
        # compiles should be 0
        "num_device_dispatches": getattr(r, "num_device_dispatches", 0),
        "num_compiles": getattr(r, "num_compiles", 0),
        # warm repeat-run series (cache/ tiers at their defaults): the cold
        # number above is measured with SET segmentCache=false so the two
        # are directly comparable on one engine instance
        "cold_p50_s": p50,
        "warm_p50_s": warm_p50,
        "warm_speedup": (p50 / warm_p50) if warm_p50 else None,
        "warm_match": warm_match,
    }
    if rw is not None:
        payload["warm_cache_hits"] = getattr(rw, "num_segments_cache_hit", 0)
        payload["warm_cache_misses"] = getattr(
            rw, "num_segments_cache_miss", 0)
        payload["warm_num_device_dispatches"] = getattr(
            rw, "num_device_dispatches", 0)
    if mesh_p50 is not None:
        # sharded-dispatch round: solo-vs-mesh on the same engine instance,
        # bit-identity required (mesh_match uses tol 0.0)
        payload["mesh_devices"] = mesh_ndev
        payload["mesh_p50_s"] = mesh_p50
        payload["mesh_match"] = mesh_match
        payload["mesh_speedup"] = p50 / mesh_p50 if mesh_p50 else None
    if note:
        payload["note"] = note
    if phases is not None:
        # compileMs/deviceExecMs/transferBytes sum the family_dispatch
        # span attributes; hostCombineMs sums the SERVER_COMBINE +
        # BROKER_REDUCE spans (see pinot_tpu/spi/trace.py:phase_breakdown)
        payload["phases"] = phases
    stage_stats = getattr(r, "mse_stage_stats", None)
    if stage_stats:
        # per-stage attribution (rows in/out, shuffled bytes, wall) from
        # the LAST timed tpu run — lets bench rounds split MSE time into
        # shuffle vs join vs agg
        payload["mse_stage_stats"] = {str(k): v
                                      for k, v in stage_stats.items()}
        # bytes that actually crossed a stage boundary (device handoffs
        # count 0); the bench gate fails MSE configs that regress this
        payload["shuffled_bytes"] = sum(
            st.get("cross_stage_bytes", st.get("shuffled_bytes", 0))
            for st in stage_stats.values())
        # device→host round-trips taken by fused stages (1 per fused plan;
        # a regression here means a plan fell back to per-operator hops)
        payload["host_crossings"] = sum(
            int(st.get("host_crossings", 0) or 0)
            for st in stage_stats.values())
    if kernel_s is not None:
        # measured pure-kernel time for ONE segment's program (all fixed
        # dispatch/tunnel costs cancelled); per-segment bytes give the
        # kernel's true roofline fraction
        payload["kernel_s"] = kernel_s
        payload["kernel_rows_per_sec"] = \
            (ROWS / len(segs)) / max(kernel_s, 1e-9)
    if nbytes:
        payload["hbm_bytes"] = nbytes
        payload["hbm_bytes_per_sec"] = nbytes / p50
        payload["hbm_peak_frac"] = (nbytes / p50) / V5E_HBM_PEAK
        payload["device_hbm_bytes_per_sec"] = nbytes / max(device_est, 1e-9)
        payload["device_hbm_peak_frac"] = \
            (nbytes / max(device_est, 1e-9)) / V5E_HBM_PEAK
        if kernel_s is not None:
            payload["kernel_hbm_peak_frac"] = \
                ((nbytes / len(segs)) / max(kernel_s, 1e-9)) / V5E_HBM_PEAK
    host_part = (f"host({ncpu}thr) {host_p50*1000:.0f}ms, "
                 f"speedup {host_p50/p50:.1f}x"
                 if host_p50 is not None else "host skipped (deadline)")
    warm_part = (f"warm {warm_p50*1000:.1f}ms ({p50/warm_p50:.1f}x, "
                 f"match={warm_match})" if warm_p50 else "warm skipped")
    mesh_part = (f"mesh[{mesh_ndev}] {mesh_p50*1000:.1f}ms "
                 f"({p50/mesh_p50:.2f}x, match={mesh_match}), "
                 if mesh_p50 else "")
    print(f"[bench] {name}: p50 {p50*1000:.1f}ms "
          f"({ROWS/p50/1e9:.2f}B rows/s; device-est {device_est*1000:.0f}ms "
          f"after {rtt*1000:.0f}ms tunnel rtt), {mesh_part}{warm_part}, "
          f"{host_part}, match={match}"
          + (f", {nbytes/p50/1e9:.0f} GB/s "
             f"({100*(nbytes/p50)/V5E_HBM_PEAK:.0f}% v5e peak; device-est "
             f"{100*(nbytes/max(device_est,1e-9))/V5E_HBM_PEAK:.0f}%)"
             if nbytes else ""),
          file=sys.stderr)
    tmp = Path(outpath + ".tmp")
    tmp.write_text(json.dumps(payload))
    tmp.replace(outpath)


def main():
    if "--config" in sys.argv:
        cfg = sys.argv[sys.argv.index("--config") + 1]
        outpath = sys.argv[sys.argv.index("--out") + 1]
        run_single(cfg, outpath)
        return
    completed = orchestrate()
    # exit 0 when at least one config completed; a zero-config run still
    # emitted its JSON (with per-config statuses) before this nonzero exit
    sys.exit(0 if completed else 1)


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # still emit ONE parseable JSON line for the driver
        import traceback

        traceback.print_exc()
        if "--config" not in sys.argv:
            print(json.dumps({
                "metric": f"ssb_{ROWS // 1_000_000}m_q2_filter_groupby_rows_per_sec_per_chip",
                "value": 0,
                "unit": "rows/s",
                "vs_baseline": 0,
                "error": f"{type(e).__name__}: {e}",
            }))
        sys.exit(0 if "--config" not in sys.argv else 1)
