// Native host runtime: hot scalar loops the Python/numpy layer delegates to.
//
// Reference analogue (SURVEY.md §2.9): the effectively-native Java machinery
// Pinot relies on — FixedBitIntReader's unrolled bit-unpacking
// (pinot-segment-local/.../io/reader/impl/FixedBitIntReader.java:27,
// readUnchecked:44, read32:50), PinotDataBitSet, and the dict-id hashing
// inside DictionaryBasedGroupKeyGenerator. Compiled via g++ -O3 and loaded
// with ctypes (segment/native_bridge.py); every entry point has a numpy
// fallback, so the library is an accelerator, not a dependency.
//
// Format contract: LSB-first packed bitstream, identical to
// segment/bitpack.py pack()/unpack() — round-trip tests enforce parity.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Unpack `count` values of `num_bits` (1..32) from an LSB-first bitstream.
// `data` must have at least (count*num_bits+7)/8 + 8 readable bytes when
// padded=1 (the loader over-allocates); with padded=0 a safe tail loop runs.
void unpack_bits(const uint8_t* data, int num_bits, int64_t count,
                 int32_t* out, int padded) {
    if (num_bits == 8) {
        for (int64_t i = 0; i < count; i++) out[i] = data[i];
        return;
    }
    if (num_bits == 16) {
        const uint16_t* p = (const uint16_t*)data;
        for (int64_t i = 0; i < count; i++) out[i] = p[i];
        return;
    }
    if (num_bits == 32) {
        memcpy(out, data, (size_t)count * 4);
        return;
    }
    const uint64_t mask = (num_bits == 64) ? ~0ULL : ((1ULL << num_bits) - 1);
    int64_t fast = count;
    if (!padded) {
        // last values whose 8-byte window read would overrun run in the
        // byte-exact tail loop below
        int64_t total_bytes = ((count * num_bits) + 7) / 8;
        int64_t safe_bits = (total_bytes - 8) * 8;  // window start must fit
        fast = safe_bits > 0 ? safe_bits / num_bits : 0;
        if (fast > count) fast = count;
    }
    for (int64_t i = 0; i < fast; i++) {
        int64_t bit = i * (int64_t)num_bits;
        uint64_t window;
        memcpy(&window, data + (bit >> 3), 8);  // little-endian load
        out[i] = (int32_t)((window >> (bit & 7)) & mask);
    }
    int64_t total_bytes = ((count * (int64_t)num_bits) + 7) / 8;
    for (int64_t i = fast; i < count; i++) {
        int64_t bit = i * (int64_t)num_bits;
        uint64_t acc = 0;
        int got = 0;
        for (int64_t b = bit >> 3; got < num_bits + 8 && b < total_bytes;
             b++, got += 8)
            acc |= (uint64_t)data[b] << got;
        out[i] = (int32_t)((acc >> (bit & 7)) & mask);
    }
}

// Pack `n` non-negative values (< 2^num_bits) into an LSB-first bitstream.
// `out` must hold (n*num_bits+7)/8 bytes, zero-initialized.
void pack_bits(const uint32_t* values, int64_t n, int num_bits, uint8_t* out) {
    if (num_bits == 8) {
        for (int64_t i = 0; i < n; i++) out[i] = (uint8_t)values[i];
        return;
    }
    if (num_bits == 16) {
        uint16_t* p = (uint16_t*)out;
        for (int64_t i = 0; i < n; i++) p[i] = (uint16_t)values[i];
        return;
    }
    if (num_bits == 32) {
        memcpy(out, values, (size_t)n * 4);
        return;
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t bit = i * (int64_t)num_bits;
        uint64_t v = (uint64_t)values[i] << (bit & 7);
        uint8_t* p = out + (bit >> 3);
        // write ≤ 5 bytes (num_bits<32 + shift<8 → ≤ 39 bits)
        for (int b = 0; v; b++, v >>= 8) p[b] |= (uint8_t)(v & 0xFF);
    }
}

// Dense bool (uint8 0/1) → packed LSB-first bitmap.
void pack_bitmap(const uint8_t* bools, int64_t n, uint8_t* out) {
    memset(out, 0, (size_t)((n + 7) / 8));
    for (int64_t i = 0; i < n; i++)
        out[i >> 3] |= (uint8_t)((bools[i] & 1) << (i & 7));
}

void unpack_bitmap(const uint8_t* data, int64_t count, uint8_t* out) {
    for (int64_t i = 0; i < count; i++)
        out[i] = (data[i >> 3] >> (i & 7)) & 1;
}

// Factorize int64 keys → dense codes in first-occurrence order.
// Open-addressing hash table; returns the number of distinct keys.
// uniques[] receives the distinct keys (caller sizes it to n).
int64_t factorize_i64(const int64_t* keys, int64_t n, int64_t* codes,
                      int64_t* uniques) {
    if (n == 0) return 0;
    // table size: next power of two ≥ 2n
    uint64_t cap = 16;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    std::vector<int64_t> slot_key(cap);
    std::vector<int64_t> slot_code(cap, -1);
    uint64_t hmask = cap - 1;
    int64_t next = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t h = (uint64_t)keys[i] * 0x9E3779B97F4A7C15ULL;
        uint64_t s = (h ^ (h >> 29)) & hmask;
        while (true) {
            if (slot_code[s] < 0) {
                slot_key[s] = keys[i];
                slot_code[s] = next;
                uniques[next] = keys[i];
                codes[i] = next++;
                break;
            }
            if (slot_key[s] == keys[i]) {
                codes[i] = slot_code[s];
                break;
            }
            s = (s + 1) & hmask;
        }
    }
    return next;
}

// Grouped aggregation over float64 values with precomputed dense codes:
// one pass computing sum/count/min/max per group (the host fallback's
// aggregateGroupBySV analogue).
void group_agg_f64(const int64_t* codes, const double* vals, int64_t n,
                   int64_t num_groups, double* sums, int64_t* counts,
                   double* mins, double* maxs) {
    for (int64_t g = 0; g < num_groups; g++) {
        sums[g] = 0.0;
        counts[g] = 0;
        mins[g] = 1.0 / 0.0;
        maxs[g] = -1.0 / 0.0;
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t g = codes[i];
        double v = vals[i];
        sums[g] += v;
        counts[g]++;
        if (v < mins[g]) mins[g] = v;
        if (v > maxs[g]) maxs[g] = v;
    }
}

}  // extern "C"
