// Native host runtime: hot scalar loops the Python/numpy layer delegates to.
//
// Reference analogue (SURVEY.md §2.9): the effectively-native Java machinery
// Pinot relies on — FixedBitIntReader's unrolled bit-unpacking
// (pinot-segment-local/.../io/reader/impl/FixedBitIntReader.java:27,
// readUnchecked:44, read32:50), PinotDataBitSet, and the dict-id hashing
// inside DictionaryBasedGroupKeyGenerator. Compiled via g++ -O3 and loaded
// with ctypes (segment/native_bridge.py); every entry point has a numpy
// fallback, so the library is an accelerator, not a dependency.
//
// Format contract: LSB-first packed bitstream, identical to
// segment/bitpack.py pack()/unpack() — round-trip tests enforce parity.

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {

// Unpack `count` values of `num_bits` (1..32) from an LSB-first bitstream.
// `data` must have at least (count*num_bits+7)/8 + 8 readable bytes when
// padded=1 (the loader over-allocates); with padded=0 a safe tail loop runs.
void unpack_bits(const uint8_t* data, int num_bits, int64_t count,
                 int32_t* out, int padded) {
    if (num_bits == 8) {
        for (int64_t i = 0; i < count; i++) out[i] = data[i];
        return;
    }
    if (num_bits == 16) {
        const uint16_t* p = (const uint16_t*)data;
        for (int64_t i = 0; i < count; i++) out[i] = p[i];
        return;
    }
    if (num_bits == 32) {
        memcpy(out, data, (size_t)count * 4);
        return;
    }
    const uint64_t mask = (num_bits == 64) ? ~0ULL : ((1ULL << num_bits) - 1);
    int64_t fast = count;
    if (!padded) {
        // last values whose 8-byte window read would overrun run in the
        // byte-exact tail loop below
        int64_t total_bytes = ((count * num_bits) + 7) / 8;
        int64_t safe_bits = (total_bytes - 8) * 8;  // window start must fit
        fast = safe_bits > 0 ? safe_bits / num_bits : 0;
        if (fast > count) fast = count;
    }
    for (int64_t i = 0; i < fast; i++) {
        int64_t bit = i * (int64_t)num_bits;
        uint64_t window;
        memcpy(&window, data + (bit >> 3), 8);  // little-endian load
        out[i] = (int32_t)((window >> (bit & 7)) & mask);
    }
    int64_t total_bytes = ((count * (int64_t)num_bits) + 7) / 8;
    for (int64_t i = fast; i < count; i++) {
        int64_t bit = i * (int64_t)num_bits;
        uint64_t acc = 0;
        int got = 0;
        for (int64_t b = bit >> 3; got < num_bits + 8 && b < total_bytes;
             b++, got += 8)
            acc |= (uint64_t)data[b] << got;
        out[i] = (int32_t)((acc >> (bit & 7)) & mask);
    }
}

// Pack `n` non-negative values (< 2^num_bits) into an LSB-first bitstream.
// `out` must hold (n*num_bits+7)/8 bytes, zero-initialized.
void pack_bits(const uint32_t* values, int64_t n, int num_bits, uint8_t* out) {
    if (num_bits == 8) {
        for (int64_t i = 0; i < n; i++) out[i] = (uint8_t)values[i];
        return;
    }
    if (num_bits == 16) {
        uint16_t* p = (uint16_t*)out;
        for (int64_t i = 0; i < n; i++) p[i] = (uint16_t)values[i];
        return;
    }
    if (num_bits == 32) {
        memcpy(out, values, (size_t)n * 4);
        return;
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t bit = i * (int64_t)num_bits;
        uint64_t v = (uint64_t)values[i] << (bit & 7);
        uint8_t* p = out + (bit >> 3);
        // write ≤ 5 bytes (num_bits<32 + shift<8 → ≤ 39 bits)
        for (int b = 0; v; b++, v >>= 8) p[b] |= (uint8_t)(v & 0xFF);
    }
}

// Dense bool (uint8 0/1) → packed LSB-first bitmap.
void pack_bitmap(const uint8_t* bools, int64_t n, uint8_t* out) {
    memset(out, 0, (size_t)((n + 7) / 8));
    for (int64_t i = 0; i < n; i++)
        out[i >> 3] |= (uint8_t)((bools[i] & 1) << (i & 7));
}

void unpack_bitmap(const uint8_t* data, int64_t count, uint8_t* out) {
    for (int64_t i = 0; i < count; i++)
        out[i] = (data[i >> 3] >> (i & 7)) & 1;
}

// Factorize int64 keys → dense codes in first-occurrence order.
// Open-addressing hash table; returns the number of distinct keys.
// uniques[] receives the distinct keys (caller sizes it to n).
int64_t factorize_i64(const int64_t* keys, int64_t n, int64_t* codes,
                      int64_t* uniques) {
    if (n == 0) return 0;
    // table size: next power of two ≥ 2n
    uint64_t cap = 16;
    while (cap < (uint64_t)n * 2) cap <<= 1;
    std::vector<int64_t> slot_key(cap);
    std::vector<int64_t> slot_code(cap, -1);
    uint64_t hmask = cap - 1;
    int64_t next = 0;
    for (int64_t i = 0; i < n; i++) {
        uint64_t h = (uint64_t)keys[i] * 0x9E3779B97F4A7C15ULL;
        uint64_t s = (h ^ (h >> 29)) & hmask;
        while (true) {
            if (slot_code[s] < 0) {
                slot_key[s] = keys[i];
                slot_code[s] = next;
                uniques[next] = keys[i];
                codes[i] = next++;
                break;
            }
            if (slot_key[s] == keys[i]) {
                codes[i] = slot_code[s];
                break;
            }
            s = (s + 1) & hmask;
        }
    }
    return next;
}

// Grouped aggregation over float64 values with precomputed dense codes:
// one pass computing sum/count/min/max per group (the host fallback's
// aggregateGroupBySV analogue).
void group_agg_f64(const int64_t* codes, const double* vals, int64_t n,
                   int64_t num_groups, double* sums, int64_t* counts,
                   double* mins, double* maxs) {
    for (int64_t g = 0; g < num_groups; g++) {
        sums[g] = 0.0;
        counts[g] = 0;
        mins[g] = 1.0 / 0.0;
        maxs[g] = -1.0 / 0.0;
    }
    for (int64_t i = 0; i < n; i++) {
        int64_t g = codes[i];
        double v = vals[i];
        sums[g] += v;
        counts[g]++;
        if (v < mins[g]) mins[g] = v;
        if (v > maxs[g]) maxs[g] = v;
    }
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Chunk compression codecs (reference: ChunkCompressionType —
// pinot-segment-spi/.../compression/ChunkCompressionType.java:22 — backed
// there by JNI lz4/snappy/zstd libraries). Clean-room implementations of the
// public LZ4 block format and Snappy format specs; ZSTD/GZIP ride Python's
// zstandard/zlib on the host side (segment/compression.py).
// ---------------------------------------------------------------------------

extern "C" {

// ---- LZ4 block format -----------------------------------------------------
// Layout per sequence: token (hi nibble literal len, lo nibble match len-4,
// 15 = continued in 255-run bytes), literals, 2-byte LE offset, ext match
// len. Final sequence is literals-only. Spec constraints honored: last 5
// bytes are literals, no match starts within the last 12 bytes.

int64_t lz4_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                       int64_t dst_cap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + src_len;
    uint8_t* op = dst;
    uint8_t* oend = dst + dst_cap;
    while (ip < iend) {
        uint8_t token = *ip++;
        int64_t lit = token >> 4;
        if (lit == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                lit += b;
            } while (b == 255);
        }
        if (ip + lit > iend || op + lit > oend) return -1;
        memcpy(op, ip, (size_t)lit);
        ip += lit;
        op += lit;
        if (ip >= iend) break;  // final literals-only sequence
        if (ip + 2 > iend) return -1;
        int64_t offset = (int64_t)ip[0] | ((int64_t)ip[1] << 8);
        ip += 2;
        if (offset == 0 || op - dst < offset) return -1;
        int64_t mlen = token & 15;
        if (mlen == 15) {
            uint8_t b;
            do {
                if (ip >= iend) return -1;
                b = *ip++;
                mlen += b;
            } while (b == 255);
        }
        mlen += 4;
        if (op + mlen > oend) return -1;
        const uint8_t* match = op - offset;
        for (int64_t i = 0; i < mlen; i++) op[i] = match[i];  // overlap-safe
        op += mlen;
    }
    return op - dst;
}

static bool lz4_emit(uint8_t*& op, uint8_t* oend, const uint8_t* src,
                     int64_t lit_start, int64_t lit_len, int64_t offset,
                     int64_t mlen) {
    uint8_t* token = op;
    if (op >= oend) return false;
    op++;
    int64_t l = lit_len;
    *token = (uint8_t)((l >= 15 ? 15 : l) << 4);
    if (l >= 15) {
        l -= 15;
        while (l >= 255) {
            if (op >= oend) return false;
            *op++ = 255;
            l -= 255;
        }
        if (op >= oend) return false;
        *op++ = (uint8_t)l;
    }
    if (op + lit_len > oend) return false;
    memcpy(op, src + lit_start, (size_t)lit_len);
    op += lit_len;
    if (offset) {
        int64_t ml = mlen - 4;
        if (op + 2 > oend) return false;
        *op++ = (uint8_t)(offset & 0xFF);
        *op++ = (uint8_t)(offset >> 8);
        if (ml >= 15) {
            *token |= 15;
            ml -= 15;
            while (ml >= 255) {
                if (op >= oend) return false;
                *op++ = 255;
                ml -= 255;
            }
            if (op >= oend) return false;
            *op++ = (uint8_t)ml;
        } else {
            *token |= (uint8_t)ml;
        }
    }
    return true;
}

// Greedy hash-chain-free LZ4 compressor (single-probe table, the classic
// fast-mode design). dst_cap must be >= n + n/255 + 16.
int64_t lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                     int64_t dst_cap) {
    uint8_t* op = dst;
    uint8_t* oend = dst + dst_cap;
    const int HASH_LOG = 16;
    std::vector<int64_t> table((size_t)1 << HASH_LOG, -1);
    int64_t anchor = 0;
    const int64_t mflimit = n - 12;
    int64_t i = 0;
    while (i < mflimit) {
        uint32_t v;
        memcpy(&v, src + i, 4);
        uint32_t h = (v * 2654435761u) >> (32 - HASH_LOG);
        int64_t cand = table[h];
        table[h] = i;
        uint32_t w;
        if (cand >= 0 && i - cand <= 65535) {
            memcpy(&w, src + cand, 4);
            if (v == w) {
                int64_t maxm = (n - 5) - i;  // keep last 5 bytes literal
                int64_t mlen = 4;
                while (mlen < maxm && src[cand + mlen] == src[i + mlen]) mlen++;
                if (!lz4_emit(op, oend, src, anchor, i - anchor, i - cand, mlen))
                    return -1;
                i += mlen;
                anchor = i;
                continue;
            }
        }
        i++;
    }
    if (!lz4_emit(op, oend, src, anchor, n - anchor, 0, 0)) return -1;
    return op - dst;
}

// ---- Snappy format --------------------------------------------------------
// Preamble: uncompressed length as varint. Elements: tag low 2 bits —
// 00 literal (len-1 in tag>>2, 60..63 → that many extra LE length bytes),
// 01 copy len 4..11 / 11-bit offset, 10 copy len 1..64 / 16-bit LE offset,
// 11 copy with 32-bit offset.

int64_t snappy_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                          int64_t dst_cap) {
    const uint8_t* ip = src;
    const uint8_t* iend = src + src_len;
    // varint preamble
    uint64_t expect = 0;
    int shift = 0;
    while (true) {
        if (ip >= iend || shift > 63) return -1;
        uint8_t b = *ip++;
        expect |= (uint64_t)(b & 0x7F) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
    }
    if ((int64_t)expect > dst_cap) return -1;
    uint8_t* op = dst;
    uint8_t* oend = dst + dst_cap;
    while (ip < iend) {
        uint8_t tag = *ip++;
        int kind = tag & 3;
        if (kind == 0) {  // literal
            int64_t len = (tag >> 2) + 1;
            if (len > 60) {
                int extra = (int)len - 60;
                if (ip + extra > iend) return -1;
                len = 0;
                for (int b = 0; b < extra; b++)
                    len |= (int64_t)ip[b] << (8 * b);
                len += 1;
                ip += extra;
            }
            if (ip + len > iend || op + len > oend) return -1;
            memcpy(op, ip, (size_t)len);
            ip += len;
            op += len;
            continue;
        }
        int64_t len, offset;
        if (kind == 1) {
            len = ((tag >> 2) & 0x7) + 4;
            if (ip >= iend) return -1;
            offset = ((int64_t)(tag >> 5) << 8) | *ip++;
        } else if (kind == 2) {
            len = (tag >> 2) + 1;
            if (ip + 2 > iend) return -1;
            offset = (int64_t)ip[0] | ((int64_t)ip[1] << 8);
            ip += 2;
        } else {
            len = (tag >> 2) + 1;
            if (ip + 4 > iend) return -1;
            offset = (int64_t)ip[0] | ((int64_t)ip[1] << 8) |
                     ((int64_t)ip[2] << 16) | ((int64_t)ip[3] << 24);
            ip += 4;
        }
        if (offset == 0 || op - dst < offset || op + len > oend) return -1;
        const uint8_t* match = op - offset;
        for (int64_t b = 0; b < len; b++) op[b] = match[b];
        op += len;
    }
    return (op - dst) == (int64_t)expect ? (op - dst) : -1;
}

static bool snappy_emit_literal(uint8_t*& op, uint8_t* oend,
                                const uint8_t* src, int64_t start,
                                int64_t len) {
    while (len > 0) {
        int64_t chunk = len;  // literal lengths are unbounded via extra bytes
        int64_t l = chunk - 1;
        if (l < 60) {
            if (op + 1 + chunk > oend) return false;
            *op++ = (uint8_t)(l << 2);
        } else if (l < (1 << 8)) {
            if (op + 2 + chunk > oend) return false;
            *op++ = (uint8_t)(60 << 2);
            *op++ = (uint8_t)l;
        } else if (l < (1 << 16)) {
            if (op + 3 + chunk > oend) return false;
            *op++ = (uint8_t)(61 << 2);
            *op++ = (uint8_t)(l & 0xFF);
            *op++ = (uint8_t)(l >> 8);
        } else if (l < (1LL << 24)) {
            if (op + 4 + chunk > oend) return false;
            *op++ = (uint8_t)(62 << 2);
            *op++ = (uint8_t)(l & 0xFF);
            *op++ = (uint8_t)((l >> 8) & 0xFF);
            *op++ = (uint8_t)(l >> 16);
        } else {
            if (op + 5 + chunk > oend) return false;
            *op++ = (uint8_t)(63 << 2);
            *op++ = (uint8_t)(l & 0xFF);
            *op++ = (uint8_t)((l >> 8) & 0xFF);
            *op++ = (uint8_t)((l >> 16) & 0xFF);
            *op++ = (uint8_t)((l >> 24) & 0xFF);
        }
        memcpy(op, src + start, (size_t)chunk);
        op += chunk;
        start += chunk;
        len -= chunk;
    }
    return true;
}

static bool snappy_emit_copy(uint8_t*& op, uint8_t* oend, int64_t offset,
                             int64_t len) {
    // 16-bit-offset copies, 1..64 bytes each
    while (len > 0) {
        int64_t chunk = len > 64 ? 64 : len;
        if (len - chunk > 0 && len - chunk < 4) chunk = len - 4;  // keep ≥4 tail
        if (op + 3 > oend) return false;
        *op++ = (uint8_t)(((chunk - 1) << 2) | 2);
        *op++ = (uint8_t)(offset & 0xFF);
        *op++ = (uint8_t)(offset >> 8);
        len -= chunk;
    }
    return true;
}

// Greedy snappy compressor (16-bit offsets only; matches within 65535).
// dst_cap must be >= 32 + n + n/6.
int64_t snappy_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                        int64_t dst_cap) {
    uint8_t* op = dst;
    uint8_t* oend = dst + dst_cap;
    // varint preamble
    uint64_t v = (uint64_t)n;
    do {
        if (op >= oend) return -1;
        uint8_t b = (uint8_t)(v & 0x7F);
        v >>= 7;
        *op++ = v ? (b | 0x80) : b;
    } while (v);
    const int HASH_LOG = 16;
    std::vector<int64_t> table((size_t)1 << HASH_LOG, -1);
    int64_t anchor = 0, i = 0;
    while (i + 4 <= n) {
        uint32_t x;
        memcpy(&x, src + i, 4);
        uint32_t h = (x * 2654435761u) >> (32 - HASH_LOG);
        int64_t cand = table[h];
        table[h] = i;
        uint32_t y;
        if (cand >= 0 && i - cand <= 65535) {
            memcpy(&y, src + cand, 4);
            if (x == y) {
                int64_t mlen = 4;
                while (i + mlen < n && src[cand + mlen] == src[i + mlen]) mlen++;
                if (!snappy_emit_literal(op, oend, src, anchor, i - anchor))
                    return -1;
                if (!snappy_emit_copy(op, oend, i - cand, mlen)) return -1;
                i += mlen;
                anchor = i;
                continue;
            }
        }
        i++;
    }
    if (!snappy_emit_literal(op, oend, src, anchor, n - anchor)) return -1;
    return op - dst;
}

}  // extern "C"
