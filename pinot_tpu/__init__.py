"""pinot_tpu — a TPU-native distributed OLAP engine.

A from-scratch rebuild of Apache Pinot's capabilities (columnar immutable
segments, scatter/gather SQL, streaming + batch ingestion) where the
per-segment filter → project → group-by → aggregate engine is a compiled
JAX/XLA program over dictionary-encoded dense column planes resident in HBM.
See SURVEY.md for the reference structural map this is built against.
"""

__version__ = "0.1.0"
