"""Multi-tier result cache (new subsystem, PR 5).

Three tiers, all keyed off segment immutability — any per-segment partial
is a pure function of (compiled plan, segment content):

- ``keys.py``     canonical plan fingerprints + segment identity tokens
- ``partial.py``  server-side (program_fp, segment_token) → partial result
- ``results.py``  broker-side full-response cache + table lineage epochs

Device-resident sparse group tables register against the HBM budget in
``segment/device_cache.py`` (their own eviction class, evicted first).
"""
