"""Canonical plan fingerprints + segment identity tokens (cache tier 1).

A per-segment partial result is a pure function of (compiled plan, segment
content), so the cache key must be *process-stable*: two fresh planner
instances compiling the same SQL must produce byte-identical fingerprints,
and any change that can alter the partial (a filter literal, an agg, a SET
option that affects results) must change them.

The encoder below is deliberately closed-world: it walks frozen IR
dataclasses, containers, numpy values and primitives, and RAISES on
anything else. There is no ``repr()``/``id()`` fallback — that is how
object identity (memory addresses, insertion order of unhashed sets)
leaks into keys and silently breaks cross-process stability. If a new
node type shows up in a Program, fingerprinting fails loudly and the
executor just skips the cache for that query.
"""

from __future__ import annotations

import dataclasses
import enum
import hashlib
import struct
import threading
from typing import Optional

import numpy as np

# SET options that change HOW a query executes but never WHAT it returns.
# Everything not listed here is conservatively folded into the fingerprint
# (numGroupsLimit, enableNullHandling, trim options, ... all affect rows).
# Compared lowercase so spelling variants can't split cache entries.
EXECUTION_ONLY_OPTIONS = frozenset({
    "segmentbatch", "devicecombine", "segmentcache", "resultcache",
    "trace", "timeoutms", "usemultistageengine", "meshexecution",
    "devicejoin", "coalesce", "realtimedeviceplanes",
})

# Lifetime fingerprint computations in this process — the perf guard
# (tests/test_cache_perf_guard.py) pins that ``SET segmentCache=false``
# performs ZERO of these on the hot path. A plain list cell keeps the
# counter GIL-atomic without a lock on every increment.
_FP_COUNT = [0]
_FP_LOCK = threading.Lock()


def fingerprint_computations() -> int:
    return _FP_COUNT[0]


class UnfingerprintableError(TypeError):
    """A value with no canonical byte encoding reached the key encoder."""


def _enc(obj, out: list) -> None:
    """Append a canonical, type-tagged byte encoding of ``obj``. Tags keep
    distinct types with equal payloads apart (1 vs 1.0 vs "1" vs True)."""
    if obj is None:
        out.append(b"N")
    elif obj is True:
        out.append(b"T")
    elif obj is False:
        out.append(b"F")
    elif isinstance(obj, enum.Enum):
        out.append(b"E")
        _enc(type(obj).__qualname__, out)
        _enc(obj.name, out)
    elif isinstance(obj, str):
        b = obj.encode("utf-8")
        out.append(b"s%d:" % len(b))
        out.append(b)
    elif isinstance(obj, bytes):
        out.append(b"b%d:" % len(obj))
        out.append(obj)
    elif isinstance(obj, int):
        out.append(b"i%d;" % obj)
    elif isinstance(obj, float):
        out.append(b"f")
        out.append(struct.pack("<d", obj))
    elif isinstance(obj, (np.generic, np.ndarray)):
        a = np.asarray(obj)
        out.append(b"a")
        _enc(a.dtype.str, out)
        _enc(tuple(int(d) for d in a.shape), out)
        out.append(a.tobytes())
    elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        # frozen IR nodes: qualname + every field in declaration order
        out.append(b"D")
        _enc(type(obj).__qualname__, out)
        for f in dataclasses.fields(obj):
            _enc(f.name, out)
            _enc(getattr(obj, f.name), out)
    elif isinstance(obj, (tuple, list)):
        out.append(b"l%d:" % len(obj))
        for item in obj:
            _enc(item, out)
    elif isinstance(obj, dict):
        items = []
        for k, v in obj.items():
            kb: list = []
            _enc(k, kb)
            items.append((b"".join(kb), v))
        items.sort(key=lambda kv: kv[0])
        out.append(b"d%d:" % len(items))
        for kb, v in items:
            out.append(kb)
            _enc(v, out)
    else:
        raise UnfingerprintableError(
            f"no canonical encoding for {type(obj).__qualname__}")


def canonical_bytes(obj) -> bytes:
    buf: list = []
    _enc(obj, buf)
    return b"".join(buf)


def _result_options(query) -> dict:
    return {str(k): str(v) for k, v in query.query_options.items()
            if str(k).lower() not in EXECUTION_ONLY_OPTIONS}


def program_fingerprint(plan, query) -> Optional[str]:
    """Fingerprint of a compiled per-segment plan: the Program IR (filter
    tree with param slot references), runtime param VALUES (the literals),
    slot layout, and the canonical query text. ``str(query)`` is included
    because structurally identical Programs can decode differently (AVG vs
    SUM+COUNT share a kernel; finalizers live in lowered_aggs, which holds
    callables and is covered by the query text instead). Returns None when
    any component has no canonical encoding — callers bypass the cache."""
    try:
        payload = (
            "pfp1",
            canonical_bytes(plan.program),
            tuple(plan.slots),
            bool(plan.fused_ok),
            tuple(canonical_bytes(np.asarray(p)) for p in plan.params),
            str(query),
            _result_options(query),
        )
        digest = hashlib.sha256(canonical_bytes(payload)).hexdigest()
    except UnfingerprintableError:
        return None
    with _FP_LOCK:
        _FP_COUNT[0] += 1
    return digest


def query_fingerprint(query) -> Optional[str]:
    """Broker-tier fingerprint: canonical SQL text + result-affecting SET
    options. QueryContext.__str__ is deterministic canonical SQL (filter /
    expression __str__ are all value-based), so two parses of the same
    request collide here by construction."""
    try:
        payload = ("qfp1", str(query), _result_options(query))
        digest = hashlib.sha256(canonical_bytes(payload)).hexdigest()
    except UnfingerprintableError:
        return None
    with _FP_LOCK:
        _FP_COUNT[0] += 1
    return digest


def mse_plan_fingerprint(stages, query_options,
                         parallelism: int) -> Optional[str]:
    """Fingerprint of a fragmented MSE stage DAG: every Stage dataclass
    (operator trees, exchange dists/keys, pruned send schemas) plus the
    result-affecting SET options and the stage parallelism (it shapes
    BREAK-mode truncation points, so it is result-affecting for overflowing
    joins). The logical IR is all frozen dataclasses, so the closed-world
    encoder covers it; any foreign node makes the plan uncacheable (None),
    never wrongly cacheable."""
    try:
        opts = {str(k): str(v) for k, v in (query_options or {}).items()
                if str(k).lower() not in EXECUTION_ONLY_OPTIONS}
        payload = ("msefp1", tuple(stages), opts, int(parallelism))
        digest = hashlib.sha256(canonical_bytes(payload)).hexdigest()
    except UnfingerprintableError:
        return None
    with _FP_LOCK:
        _FP_COUNT[0] += 1
    return digest


def segment_token(segment) -> Optional[tuple]:
    """Content identity of an immutable segment: (name, crc). Realtime
    snapshot views with a pinned generation get ("rt", name, generation):
    the row prefix below the pinned count is append-only immutable and the
    upsert validity generation rides in the tuple, so equal tokens imply
    byte-identical snapshot contents — stale reuse is impossible by
    construction. Mutable objects WITHOUT a pinned generation, and
    segments without a crc, return None and always bypass the cache. The
    crc is part of the immutable key, so a replaced segment reusing its
    name can never serve stale partials even before eager invalidation
    runs."""
    if getattr(segment, "is_mutable", False):
        gen = getattr(segment, "snapshot_generation", None)
        name = getattr(segment, "name", None)
        if gen is None or not name:
            return None
        return ("rt", str(name), tuple(gen))
    meta = getattr(segment, "metadata", None)
    name = getattr(segment, "name", None) or getattr(meta, "segment_name", None)
    crc = getattr(meta, "crc", None)
    if not name or not crc:
        return None
    return (str(name), str(crc))


def family_fingerprint(program, padded: int, fused: str = "",
                       lut_meta: tuple = (),
                       batch_size: int = 0,
                       mesh: tuple = ()) -> Optional[str]:
    """Fingerprint of one COMPILED EXECUTABLE FAMILY: the Program IR plus
    the shape/variant axes jit actually specializes on (padded bucket,
    fused variant, LUT run metadata, batch size) — and nothing that is a
    runtime argument (param values, literals, query text). This is the
    stable cross-process identity of a compiled artifact: the compile
    telemetry registry keys on it, and it is the key an AOT executable
    cache would persist under. Deliberately does NOT bump
    ``fingerprint_computations()`` — it is compile telemetry, not a
    result-cache key, and it is only computed on compile-guard misses
    (cold path), so the hot-path perf guards stay meaningful."""
    try:
        payload = ("ffp1", canonical_bytes(program), int(padded),
                   str(fused), tuple(lut_meta), int(batch_size))
        if mesh:
            # sharded executables are distinct artifacts; solo families keep
            # the historical ffp1 digest so registries don't churn
            payload = ("ffp2",) + payload[1:] + (
                tuple(int(x) for x in mesh),)
        return hashlib.sha256(canonical_bytes(payload)).hexdigest()
    except UnfingerprintableError:
        return None
