"""Segment partial-result cache (cache tier 2, host side).

Server-side map from ``(program_fp, segment_token)`` → the per-segment
partial (dense agg state vector or group table). Because segments are
immutable and the fingerprint folds in every result-affecting input
(cache/keys.py), a hit is exactly the value the device would recompute —
the executor skips the dispatch entirely and feeds the combine.

Values are deep-copied on BOTH put and get: the combine functions merge
agg states IN PLACE (engine/combine.py mutates lists/sets/digests of the
first intermediate), so sharing a cached object across queries would
corrupt it on the second merge.

Device-resident sparse tables live in segment/device_cache.py against the
HBM budget; this tier holds host objects under its own byte budget
(``PINOT_TPU_PARTIAL_CACHE_MB``, default 256).
"""

from __future__ import annotations

import copy
import os
import threading
from collections import OrderedDict
from typing import Optional

from ..spi.metrics import SERVER_METRICS, ServerMeter


def partial_cache_enabled() -> bool:
    """Segment partial caching defaults ON; PINOT_TPU_SEGMENT_CACHE=0
    disables it process-wide (per query: ``SET segmentCache = false``)."""
    return os.environ.get("PINOT_TPU_SEGMENT_CACHE", "1") \
        not in ("0", "false", "")


def _default_budget() -> int:
    return int(float(os.environ.get("PINOT_TPU_PARTIAL_CACHE_MB", 256))
               * (1 << 20))


def _estimate_partial_bytes(inter) -> int:
    """Footprint estimate for the byte budget — same container heuristics
    as the scheduler accountant (engine/query_executor._estimate_bytes),
    inlined here so the cache never imports the engine (cycle)."""
    from ..engine.results import (AggIntermediate, GroupArrays,
                                  GroupByIntermediate)

    if isinstance(inter, GroupArrays):
        return (sum(k.nbytes for k in inter.key_cols)
                + sum(c.nbytes for comps in inter.state_cols for c in comps)
                + 64)
    if isinstance(inter, GroupByIntermediate):
        width = 1 + max((len(v) for v in inter.groups.values()), default=0)
        return 64 * width * max(1, len(inter.groups))
    if isinstance(inter, AggIntermediate):
        return 64 * max(1, len(inter.states))
    return 256


class SegmentPartialCache:
    """LRU, byte-budgeted map of (program_fp, segment_token) → partial.
    Thread-safe: cluster servers run concurrent queries over one process-
    global instance. Entries remember which segment names fed them so
    lineage events (replace/delete/commit) can evict eagerly by name."""

    def __init__(self, max_bytes: Optional[int] = None):
        self.max_bytes = _default_budget() if max_bytes is None else max_bytes
        # key → (value, nbytes, segment_names)
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: tuple):
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            value = ent[0]
        return copy.deepcopy(value)

    def put(self, key: tuple, value, segment_names: tuple) -> None:
        try:
            stored = copy.deepcopy(value)
        except Exception:
            return  # uncopyable state (open handles etc.): skip, never fail
        nbytes = _estimate_partial_bytes(stored)
        with self._lock:
            if nbytes > self.max_bytes:
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (stored, nbytes, tuple(segment_names))
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, (_, freed, _) = self._entries.popitem(last=False)
                self._bytes -= freed
                self.evictions += 1
                SERVER_METRICS.add_meter(ServerMeter.SEGMENT_CACHE_EVICTIONS)

    def invalidate_segment(self, segment_name: str) -> int:
        """Drop every entry derived from ``segment_name`` (lineage event:
        replace/delete/realtime commit). Content-addressed keys make stale
        hits impossible anyway; this frees the bytes eagerly."""
        with self._lock:
            stale = [k for k, ent in self._entries.items()
                     if segment_name in ent[2]]
            for k in stale:
                self._bytes -= self._entries.pop(k)[1]
            return len(stale)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "maxBytes": self.max_bytes, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions}


GLOBAL_PARTIAL_CACHE = SegmentPartialCache()
