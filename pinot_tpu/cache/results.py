"""Broker full-response cache + table lineage epochs (cache tier 3).

A full BrokerResponse is reusable only while the table's segment lineage
is unchanged, so cache keys embed a **lineage epoch**: a counter in the
property store (``/CACHEEPOCH/{tableNameWithType}``) bumped on every
segment upload/replace/delete (cluster/controller.py, cluster/periodic.py
— which also covers minion refresh/merge tasks, since those land through
the controller) and on realtime segment commit (realtime/completion.py).
A bumped epoch changes every key for the table; stale entries simply stop
being addressable and age out by TTL/LRU.

Entries expire by TTL (``PINOT_TPU_RESULT_CACHE_TTL_S``, default 300) and
by a byte budget (``PINOT_TPU_RESULT_CACHE_MB``, default 64). The clock is
injectable for tests.
"""

from __future__ import annotations

import copy
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

from ..spi.metrics import BROKER_METRICS, BrokerMeter

EPOCH_PREFIX = "/CACHEEPOCH"


def result_cache_enabled() -> bool:
    """Broker result caching defaults ON; PINOT_TPU_RESULT_CACHE=0
    disables it process-wide (per query: ``SET resultCache = false``)."""
    return os.environ.get("PINOT_TPU_RESULT_CACHE", "1") \
        not in ("0", "false", "")


def lineage_epoch(store, name_with_type: str) -> int:
    """Current lineage epoch for a table (0 = never bumped)."""
    return int(store.get(f"{EPOCH_PREFIX}/{name_with_type}") or 0)


def bump_lineage_epoch(store, name_with_type: str) -> None:
    """Advance the table's epoch — every broker result-cache key derived
    from the old epoch becomes unreachable atomically."""
    store.update(f"{EPOCH_PREFIX}/{name_with_type}",
                 lambda cur: int(cur or 0) + 1)


def _estimate_response_bytes(resp) -> int:
    rt = getattr(resp, "result_table", None)
    if rt is None:
        return 512
    width = max(1, len(getattr(rt, "rows", None) and rt.rows[0] or ()))
    return 512 + 48 * width * len(rt.rows)


class BrokerResultCache:
    """TTL + byte-budgeted LRU of query_fp-keyed BrokerResponses."""

    def __init__(self, max_bytes: Optional[int] = None,
                 ttl_s: Optional[float] = None, clock=time.monotonic):
        if max_bytes is None:
            max_bytes = int(float(os.environ.get(
                "PINOT_TPU_RESULT_CACHE_MB", 64)) * (1 << 20))
        if ttl_s is None:
            ttl_s = float(os.environ.get("PINOT_TPU_RESULT_CACHE_TTL_S", 300))
        self.max_bytes = max_bytes
        self.ttl_s = ttl_s
        self._clock = clock
        # key → (response, nbytes, inserted_at)
        self._entries: OrderedDict[tuple, tuple] = OrderedDict()
        self._bytes = 0
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key: tuple):
        """Shallow copy on hit: callers restamp per-request fields
        (time_used_ms, requestId) without touching the cached object.
        result_table/rows are shared read-only — the REST layer only
        serializes them."""
        with self._lock:
            ent = self._entries.get(key)
            if ent is not None and \
                    self._clock() - ent[2] > self.ttl_s:
                self._entries.pop(key)
                self._bytes -= ent[1]
                ent = None
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return copy.copy(ent[0])

    def put(self, key: tuple, resp) -> None:
        nbytes = _estimate_response_bytes(resp)
        with self._lock:
            if nbytes > self.max_bytes:
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (copy.copy(resp), nbytes, self._clock())
            self._bytes += nbytes
            while self._bytes > self.max_bytes and len(self._entries) > 1:
                _, (_, freed, _) = self._entries.popitem(last=False)
                self._bytes -= freed
                self.evictions += 1
                BROKER_METRICS.add_meter(BrokerMeter.RESULT_CACHE_EVICTIONS)

    def clear(self) -> int:
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._bytes = 0
            return n

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self._bytes,
                    "maxBytes": self.max_bytes, "ttlS": self.ttl_s,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}
