"""Python client: connect to a broker over HTTP and run SQL.

Reference analogue: pinot-clients/pinot-java-client (Connection.execute →
broker /query/sql) and the JDBC driver's ResultSet surface. Zero-dependency
urllib; `connect()` is the module entry like the reference's
ConnectionFactory.
"""

from __future__ import annotations

import json
import urllib.request
from typing import Iterator, Optional


class PinotClientError(Exception):
    pass


class ResultSet:
    """Row/column access over one query's result table."""

    def __init__(self, response: dict):
        self._response = response
        table = response.get("resultTable") or {}
        schema = table.get("dataSchema") or {}
        self.column_names: list[str] = schema.get("columnNames", [])
        self.column_types: list[str] = schema.get("columnDataTypes", [])
        self.rows: list[list] = table.get("rows", [])

    def __len__(self) -> int:
        return len(self.rows)

    def __iter__(self) -> Iterator[list]:
        return iter(self.rows)

    def get(self, row: int, column) -> object:
        if isinstance(column, str):
            column = self.column_names.index(column)
        return self.rows[row][column]

    @property
    def execution_stats(self) -> dict:
        return {k: v for k, v in self._response.items() if k != "resultTable"}


class Connection:
    def __init__(self, broker_url: str, timeout_s: float = 60.0,
                 auth=None, token: str = None):
        """``auth=(user, password)`` sends Basic auth; ``token`` sends a
        Bearer token (cluster/auth.py providers)."""
        self.broker_url = broker_url.rstrip("/")
        self.timeout_s = timeout_s
        self._auth_header = None
        if auth is not None:
            import base64

            cred = base64.b64encode(f"{auth[0]}:{auth[1]}".encode()).decode()
            self._auth_header = f"Basic {cred}"
        elif token is not None:
            self._auth_header = f"Bearer {token}"

    def execute(self, sql: str) -> ResultSet:
        resp = self._post("/query/sql", {"sql": sql})
        if resp.get("exceptions"):
            raise PinotClientError(str(resp["exceptions"]))
        return ResultSet(resp)

    def execute_timeseries(self, query: str, start: int, end: int, step: int,
                           language: str = "m3ql") -> dict:
        return self._post("/timeseries/api/v1/query_range", {
            "query": query, "start": start, "end": end, "step": step,
            "language": language})

    def _post(self, path: str, body: dict) -> dict:
        headers = {"Content-Type": "application/json"}
        if self._auth_header:
            headers["Authorization"] = self._auth_header
        req = urllib.request.Request(
            self.broker_url + path,
            data=json.dumps(body).encode("utf-8"), headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                return json.loads(r.read().decode("utf-8"))
        except urllib.error.HTTPError as e:
            if e.code in (401, 403):
                raise PinotClientError(
                    f"HTTP {e.code}: access denied for {path}") from e
            try:
                return json.loads(e.read().decode("utf-8"))
            except ValueError:
                raise PinotClientError(f"HTTP {e.code} from {path}") from e
        except OSError as e:
            raise PinotClientError(f"cannot reach broker: {e}") from e


def connect(broker_url: str, timeout_s: float = 60.0, auth=None,
            token: str = None) -> Connection:
    """Reference: ConnectionFactory.fromHostList."""
    return Connection(broker_url, timeout_s, auth=auth, token=token)
