"""Cluster layer: controller / broker / server roles over a shared
property store — the Helix-over-ZooKeeper analogue.

Reference analogue: Apache Helix 1.3.1 + ZooKeeper control plane
(SURVEY.md §2.10), PinotHelixResourceManager (pinot-controller/.../helix/
core/PinotHelixResourceManager.java), broker routing
(pinot-broker/.../routing/BrokerRoutingManager.java), server state model
(pinot-server/.../helix/SegmentOnlineOfflineStateModelFactory.java:44).

TPU-first stance: the control plane stays host-side and lightweight (an
in-process/etcd-style store with watches); the data plane is a socket
scatter/gather whose per-server execution path is the device engine. The
hierarchy mirrors the reference exactly: ideal state (what should be) vs
external view (what is), with servers converging one to the other.
"""

from .store import PropertyStore
from .controller import ClusterController
from .server import ServerInstance
from .broker import Broker
from .rebalance import RebalanceActuator, SegmentRebalancer

__all__ = ["PropertyStore", "ClusterController", "ServerInstance", "Broker",
           "SegmentRebalancer", "RebalanceActuator"]
