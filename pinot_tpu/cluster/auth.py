"""Access control: authentication + table-level authorization.

Reference: pinot-controller/.../api/access/AccessControl.java (+
BasicAuthAccessControlFactory in pinot-core, ZkBasicAuthAccessControl) —
every REST request resolves a principal from its Authorization header, and
each endpoint checks (principal, table, access type). Providers are
pluggable; AllowAll is the default, Basic auth (user:password) and Bearer
tokens ship in-tree.

Principals carry table patterns ("*" or explicit names) and permission
sets (READ/WRITE) exactly like the reference's BasicAuthPrincipal.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
from dataclasses import dataclass, field
from typing import Mapping, Optional

READ = "READ"
WRITE = "WRITE"


@dataclass
class Principal:
    name: str
    tables: tuple = ("*",)  # "*" or explicit raw table names
    permissions: frozenset = frozenset({READ, WRITE})

    def allows(self, table: Optional[str], access_type: str) -> bool:
        if access_type not in self.permissions:
            return False
        if table is None or "*" in self.tables:
            return True
        from .controller import raw_table_name

        return raw_table_name(table) in self.tables


class AccessControl:
    """Provider interface (reference AccessControl.java)."""

    def authenticate(self, headers: Mapping[str, str]) -> Optional[Principal]:
        """Header map → principal, or None when unauthenticated."""
        raise NotImplementedError

    def has_access(self, principal: Optional[Principal],
                   table: Optional[str], access_type: str) -> bool:
        raise NotImplementedError


class AllowAllAccessControl(AccessControl):
    """Default: everything allowed (reference AllowAllAccessFactory)."""

    def authenticate(self, headers) -> Principal:
        return Principal("anonymous")

    def has_access(self, principal, table, access_type) -> bool:
        return True


def _hash(secret: str) -> str:
    return hashlib.sha256(secret.encode("utf-8")).hexdigest()


@dataclass
class _Entry:
    principal: Principal
    secret_hash: str


class BasicAuthAccessControl(AccessControl):
    """``Authorization: Basic base64(user:password)`` or
    ``Authorization: Bearer <token>`` (reference
    BasicAuthAccessControlFactory; tokens are the user-less variant).

    principals: list of dicts
        {"username": ..., "password": ...} or {"token": ...}
        plus optional "tables": ["*"] | [names], "permissions": ["READ",...]
    Secrets are stored hashed; comparison is constant-time.
    """

    def __init__(self, principals: list[dict]):
        self._by_user: dict[str, _Entry] = {}
        self._tokens: dict[str, Principal] = {}
        for p in principals:
            tables = tuple(p.get("tables", ["*"]))
            perms = frozenset(p.get("permissions", [READ, WRITE]))
            if "token" in p:
                name = p.get("username", f"token:{p['token'][:6]}")
                self._tokens[_hash(p["token"])] = Principal(name, tables, perms)
            else:
                prin = Principal(p["username"], tables, perms)
                self._by_user[p["username"]] = _Entry(prin, _hash(p["password"]))

    def authenticate(self, headers) -> Optional[Principal]:
        auth = None
        for k, v in headers.items():
            if k.lower() == "authorization":
                auth = v
                break
        if not auth:
            return None
        scheme, _, value = auth.partition(" ")
        scheme = scheme.lower()
        if scheme == "basic":
            try:
                user, _, password = base64.b64decode(value.strip()) \
                    .decode("utf-8").partition(":")
            except Exception:
                return None
            entry = self._by_user.get(user)
            if entry is None:
                return None
            if hmac.compare_digest(entry.secret_hash, _hash(password)):
                return entry.principal
            return None
        if scheme == "bearer":
            # dict lookup by sha256 of the presented token: equivalent to a
            # constant-time scan for fixed-length high-entropy digests
            return self._tokens.get(_hash(value.strip()))
        return None

    def has_access(self, principal, table, access_type) -> bool:
        return principal is not None and principal.allows(table, access_type)
