"""Per-server circuit breakers for the broker routing table.

Reference analogue: ConnectionFailureDetector marks a server unhealthy
behind an exponential-backoff retry window; a circuit breaker is the
stronger contract the retry/hedge layer needs — a dead server must stop
eating retry budget the moment it trips, and must be re-admitted through
a bounded probe, not a thundering herd.

State machine per server (the classic closed → open → half-open cycle):

  closed     all traffic; ``failure_threshold`` CONSECUTIVE transport
             failures — or, when ``error_rate_threshold`` is configured,
             that failure ratio over a recent-outcome window — trips it.
  open       no traffic for ``cooldown_s`` (doubles on every re-trip,
             capped at ``max_cooldown_s``); selection skips the server.
  half-open  exactly one probe RPC is admitted; success closes the
             breaker (cooldown resets), failure re-opens it with a
             longer cooldown. A probe that never resolves (hung socket)
             releases the probe slot after another cooldown.

All transitions are pure call-count/clock bookkeeping — no background
thread — so the deterministic fault schedules in spi/faults.py drive the
full lifecycle from tests.

Env knobs:
  PINOT_TPU_BREAKER_FAILURES    consecutive-failure trip threshold (3)
  PINOT_TPU_BREAKER_COOLDOWN_S  initial open→half-open cooldown (2.0)
  PINOT_TPU_BREAKER_ERROR_RATE  failure-ratio trip threshold over the
                                outcome window (unset/0 = disabled)
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque

from ..spi.metrics import BROKER_METRICS, BrokerMeter

CLOSED, HALF_OPEN, OPEN = "closed", "half_open", "open"
_STATE_VALUE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class CircuitBreaker:
    """One server's breaker. Not thread-safe on its own — the owning
    CircuitBreakerTable serializes access."""

    __slots__ = ("state", "consecutive_failures", "cooldown_s", "open_until",
                 "probe_inflight_since", "outcomes", "opened_count")

    def __init__(self, base_cooldown_s: float):
        self.state = CLOSED
        self.consecutive_failures = 0
        self.cooldown_s = base_cooldown_s
        self.open_until = 0.0
        self.probe_inflight_since: float | None = None
        # recent (timestamp, ok) outcomes for the error-rate trip
        self.outcomes: deque = deque(maxlen=64)
        self.opened_count = 0


class CircuitBreakerTable:
    """Breaker per server instance, consulted by replica selection
    (``allow``) and fed by scatter-RPC outcomes (``record_success`` /
    ``record_failure``). API-compatible with the _FailureDetector it
    replaces (``mark_failed`` / ``mark_healthy`` / ``is_healthy`` /
    ``down_count``)."""

    def __init__(self, failure_threshold: int | None = None,
                 cooldown_s: float | None = None,
                 error_rate_threshold: float | None = None,
                 max_cooldown_s: float = 30.0,
                 error_rate_min_volume: int = 8,
                 error_rate_window_s: float = 30.0,
                 metrics=BROKER_METRICS):
        if failure_threshold is None:
            failure_threshold = int(os.environ.get(
                "PINOT_TPU_BREAKER_FAILURES", 3))
        if cooldown_s is None:
            cooldown_s = float(os.environ.get(
                "PINOT_TPU_BREAKER_COOLDOWN_S", 2.0))
        if error_rate_threshold is None:
            rate = float(os.environ.get("PINOT_TPU_BREAKER_ERROR_RATE", 0.0))
            error_rate_threshold = rate if rate > 0 else None
        self.failure_threshold = max(1, failure_threshold)
        self.base_cooldown_s = cooldown_s
        self.max_cooldown_s = max_cooldown_s
        self.error_rate_threshold = error_rate_threshold
        self.error_rate_min_volume = error_rate_min_volume
        self.error_rate_window_s = error_rate_window_s
        self.metrics = metrics
        self._lock = threading.Lock()
        self._breakers: dict[str, CircuitBreaker] = {}

    def _breaker_locked(self, instance: str) -> CircuitBreaker:
        b = self._breakers.get(instance)
        if b is None:
            b = CircuitBreaker(self.base_cooldown_s)
            self._breakers[instance] = b
            if self.metrics is not None:
                # per-server breaker state gauge (0=closed, 1=half-open,
                # 2=open) for GET /metrics
                self.metrics.set_gauge(
                    f"circuitBreakerState.{instance}",
                    lambda b=b: _STATE_VALUE[b.state])
        return b

    # -- selection side ------------------------------------------------------
    def allow(self, instance: str) -> bool:
        """May the next RPC go to this server? Open breakers whose cooldown
        has elapsed transition to half-open and admit ONE probe; open
        breakers inside the cooldown (and half-open breakers with a live
        probe) refuse."""
        now = time.monotonic()
        with self._lock:
            b = self._breakers.get(instance)
            if b is None or b.state == CLOSED:
                return True
            if b.state == OPEN:
                if now < b.open_until:
                    return False
                b.state = HALF_OPEN
                b.probe_inflight_since = now
                return True  # this caller carries the probe
            # half-open: one probe at a time; a probe stuck longer than
            # the cooldown is presumed lost — hand out another
            if b.probe_inflight_since is None or \
                    now - b.probe_inflight_since >= b.cooldown_s:
                b.probe_inflight_since = now
                return True
            return False

    def is_healthy(self, instance: str) -> bool:  # _FailureDetector compat
        return self.allow(instance)

    # -- outcome side --------------------------------------------------------
    def record_success(self, instance: str) -> None:
        with self._lock:
            # create on first success too: the error-rate trip needs the
            # success side of the outcome window, not just failures
            b = self._breaker_locked(instance)
            b.consecutive_failures = 0
            b.outcomes.append((time.monotonic(), True))
            b.probe_inflight_since = None
            if b.state != CLOSED:
                b.state = CLOSED
                b.cooldown_s = self.base_cooldown_s

    def record_failure(self, instance: str) -> None:
        opened = False
        with self._lock:
            b = self._breaker_locked(instance)
            now = time.monotonic()
            b.consecutive_failures += 1
            b.outcomes.append((now, False))
            if b.state == HALF_OPEN:
                # failed probe: re-open with a longer cooldown
                b.probe_inflight_since = None
                b.cooldown_s = min(b.cooldown_s * 2, self.max_cooldown_s)
                opened = self._open_locked(b, now)
            elif b.state == CLOSED and (
                    b.consecutive_failures >= self.failure_threshold
                    or self._error_rate_tripped_locked(b, now)):
                opened = self._open_locked(b, now)
        if opened and self.metrics is not None:
            self.metrics.add_meter(BrokerMeter.CIRCUIT_OPEN)

    def _open_locked(self, b: CircuitBreaker, now: float) -> bool:
        b.state = OPEN
        b.open_until = now + b.cooldown_s
        b.opened_count += 1
        return True

    def _error_rate_tripped_locked(self, b: CircuitBreaker,
                                   now: float) -> bool:
        if self.error_rate_threshold is None:
            return False
        recent = [ok for ts, ok in b.outcomes
                  if now - ts <= self.error_rate_window_s]
        if len(recent) < self.error_rate_min_volume:
            return False
        failures = sum(1 for ok in recent if not ok)
        return failures / len(recent) >= self.error_rate_threshold

    def mark_failed(self, instance: str) -> None:  # _FailureDetector compat
        self.record_failure(instance)

    def mark_healthy(self, instance: str) -> None:  # _FailureDetector compat
        self.record_success(instance)

    # -- observability -------------------------------------------------------
    def state(self, instance: str) -> str:
        with self._lock:
            b = self._breakers.get(instance)
            if b is None:
                return CLOSED
            if b.state == OPEN and time.monotonic() >= b.open_until:
                return HALF_OPEN  # next allow() will hand out the probe
            return b.state

    def down_count(self) -> int:
        """Servers with an OPEN breaker still inside cooldown (the
        serversUnhealthy gauge)."""
        now = time.monotonic()
        with self._lock:
            return sum(1 for b in self._breakers.values()
                       if b.state == OPEN and b.open_until > now)

    def snapshot(self) -> dict:
        """Breaker table for GET /debug/servers."""
        out = {}
        with self._lock:
            items = list(self._breakers.items())
        for inst, b in items:
            out[inst] = {
                "state": self.state(inst),
                "consecutiveFailures": b.consecutive_failures,
                "cooldownS": round(b.cooldown_s, 3),
                "timesOpened": b.opened_count,
            }
        return out
