"""Broker role: route, scatter, gather, reduce.

Reference analogue: pinot-broker — BaseSingleStageBrokerRequestHandler
.handleRequest:279 (parse → optimize → route → scatter → gather → reduce),
BrokerRoutingManager (routing tables from external view), replica selection
(BalancedInstanceSelector), ConnectionFailureDetector (exponential-backoff
unhealthy marking), TimeBoundaryManager:56 (hybrid OFFLINE+REALTIME split),
and BrokerReduceService.reduceOnDataTable:61.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import uuid
import zlib
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from typing import Optional

from ..engine.combine import combine_aggregation, combine_group_by, combine_selection
from ..engine.aggregation import semantics_for
from ..engine.reduce import BrokerReducer
from ..engine.perf_ledger import ALERTS, PERF_LEDGER
from ..engine.results import (
    AggIntermediate,
    BrokerResponse,
    GroupByIntermediate,
    SelectionIntermediate,
)
from ..query.context import QueryContext
from ..query.expressions import ExpressionContext
from ..query.filter import FilterContext, Predicate, PredicateType
from ..query.parser.sql import SqlParseError, parse_sql
from ..spi import faults
from ..spi.data_types import Schema
from ..spi.metrics import BROKER_METRICS, BrokerMeter, BrokerTimer
from ..cache.results import BrokerResultCache, lineage_epoch, \
    result_cache_enabled
from .breaker import CircuitBreakerTable
from .controller import ONLINE, raw_table_name, table_name_with_type
from .datatable import DataTableError
from .datatable import decode as decode_datatable
from .quota import (
    AdmissionController,
    AdmissionRejectedError,
    QueryQuotaExceededError,
    QueryQuotaManager,
    ResponseStore,
)
from .store import PropertyStore
from .transport import RemoteError, RpcClient, TransportError


class _StaleRoutingError(Exception):
    """A routed segment vanished mid-query (atomic lineage swap committed);
    the scatter must restart on a fresh routing snapshot."""


class _ServerStats:
    """Per-server latency EWMA + in-flight count for adaptive selection
    (reference: pinot-broker/.../routing/adaptiveserverselector/ —
    NumInFlightReqSelector / LatencySelector hybrid)."""

    __slots__ = ("ewma_ms", "inflight")

    def __init__(self):
        self.ewma_ms = 0.0
        self.inflight = 0

    def score(self) -> float:
        return self.ewma_ms * (1.0 + self.inflight)

    def record(self, latency_ms: float, alpha: float = 0.3) -> None:
        self.ewma_ms = (alpha * latency_ms + (1 - alpha) * self.ewma_ms
                        if self.ewma_ms else latency_ms)


class _QueryBudget:
    """Per-query deadline + failure-degradation context, threaded through
    scatter/gather so every RPC is stamped with the REMAINING time budget
    and every degradation decision (failover exhausted, deadline expired)
    can consult allowPartialResults."""

    __slots__ = ("deadline", "query_id", "partial_ok", "_shard_seq")

    def __init__(self, timeout_ms: float, partial_ok: bool):
        self.deadline = time.monotonic() + timeout_ms / 1000.0
        self.query_id = uuid.uuid4().hex[:12]
        self.partial_ok = partial_ok
        self._shard_seq = itertools.count()

    def remaining_s(self) -> float:
        return self.deadline - time.monotonic()

    def next_shard_id(self) -> str:
        """One id per scatter RPC (``<query_id>:<n>``): a hedged duplicate
        can be cancelled individually without killing the sibling shards,
        while a broadcast cancel kills the whole ``<query_id>`` prefix."""
        return f"{self.query_id}:{next(self._shard_seq)}"


class Broker:
    def __init__(self, store: PropertyStore, num_scatter_threads: int = 8,
                 adaptive_selection: bool = True,
                 allow_partial_default: Optional[bool] = None,
                 scatter_retries: Optional[int] = None,
                 hedge_ms: Optional[float] = None,
                 hedge_quantile: Optional[float] = None,
                 broker_id: Optional[str] = None):
        self.store = store
        self.broker_id = broker_id or f"Broker_{uuid.uuid4().hex[:8]}"
        # brokers are store CLIENTS (never in /LIVEINSTANCES — the MSE
        # worker placement enumerates that), so breaker/load state reaches
        # the controller's health rollup via /BROKERSTATE beacons instead
        # of a scrape. Publication is opt-in (PINOT_TPU_BROKER_STATE_S > 0)
        # and rate-limited to one store write per interval — the query
        # thread's common case stays a single monotonic comparison.
        self._state_publish_s = float(os.environ.get(
            "PINOT_TPU_BROKER_STATE_S", 0.0))
        self._state_published_at = 0.0
        # per-server circuit breakers drive both replica selection and the
        # serversUnhealthy gauge; kept under the historical attribute name
        # too (is_healthy/mark_failed/mark_healthy are API-compatible)
        self.breakers = CircuitBreakerTable()
        self.failure_detector = self.breakers
        # broker-level default for graceful degradation; per-query
        # SET allowPartialResults=... always wins
        if allow_partial_default is None:
            allow_partial_default = os.environ.get(
                "PINOT_TPU_ALLOW_PARTIAL", "").lower() in ("1", "true", "on")
        self.allow_partial_default = allow_partial_default
        # default end-to-end budget when the query carries no timeoutMs
        self.default_timeout_ms = float(os.environ.get(
            "PINOT_TPU_BROKER_TIMEOUT_MS", 60000))
        # replica retry: how many re-scatter rounds a failed segment gets
        # before the broker degrades (partial) or fails the query
        if scatter_retries is None:
            scatter_retries = int(os.environ.get(
                "PINOT_TPU_SCATTER_RETRIES", 2))
        self.max_scatter_retries = max(0, scatter_retries)
        self.backoff_base_s = float(os.environ.get(
            "PINOT_TPU_SCATTER_BACKOFF_MS", 50)) / 1000.0
        self.backoff_cap_s = float(os.environ.get(
            "PINOT_TPU_SCATTER_BACKOFF_CAP_MS", 1000)) / 1000.0
        # hedging is OPT-IN (a fixed PINOT_TPU_HEDGE_MS, or a
        # PINOT_TPU_HEDGE_QUANTILE over the scatterRpcMs histogram): a
        # duplicate RPC changes the cluster's call pattern, which must
        # never happen behind the back of a deterministic fault schedule
        if hedge_ms is None:
            env = os.environ.get("PINOT_TPU_HEDGE_MS")
            hedge_ms = float(env) if env else None
        self.hedge_fixed_ms = hedge_ms
        if hedge_quantile is None:
            env = os.environ.get("PINOT_TPU_HEDGE_QUANTILE")
            hedge_quantile = float(env) if env else 0.0
        self.hedge_quantile = hedge_quantile
        self.hedge_min_samples = 20
        BROKER_METRICS.set_gauge("serversUnhealthy",
                                 self.breakers.down_count)
        # broker-wide admission gate (PINOT_TPU_MAX_INFLIGHT_QUERIES);
        # disabled by default — then admit() is a plain yield
        self.admission = AdmissionController()
        BROKER_METRICS.set_gauge("brokerQueriesInflight",
                                 self.admission.inflight)
        BROKER_METRICS.set_gauge("brokerQueriesQueued", self.admission.queued)
        self.quota = QueryQuotaManager()
        self.response_store = ResponseStore()
        self.adaptive_selection = adaptive_selection
        from .querylog import QueryLogger
        from .tracestore import TraceStore
        from .workload import WorkloadTracker

        # flight recorder: retained traces (head-sampled + tail-captured
        # slow/partial/failed) served at GET /debug/traces[/{queryId}];
        # the query logger links its slow entries to retained trace ids
        self.trace_store = TraceStore()
        # supplier gauges: polled only when /metrics snapshots — the
        # query path never pays for them
        BROKER_METRICS.set_gauge("traceStoreTraces",
                                 lambda: self.trace_store.stats()["traces"])
        BROKER_METRICS.set_gauge("traceStoreBytes",
                                 lambda: self.trace_store.stats()["bytes"])
        BROKER_METRICS.set_gauge("ledgerFingerprints",
                                 lambda: len(PERF_LEDGER))
        BROKER_METRICS.set_gauge(
            "exemplarsPinned",
            lambda: self.trace_store.stats()["alertExemplars"])
        BROKER_METRICS.set_gauge(
            "traceStoreEvictions",
            lambda: self.trace_store.stats()["evictions"])
        self.query_logger = QueryLogger(trace_store=self.trace_store)
        # per-query cost accounting → decaying per-table/client rollups
        # (GET /debug/workload); also the admission cost-hint source
        self.workload = WorkloadTracker()
        # full-response cache (cache/results.py): keyed on canonical query
        # fingerprint + table lineage epoch, so any segment upload/replace/
        # delete or realtime commit makes old entries unreachable
        self.result_cache = BrokerResultCache()
        self._server_stats: dict[str, _ServerStats] = {}
        # last successfully computed routing per table: a control-plane
        # outage (store restarting, routing read glitching) must degrade to
        # serving the last external view, not to a dead broker
        self._last_routing: dict[str, dict[str, list[str]]] = {}
        self._clients: dict[str, RpcClient] = {}
        # cold-aware routing hints (tiered storage): (instance, segment) →
        # hint-expiry, learned from cold_segments warming reports; while a
        # hint is live, selection prefers replicas that hold the segment
        # resident, falling back to triggering a warm when none do
        self._cold_hints: dict[tuple, float] = {}
        self._cold_hint_ttl = float(
            os.environ.get("PINOT_TPU_COLD_HINT_TTL_S", "15"))
        self._rr = 0  # round-robin cursor for replica selection
        self._pool = ThreadPoolExecutor(max_workers=num_scatter_threads,
                                        thread_name_prefix="broker-scatter")
        self._lock = threading.Lock()

    # -- health -------------------------------------------------------------
    def is_ready(self) -> bool:
        """Readiness = at least one materialized routing snapshot: before
        the first successful routing read every query would fail routing,
        so orchestrators should not send traffic yet. Serves the REST
        GET /health[/readiness] (liveness is unconditional)."""
        with self._lock:
            if self._last_routing:
                return True
        # no query has warmed routing yet: try to materialize one now so a
        # freshly-started broker over a healthy store turns ready without
        # needing traffic first
        tables = self.store.children("/CONFIGS/TABLE")
        if not tables:
            return True  # nothing to route — vacuously ready
        for nwt in tables:
            try:
                self.routing_table(nwt)
                return True
            except Exception:
                continue
        return False

    def publish_state(self) -> dict:
        """Write this broker's health beacon to /BROKERSTATE/{id} for the
        controller's ClusterHealthChecker (breaker states feed its
        breaker-flap rule). Called opportunistically from the query return
        path when PINOT_TPU_BROKER_STATE_S is set, or directly by
        harnesses (tools/soak.py) and tests."""
        state = {
            "brokerId": self.broker_id,
            "publishedAtMs": int(time.time() * 1000),
            "breakers": self.breakers.snapshot(),
            "inflight": self.admission.inflight(),
            "queued": self.admission.queued(),
            "queryP50Ms": round(BROKER_METRICS.timer_quantile(
                BrokerTimer.QUERY_PROCESSING_TIME_MS, 0.5), 3),
            "queryP99Ms": round(BROKER_METRICS.timer_quantile(
                BrokerTimer.QUERY_PROCESSING_TIME_MS, 0.99), 3),
            "resultCacheHits": BROKER_METRICS.meter_count(
                BrokerMeter.RESULT_CACHE_HITS),
            "resultCacheMisses": BROKER_METRICS.meter_count(
                BrokerMeter.RESULT_CACHE_MISSES),
            # per-table decayed query cost (PR-10 rollups): the rebalancer
            # reads these to spread hot-table segments first
            "tableCostsMs": self.workload.table_costs(),
        }
        self.store.set(f"/BROKERSTATE/{self.broker_id}", state)
        return state

    # -- routing ------------------------------------------------------------
    def routing_table(self, name_with_type: str) -> dict[str, list[str]]:
        """segment → online instances, from the external view (reference:
        BrokerRoutingManager watching ExternalView). A failed routing read
        falls back to the last successful snapshot for the table (brokers
        keep serving through a control-plane outage on the last external
        view); with no snapshot yet the failure propagates."""
        try:
            out = self._routing_table_uncached(name_with_type)
        except Exception:
            with self._lock:
                last = self._last_routing.get(name_with_type)
            if last is None:
                raise
            BROKER_METRICS.add_meter(BrokerMeter.ROUTING_FROM_LAST_VIEW)
            return {seg: list(insts) for seg, insts in last.items()}
        with self._lock:
            self._last_routing[name_with_type] = out
        return out

    def _routing_table_uncached(self, name_with_type: str) -> dict[str, list[str]]:
        from .periodic import hidden_from_lineage

        if faults.ACTIVE:
            faults.FAULTS.fire("broker.route", table=name_with_type)

        # lineage is read BEFORE and AFTER the ideal-state read: if a
        # replacement committed in between (entry state changed/vanished),
        # the ideal snapshot may contain FROM ∪ TO with nothing hidden —
        # re-snapshot instead of double counting. A stable pair of lineage
        # reads brackets the ideal read into one routing generation.
        for _ in range(5):
            lineage_before = self.store.get(f"/LINEAGE/{name_with_type}")
            view = self.store.get(f"/EXTERNALVIEW/{name_with_type}") or {}
            ideal = self.store.get(f"/IDEALSTATES/{name_with_type}") or {}
            live = set(self.store.children("/LIVEINSTANCES"))
            if self.store.get(f"/LINEAGE/{name_with_type}") == lineage_before:
                break
        hidden = hidden_from_lineage(lineage_before)
        out = {}
        for seg in ideal:
            if seg in hidden:
                continue
            insts = [i for i, st in (view.get(seg) or {}).items()
                     if st == ONLINE and i in live]
            out[seg] = sorted(insts)
        return out

    def _client(self, instance: str) -> RpcClient:
        cfg = self.store.get(f"/LIVEINSTANCES/{instance}") or \
            self.store.get(f"/INSTANCECONFIGS/{instance}")
        with self._lock:
            c = self._clients.get(instance)
            # a restarted server re-registers under a new address; a cached
            # client pointing at the old one must not linger — an open
            # breaker can shield it from traffic long enough that the
            # failure-eviction path never fires, and a later query then
            # burns ALL of a shard's replicas on stale connections at once
            if c is not None and cfg is not None and \
                    (c.host, c.port) != (cfg["host"], cfg["port"]):
                self._clients.pop(instance, None)
                c = None
            if c is None:
                if cfg is None:
                    raise TransportError(f"no address for {instance}")
                c = RpcClient(cfg["host"], cfg["port"])
                self._clients[instance] = c
            return c

    def _select_instances(self, routing: dict[str, list[str]],
                          unavailable_sink: Optional[list] = None
                          ) -> dict[str, list[str]]:
        """instance → segments, balanced round-robin over healthy replicas
        (reference: BalancedInstanceSelector). With ``unavailable_sink``
        (partial-results mode), segments with no online replica are
        appended to the sink instead of failing the query."""
        plan: dict[str, list[str]] = {}
        unavailable = []
        with self._lock:
            self._rr += 1
            rr = self._rr
        hinted = bool(self._cold_hints)
        now = time.monotonic() if hinted else 0.0
        for seg, replicas in routing.items():
            # breaker-gated: open breakers are skipped; a half-open breaker
            # admits exactly one probe here. If EVERY replica is tripped the
            # query still goes out (last-resort traffic beats a guaranteed
            # failure — and doubles as extra probing).
            healthy = [i for i in replicas if self.breakers.allow(i)]
            candidates = healthy or replicas
            if hinted:
                # cold-aware routing: prefer a replica NOT recently observed
                # warming this segment; when every replica is cold, fall
                # through and let the pick trigger the warm
                resident = [i for i in candidates
                            if self._cold_hints.get((i, seg), 0.0) <= now]
                candidates = resident or candidates
            if not candidates:
                unavailable.append(seg)
                continue
            if self.adaptive_selection:
                with self._lock:
                    pick = min(candidates, key=lambda i: (
                        self._server_stats.setdefault(i, _ServerStats()).score(),
                        (hash(i) + rr) % 97))
            else:
                pick = candidates[rr % len(candidates)]
            plan.setdefault(pick, []).append(seg)
        if unavailable:
            if unavailable_sink is not None:
                unavailable_sink.extend(unavailable)
            else:
                raise TransportError(
                    f"no online replica for segments {unavailable}")
        return plan

    def _note_cold(self, inst: str, seg: str) -> None:
        """A server reported ``seg`` cold (still warming): route the next
        queries to other replicas for the hint TTL, then forget — the warm
        completes in the background, so the hint must expire."""
        now = time.monotonic()
        with self._lock:
            if len(self._cold_hints) > 4096:
                self._cold_hints = {
                    k: t for k, t in self._cold_hints.items() if t > now}
            self._cold_hints[(inst, seg)] = now + self._cold_hint_ttl

    # -- query --------------------------------------------------------------
    def execute_sql(self, sql: str,
                    segments: Optional[dict] = None) -> BrokerResponse:
        """``segments``: optional {tableNameWithType: [segment, ...]}
        restriction — the connector's segment-parallel scan plane
        (reference: the Spark connector dispatches per-segment reads with
        an explicit searchSegments list). EVERY return path — including
        quota rejections, parse errors, and the MSE route — funnels
        through the query log (reference: QueryLogger logs completions
        AND failures)."""
        t0 = time.perf_counter()
        resp = self._execute_sql_impl(sql, segments)
        if not getattr(resp, "time_used_ms", 0):
            resp.time_used_ms = (time.perf_counter() - t0) * 1000
        # broker-side end-to-end latency histogram — the p50/p95/p99
        # behind the broker's GET /metrics
        from ..spi.metrics import BROKER_METRICS, BrokerTimer

        BROKER_METRICS.update_timer(BrokerTimer.QUERY_PROCESSING_TIME_MS,
                                    resp.time_used_ms)
        table = getattr(resp, "_log_table", "")
        if table:
            from ..spi.metrics import BrokerMeter

            BROKER_METRICS.add_table_meter(table, BrokerMeter.QUERIES)
        # flight-recorder retention BEFORE the query log so slow entries
        # can link the retained trace id they just minted
        self._retain_trace(resp, table)
        self.query_logger.log(sql, resp, table=table)
        self.workload.note_response(sql, resp, table=table)
        self._record_ledger(sql, resp, table)
        if getattr(resp, "trace_sampled", False):
            # the client never asked for this trace: the store and the
            # query log took their copies above — the response ships plain
            resp.trace_info = None
        if self._state_publish_s and time.monotonic() \
                - self._state_published_at >= self._state_publish_s:
            self._state_published_at = time.monotonic()
            try:
                self.publish_state()
            except Exception:
                pass  # a glitching store must not fail the query
        return resp

    def _retain_trace(self, resp: BrokerResponse, table: str) -> None:
        """Flight-recorder retention: every traced completion — head-sampled
        or client-requested — is offered to the broker TraceStore under its
        queryId. Tail-based capture PINS the traces that matter most (slow,
        partial, failed): pinned entries outlive healthy samples when the
        byte budget evicts. Runs before the query log so slow entries link
        the retained id instead of embedding a second copy of the spans."""
        trace_info = getattr(resp, "trace_info", None)
        qid = getattr(resp, "query_id", None)
        if not trace_info or not qid:
            return
        time_ms = getattr(resp, "time_used_ms", 0) or 0
        n_exc = len(getattr(resp, "exceptions", []) or [])
        partial = bool(getattr(resp, "partial_result", False))
        slow = time_ms >= self.query_logger.slow_threshold_ms
        if n_exc:
            reason = "failed"
        elif partial:
            reason = "partial"
        elif slow:
            reason = "slow"
        elif getattr(resp, "trace_sampled", False):
            reason = "sampled"
        else:
            reason = "traced"
        alert_id = getattr(resp, "_alert_id", "") or ""
        try:
            resp.trace_id = self.trace_store.offer(
                qid, trace_info, reason=reason,
                pinned=bool(n_exc or partial or slow or alert_id),
                table=table, time_ms=time_ms, exceptions=n_exc,
                partial=partial, alert_id=alert_id)
            if alert_id:
                # the alert record links back to its pinned exemplars
                ALERTS.note_exemplar(alert_id, resp.trace_id)
        except Exception:
            pass  # retention is best-effort; never fail the query for it

    def _record_ledger(self, sql: str, resp: BrokerResponse,
                       table: str) -> None:
        """Per-plan performance ledger bump (engine/perf_ledger.py): pure
        counter arithmetic over fields the response already carries. The
        key is the plan fingerprint when the result-cache path computed
        one, a crc of the SQL text otherwise — NEVER a fresh
        canonicalization walk (the warm path is perf-guard-pinned to zero
        fingerprint work)."""
        try:
            key = getattr(resp, "_ledger_key", None)
            if key is None:
                key = "sql:%08x" % (zlib.crc32(sql.encode()) & 0xFFFFFFFF)
            crossings = bytes_shuffled = 0
            stages = getattr(resp, "mse_stage_stats", None)
            if stages:
                for st in stages.values():
                    crossings += int(st.get("host_crossings", 0) or 0)
                    bytes_shuffled += int(st.get("shuffled_bytes", 0) or 0)
            PERF_LEDGER.record(
                key, table=table,
                time_ms=getattr(resp, "time_used_ms", 0.0) or 0.0,
                error=bool(getattr(resp, "exceptions", None)),
                partial=bool(getattr(resp, "partial_result", False)),
                dispatches=getattr(resp, "num_device_dispatches", 0) or 0,
                compiles=getattr(resp, "num_compiles", 0) or 0,
                cache_outcome=getattr(resp, "cache_outcome", "") or "",
                seg_cache_hits=getattr(resp, "num_segments_cache_hit", 0)
                or 0,
                seg_cache_misses=getattr(resp, "num_segments_cache_miss", 0)
                or 0,
                coalesced=getattr(resp, "num_coalesced_queries", 0) or 0,
                host_crossings=crossings, bytes_shuffled=bytes_shuffled,
                sql=sql)
        except Exception:
            pass  # the ledger must never fail a query

    def _execute_sql_impl(self, sql: str,
                          segments: Optional[dict]) -> BrokerResponse:
        t0 = time.perf_counter()
        try:
            query = parse_sql(sql)
        except SqlParseError as e:
            # shapes the single-stage grammar rejects (joins, subqueries,
            # set ops) route to the multi-stage dispatcher — the reference's
            # cross-engine fallback at the broker request handler
            resp = self._admitted_mse(sql)
            if resp.exceptions and any(
                    x.startswith(("SqlParseError", "PlanError", "ParseError"))
                    for x in resp.exceptions):
                # neither grammar accepts it: the V1 error names the query's
                # syntax problem; an MSE *execution* failure passes through
                return BrokerResponse(exceptions=[f"SqlParseError: {e}"])
            return resp
        if query.query_options.get("useMultistageEngine") in (True, "true", 1):
            resp = self._admitted_mse(sql)
            resp._log_table = query.table_name
            return resp
        if getattr(query, "explain", False) == "analyze":
            # EXPLAIN ANALYZE: run the scatter for real with tracing armed
            # (caches live) and render ONE merged broker-side tree
            try:
                resp = self._execute_analyze(query, segments, t0)
            except Exception as e:
                resp = BrokerResponse(exceptions=[f"{type(e).__name__}: {e}"])
            resp._log_table = query.table_name
            return resp
        if getattr(query, "explain", False):
            # plan-only: route to ONE server hosting routed segments
            # (reference: EXPLAIN runs the plan maker, never the operators)
            try:
                resp = self._explain(query)
            except Exception as e:
                resp = BrokerResponse(exceptions=[f"{type(e).__name__}: {e}"])
            resp._log_table = query.table_name
            return resp
        try:
            self.quota.acquire(raw_table_name(query.table_name))
        except QueryQuotaExceededError as e:
            resp = BrokerResponse(
                exceptions=[f"QueryQuotaExceededError: {e}"])
            resp._log_table = query.table_name
            return resp
        ck = self._result_cache_key(query, segments)
        if ck is not None:
            cached = self.result_cache.get(ck)
            if cached is not None:
                BROKER_METRICS.add_meter(BrokerMeter.RESULT_CACHE_HITS)
                cached.cache_outcome = "hit"
                cached.time_used_ms = (time.perf_counter() - t0) * 1000
                cached._log_table = query.table_name
                cached._ledger_key = f"fp:{str(ck[0])[:16]}"
                return cached
        # exemplar pinning (engine/perf_ledger.py): ONE attribute read on
        # the disarmed path; when the sentinel armed this plan or table,
        # the claim forces head-sampling and tags the trace with the alert
        exemplar_alert = None
        if PERF_LEDGER.exemplar_armed:
            lkey = f"fp:{str(ck[0])[:16]}" if ck is not None else \
                "sql:%08x" % (zlib.crc32(sql.encode()) & 0xFFFFFFFF)
            exemplar_alert = PERF_LEDGER.claim_exemplar(
                lkey, query.table_name)
        # admission control (load shedding): the budget starts ticking NOW,
        # so time spent queued for a broker slot comes out of the query's
        # own deadline — an overloaded broker sheds with a 429-style
        # rejection instead of stacking unbounded work
        budget = _QueryBudget(self._timeout_ms(query),
                              self._partial_allowed(query))
        try:
            with self.admission.admit(
                    timeout_s=budget.remaining_s(),
                    cost_hint_ms=self.workload.expected_cost_ms(
                        raw_table_name(query.table_name))):
                resp = self._execute(query, only_segments=segments,
                                     budget=budget,
                                     force_trace=bool(exemplar_alert))
        except AdmissionRejectedError as e:
            resp = self._rejected_response(e)
        except Exception as e:
            resp = BrokerResponse(exceptions=[f"{type(e).__name__}: {e}"])
        resp.time_used_ms = (time.perf_counter() - t0) * 1000
        resp._log_table = query.table_name
        resp.cache_outcome = "miss" if ck is not None else "bypass"
        if ck is not None:
            resp._ledger_key = f"fp:{str(ck[0])[:16]}"
        if exemplar_alert:
            resp._alert_id = exemplar_alert
        if ck is not None and not resp.exceptions \
                and not resp.partial_result \
                and resp.result_table is not None:
            BROKER_METRICS.add_meter(BrokerMeter.RESULT_CACHE_MISSES)
            if getattr(resp, "trace_sampled", False) and resp.trace_info:
                # a head-sampled query is cacheable (the CLIENT never asked
                # for a trace) — but the cached copy must be plain, or the
                # next client's hit replays a stale trace
                import copy

                plain = copy.copy(resp)
                plain.trace_info = None
                plain.trace_sampled = False
                self.result_cache.put(ck, plain)
            else:
                self.result_cache.put(ck, resp)
        return resp

    def _execute_analyze(self, query: QueryContext,
                         segments: Optional[dict],
                         t0: float) -> BrokerResponse:
        """EXPLAIN ANALYZE at the broker: consult the result cache first
        (a warm hit renders as a RESULT_CACHE node with zero dispatches),
        otherwise scatter the real query with an analyze-flagged trace and
        render the merged cross-server span tree as the annotated plan."""
        import copy

        from ..engine.explain import analyze_table

        raw = raw_table_name(query.table_name)
        ck = self._result_cache_key(query, segments)
        if ck is not None:
            cached = self.result_cache.get(ck)
            if cached is not None:
                BROKER_METRICS.add_meter(BrokerMeter.RESULT_CACHE_HITS)
                base = copy.copy(cached)
                base.cache_outcome = "hit"
                base.time_used_ms = (time.perf_counter() - t0) * 1000
                out = copy.copy(base)
                out.result_table = analyze_table(
                    base.trace_info or [], base, table_name=raw)
                return out
        sub = copy.copy(query)
        sub.explain = False
        sub.query_options = dict(query.query_options)
        sub.query_options["trace"] = True
        # the analyze marker rides the query to every server so their
        # traces keep the cache tiers live (spi/trace.py analyze flag)
        sub.query_options["analyze"] = True
        budget = _QueryBudget(self._timeout_ms(query),
                              self._partial_allowed(query))
        try:
            with self.admission.admit(
                    timeout_s=budget.remaining_s(),
                    cost_hint_ms=self.workload.expected_cost_ms(raw)):
                resp = self._execute(sub, only_segments=segments,
                                     budget=budget)
        except AdmissionRejectedError as e:
            return self._rejected_response(e)
        resp.time_used_ms = (time.perf_counter() - t0) * 1000
        if resp.exceptions:
            return resp
        resp.cache_outcome = "miss" if ck is not None else "bypass"
        if ck is not None and not resp.partial_result \
                and resp.result_table is not None:
            # cache the PLAIN result (trace scrubbed): the next run — plain
            # or ANALYZE — hits, and ANALYZE then reports cache: hit
            BROKER_METRICS.add_meter(BrokerMeter.RESULT_CACHE_MISSES)
            plain = copy.copy(resp)
            plain.trace_info = None
            self.result_cache.put(ck, plain)
        out = copy.copy(resp)
        out.result_table = analyze_table(resp.trace_info or [], resp,
                                         table_name=raw)
        return out

    def _result_cache_key(self, query: QueryContext,
                          only_segments: Optional[dict]) -> Optional[tuple]:
        """Cacheability decision tree (README "Result caching"): no explicit
        segment restriction, no trace, no SET resultCache=false, no
        non-deterministic functions, and no REALTIME half (a consuming
        snapshot's rows advance without any lineage event). Returns the
        (query_fp, table, lineage epoch) key, or None → bypass."""
        if only_segments is not None or not result_cache_enabled():
            return None
        opt = query.query_options.get("resultCache")
        if opt is not None and str(opt).lower() in ("false", "0", "off"):
            return None
        if query.query_options.get("trace") in (True, "true", 1):
            return None
        text = str(query).lower()
        if "now(" in text or "rand(" in text or "ago(" in text:
            return None
        raw = raw_table_name(query.table_name)
        if self.store.get(
                f"/CONFIGS/TABLE/{table_name_with_type(raw, 'REALTIME')}") \
                is not None:
            return None
        from ..cache.keys import query_fingerprint

        fp = query_fingerprint(query)
        if fp is None:
            return None
        offline = table_name_with_type(raw, "OFFLINE")
        return (fp, offline, lineage_epoch(self.store, offline))

    def execute_sql_stream(self, sql: str):
        """Streaming query: a generator of ResultTable pages (reference:
        the gRPC streaming broker path). Selection queries WITHOUT order-by
        stream one page per server segment as it completes, stopping early
        once LIMIT rows have been emitted; non-streamable shapes
        (aggregation, group-by, order-by) buffer and yield one final page."""
        from ..engine.reduce import BrokerReducer
        from ..engine.results import SelectionIntermediate
        from .controller import raw_table_name as _raw
        from .controller import table_name_with_type as _nwt
        from .datatable import decode

        try:
            query = parse_sql(sql)
        except SqlParseError as e:
            raise ValueError(f"SqlParseError: {e}") from None
        streamable = (not query.is_aggregation_query and not query.is_group_by
                      and not query.distinct
                      and not query.order_by_expressions
                      and not query.offset)  # offset is a global cut, not
        # a per-page one — buffer it
        if not streamable:
            resp = self.execute_sql(sql)
            if resp.exceptions:
                raise RuntimeError("; ".join(resp.exceptions))
            yield resp.result_table
            return

        raw = _raw(query.table_name)
        schema_json = self.store.get(f"/SCHEMAS/{raw}")
        schema = Schema.from_json(schema_json) if schema_json else None
        reducer = BrokerReducer(schema)
        remaining = query.limit
        for ttype in ("OFFLINE", "REALTIME"):
            nwt = _nwt(raw, ttype)
            if self.store.get(f"/CONFIGS/TABLE/{nwt}") is None:
                continue
            routing = self.routing_table(nwt)
            if not routing:
                continue
            plan = self._select_instances(routing)
            sub = _with_filter(query, nwt, None)
            for inst, segs in plan.items():
                stream = self._client(inst).call_stream(
                    {"type": "query_stream", "table": nwt,
                     "segments": segs, "query": sub})
                for blob in stream:
                    combined, _st = decode(blob)
                    if isinstance(combined, SelectionIntermediate) and \
                            not combined.rows:
                        continue
                    page = reducer.reduce(sub, combined)
                    if remaining is not None:
                        page.rows = page.rows[:remaining]
                        remaining -= len(page.rows)
                    if page.rows:
                        yield page
                    if remaining is not None and remaining <= 0:
                        stream.close()  # early termination
                        return

    def execute_sql_mse(self, sql: str) -> BrokerResponse:
        """Multi-stage execution across server processes: plan fragments are
        serialized and dispatched to workers, shuffle blocks cross the TCP
        transport (reference: MultiStageBrokerRequestHandler →
        QueryDispatcher.submitAndReduce)."""
        return self.mse_dispatcher.execute_sql(sql)

    def _admitted_mse(self, sql: str) -> BrokerResponse:
        """MSE dispatch behind the same broker admission gate as the
        single-stage path."""
        try:
            with self.admission.admit(
                    timeout_s=self.default_timeout_ms / 1000.0):
                return self.execute_sql_mse(sql)
        except AdmissionRejectedError as e:
            return self._rejected_response(e)

    def _rejected_response(self, e: Exception) -> BrokerResponse:
        BROKER_METRICS.add_meter(BrokerMeter.QUERIES_REJECTED)
        resp = BrokerResponse(
            exceptions=[f"QueryRejectedError: {e}"])
        resp.query_rejected = True
        return resp

    @property
    def mse_dispatcher(self):
        if not hasattr(self, "_mse_dispatcher"):
            from ..mse.distributed import DistributedMseDispatcher

            self._mse_dispatcher = DistributedMseDispatcher(self)
        return self._mse_dispatcher

    def execute_sql_cursor(self, sql: str, num_rows: int = 1000) -> dict:
        """Spool the full result and return the first page + cursor id
        (reference: getCursor=true query option + /resultStore endpoints).
        Subsequent pages via fetch_cursor()."""
        resp = self.execute_sql(sql)
        if resp.exceptions or resp.result_table is None:
            return {"exceptions": resp.exceptions}
        rt = resp.result_table
        cursor_id = self.response_store.create_cursor(
            rt.schema.column_names, rt.schema.column_types, rt.rows)
        return self.response_store.fetch(cursor_id, 0, num_rows)

    def fetch_cursor(self, cursor_id: str, offset: int,
                     num_rows: int = 1000) -> dict:
        return self.response_store.fetch(cursor_id, offset, num_rows)

    def _explain(self, query: QueryContext) -> BrokerResponse:
        from ..engine.results import DataSchema, ResultTable

        raw = raw_table_name(query.table_name)
        for ttype in ("OFFLINE", "REALTIME"):
            nwt = table_name_with_type(raw, ttype)
            if self.store.get(f"/CONFIGS/TABLE/{nwt}") is None:
                continue
            routing = self.routing_table(nwt)
            if not routing:
                continue
            plan = self._select_instances(routing)
            inst, segs = next(iter(plan.items()))
            out = self._client(inst).call({
                "type": "explain", "table": nwt, "segments": segs,
                "query": query})
            return BrokerResponse(result_table=ResultTable(
                DataSchema(out["columns"], out["types"]), out["rows"]))
        return BrokerResponse(
            exceptions=[f"table {raw} not found or has no routable segments"])

    def _execute(self, query: QueryContext,
                 only_segments: Optional[dict] = None,
                 budget: Optional[_QueryBudget] = None,
                 force_trace: bool = False) -> BrokerResponse:
        raw = raw_table_name(query.table_name)
        offline = table_name_with_type(raw, "OFFLINE")
        realtime = table_name_with_type(raw, "REALTIME")
        has_offline = self.store.get(f"/CONFIGS/TABLE/{offline}") is not None
        has_realtime = self.store.get(f"/CONFIGS/TABLE/{realtime}") is not None
        if not has_offline and not has_realtime:
            return BrokerResponse(exceptions=[f"table {raw} not found"])

        halves: list[tuple[str, Optional[FilterContext]]] = []
        if has_offline and has_realtime:
            boundary = self._time_boundary(offline)
            time_col = (self.store.get(f"/CONFIGS/TABLE/{offline}") or {}).get(
                "timeColumn")
            if boundary is not None and time_col:
                # hybrid split (reference TimeBoundaryManager:56):
                # offline ≤ boundary < realtime
                halves.append((offline, _range_filter(time_col, None, boundary)))
                halves.append((realtime, _range_filter(time_col, boundary, None)))
            else:
                halves.append((offline, None))
                halves.append((realtime, None))
        else:
            halves.append((offline if has_offline else realtime, None))

        schema_json = self.store.get(f"/SCHEMAS/{raw}")
        schema = Schema.from_json(schema_json) if schema_json else None

        if budget is None:
            budget = _QueryBudget(self._timeout_ms(query),
                                  self._partial_allowed(query))

        # trace option: the broker owns the root trace; each server ships
        # its own span list back next to the datatable and they are merged
        # (ids namespaced per instance) into one response trace_info.
        # Flight recorder: with PINOT_TPU_TRACE_SAMPLE set, the broker also
        # head-samples production queries deterministically on the queryId
        # hash — every server strips its ``:<n>`` shard suffix and makes
        # the SAME decision, so sampled queries trace end to end without
        # any option riding the wire. Sampled traces arm analyze=True so
        # the cache tiers stay live (a sampled query must behave exactly
        # like its unsampled twin).
        from ..spi.trace import TRACING, sample_decision, trace_sample_rate

        trace = None
        sampled = False
        if TRACING.active_trace() is None:
            if query.query_options.get("trace") in (True, "true", 1):
                trace = TRACING.start_trace(
                    f"broker:{raw}",
                    analyze=query.query_options.get("analyze") in
                    (True, "true", 1))
            elif force_trace or sample_decision(budget.query_id,
                                                trace_sample_rate()):
                # force_trace: sentinel exemplar pinning — sample this
                # query regardless of the configured head-sampling rate
                sampled = True
                trace = TRACING.start_trace(f"broker:{raw}", analyze=True)
        all_results = []
        stats_sum = {"total_docs": 0, "num_segments_processed": 0,
                     "num_segments_pruned": 0, "num_segments_queried": 0,
                     "num_device_dispatches": 0, "num_compiles": 0,
                     "num_segments_cache_hit": 0,
                     "num_segments_cache_miss": 0,
                     "scatter_retries": 0, "hedged_requests": 0,
                     "hedge_wins": 0, "corrupt_shards_retried": 0,
                     "cold_segments_warming": 0,
                     "num_coalesced_queries": 0, "coalesce_wait_ms": 0.0,
                     "server_traces": [],
                     "servers_queried": [], "servers_responded": [],
                     "partial_exceptions": []}
        try:
            try:
                # BROKER_SCATTER is the exporter's flow anchor: shard
                # timelines re-base here and scatter flows fan out from it
                with TRACING.scope("BROKER_SCATTER"):
                    for name_with_type, extra_filter in halves:
                        sub = _with_filter(query, name_with_type, extra_filter)
                        results = self._scatter_gather(
                            name_with_type, sub, stats_sum, budget,
                            only_segments=(only_segments or {}).get(
                                name_with_type))
                        all_results.extend(results)
            except TimeoutError:
                # broker abandons the query: best-effort cancel so server
                # device work stops (lands on ResourceAccountant.kill_query)
                BROKER_METRICS.add_meter(BrokerMeter.DEADLINE_EXCEEDED)
                self._broadcast_cancel(budget, stats_sum)
                raise

            with TRACING.scope("BROKER_REDUCE"):
                combined = self._merge(query, all_results)
                result = BrokerReducer(schema).reduce(query, combined)
        finally:
            if trace is not None:
                TRACING.end_trace()
        trace_info = None
        if trace is not None:
            trace_info = trace.to_json()
            # span ids are namespaced per (instance, shard ordinal), not per
            # instance alone: a hedge win lands a second shard on an
            # instance that already answered one, and a bare per-instance
            # prefix would collide both traces' ids — any id-keyed consumer
            # (to_tree, the ANALYZE renderer) then silently drops the
            # winning shard's spans
            shard_ordinal: dict[str, int] = {}
            for inst, server_spans in stats_sum["server_traces"]:
                n = shard_ordinal.get(inst, 0)
                shard_ordinal[inst] = n + 1
                prefix = inst if n == 0 else f"{inst}#{n}"
                for s in server_spans:
                    s = dict(s)
                    s["spanId"] = f"{prefix}:{s['spanId']}"
                    if s.get("parentId") is not None:
                        s["parentId"] = f"{prefix}:{s['parentId']}"
                    else:
                        s["server"] = inst
                    trace_info.append(s)
        queried = sorted(set(stats_sum["servers_queried"]))
        responded = sorted(set(stats_sum["servers_responded"]))
        partial_notes = stats_sum["partial_exceptions"]
        resp = BrokerResponse(
            result_table=result,
            num_docs_scanned=getattr(combined, "num_docs_scanned", 0),
            total_docs=stats_sum["total_docs"],
            num_segments_queried=stats_sum["num_segments_queried"],
            num_segments_processed=stats_sum["num_segments_processed"],
            num_segments_pruned=stats_sum["num_segments_pruned"],
            num_groups_limit_reached=getattr(combined, "groups_trimmed",
                                             False),
            num_device_dispatches=stats_sum["num_device_dispatches"],
            num_compiles=stats_sum["num_compiles"],
            num_segments_cache_hit=stats_sum["num_segments_cache_hit"],
            num_segments_cache_miss=stats_sum["num_segments_cache_miss"],
            num_servers_queried=len(queried),
            num_servers_responded=len(responded),
            num_scatter_retries=stats_sum["scatter_retries"],
            num_hedged_requests=stats_sum["hedged_requests"],
            num_hedge_wins=stats_sum["hedge_wins"],
            num_corrupt_shards_retried=stats_sum["corrupt_shards_retried"],
            cold_segments_warming=stats_sum.get("cold_segments_warming", 0),
            num_coalesced_queries=stats_sum.get("num_coalesced_queries", 0),
            coalesce_wait_ms=stats_sum.get("coalesce_wait_ms", 0.0),
        )
        if partial_notes:
            # degraded gather: merged answer of the responding servers only,
            # flagged partial with per-server exceptions — and never cached
            resp.partial_result = True
            resp.exceptions = list(partial_notes)
            BROKER_METRICS.add_meter(BrokerMeter.PARTIAL_RESULTS)
            if any(n.startswith("TimeoutError") for n in partial_notes):
                BROKER_METRICS.add_meter(BrokerMeter.DEADLINE_EXCEEDED)
                self._broadcast_cancel(budget, stats_sum)
        if trace_info is not None:
            resp.trace_info = trace_info
        # retention metadata the execute_sql funnel consumes: the queryId
        # is the /debug/traces/{id} handle, trace_sampled marks traces the
        # client never asked for (stripped from the response after the
        # trace store and query log take their copies)
        resp.query_id = budget.query_id
        resp.trace_sampled = sampled
        return resp

    def _timeout_ms(self, query: QueryContext) -> float:
        opt = query.query_options.get("timeoutMs")
        if opt is not None:
            try:
                return float(opt)
            except (TypeError, ValueError):
                pass
        return self.default_timeout_ms

    def _partial_allowed(self, query: QueryContext) -> bool:
        opt = query.query_options.get("allowPartialResults")
        if opt is None:
            return self.allow_partial_default
        return opt in (True, 1) or str(opt).lower() in ("true", "1", "on")

    def _broadcast_cancel(self, budget: _QueryBudget, stats_sum: dict) -> None:
        """Best-effort cancel to every server that was sent a shard of the
        query but never responded; the server resolves the queryId PREFIX
        through the accountant (each scatter RPC carries its own
        ``<query_id>:<n>`` shard id) so the segment loop's check_cancel
        stops device work — and a shard that hasn't registered yet dies on
        arrival via the accountant's tombstone."""
        pending = set(stats_sum.get("servers_queried", [])) - \
            set(stats_sum.get("servers_responded", []))
        for inst in pending:
            try:
                self._client(inst).call(
                    {"type": "cancel", "queryId": budget.query_id,
                     "prefix": True, "reason": "broker deadline exceeded"},
                    retry=False, timeout=2.0)
            except Exception:
                pass  # cancel is advisory; the server may already be gone

    def _cancel_shard(self, inst: str, shard_qid: str) -> None:
        """Cancel one hedging loser, off-thread (the loser's server is
        usually the slow or dead one — never block the winner on it).

        Uses a DEDICATED connection, never the pooled per-instance client:
        the pool serializes calls per target, and the connection's lock is
        held right now by the losing RPC itself — a pooled cancel would
        queue behind the very call it is trying to kill and only land
        after the loser finished on its own."""
        cfg = self.store.get(f"/LIVEINSTANCES/{inst}") or \
            self.store.get(f"/INSTANCECONFIGS/{inst}") or {}
        host, port = cfg.get("host"), cfg.get("port")
        if port is None:
            return  # instance gone; nothing left to cancel

        def _send():
            client = RpcClient(host, port, timeout=2.0, connect_timeout=2.0)
            try:
                client.call(
                    {"type": "cancel", "queryId": shard_qid,
                     "reason": "hedged duplicate superseded"},
                    retry=False, timeout=2.0)
            except Exception:
                pass
            finally:
                client.close()
        threading.Thread(target=_send, daemon=True,
                         name="broker-hedge-cancel").start()

    def _scatter_gather(self, table: str, query: QueryContext, stats_sum: dict,
                        budget: _QueryBudget,
                        only_segments: Optional[list] = None):
        """Scatter with a bounded whole-query restart: when a routed segment
        vanishes from routing mid-flight (an atomic lineage swap committed —
        merge/compaction replaced it), per-segment retry would double-count
        or under-count, so re-snapshot the routing and re-run (reference:
        broker re-executing on stale routing generation). Per-attempt
        accounting (incl. the partial/server lists) lives in ``local`` and
        merges only on success, so a discarded stale attempt can't leak
        failure records into the final response."""
        last: Exception | None = None
        for _ in range(3):
            local = {"total_docs": 0, "num_segments_processed": 0,
                     "num_segments_pruned": 0, "num_segments_queried": 0,
                     "num_device_dispatches": 0, "num_compiles": 0,
                     "num_segments_cache_hit": 0,
                     "num_segments_cache_miss": 0,
                     "scatter_retries": 0, "hedged_requests": 0,
                     "hedge_wins": 0, "corrupt_shards_retried": 0,
                     "cold_segments_warming": 0,
                     "num_coalesced_queries": 0, "coalesce_wait_ms": 0.0,
                     "server_traces": [],
                     "servers_queried": [], "servers_responded": [],
                     "partial_exceptions": []}
            try:
                results = self._scatter_gather_once(
                    table, query, local, budget, only_segments)
            except _StaleRoutingError as e:
                last = e
                continue
            except TimeoutError:
                # the deadline path needs the attempt's servers_queried /
                # servers_responded so _broadcast_cancel knows which
                # servers still hold a shard — merge just those before the
                # discard (counters stay attempt-local as on any failure)
                for k in ("servers_queried", "servers_responded"):
                    stats_sum.setdefault(k, []).extend(local[k])
                raise
            for k, v in local.items():
                if isinstance(v, list):
                    stats_sum.setdefault(k, []).extend(v)
                else:
                    stats_sum[k] += v
            return results
        raise RuntimeError(f"routing kept changing mid-query: {last}")

    def _scatter_gather_once(self, table: str, query: QueryContext,
                             stats_sum: dict, budget: _QueryBudget,
                             only_segments: Optional[list] = None):
        routing = self.routing_table(table)
        if only_segments is not None:
            missing = [s for s in only_segments if s not in routing]
            if missing:
                # an explicitly requested segment (connector per-segment
                # scan) that is not routable must fail loudly — silently
                # skipping it would drop its rows from the scan
                raise RuntimeError(
                    f"requested segments not routable: {missing}")
            routing = {s: routing[s] for s in only_segments}
        if not routing:
            return []
        stats_sum["num_segments_queried"] += len(routing)
        unavailable: list[str] = []
        plan = self._select_instances(
            routing,
            unavailable_sink=unavailable if budget.partial_ok else None)
        if unavailable:
            stats_sum["partial_exceptions"].append(
                f"TransportError: no online replica for segments "
                f"{sorted(unavailable)}")

        def degrade(inst, segs, err) -> None:
            stats_sum["partial_exceptions"].append(
                f"{type(err).__name__}: {inst}: "
                f"segments {sorted(segs)}: {err}")

        results, failed = self._dispatch_round(
            plan, table, query, budget, stats_sum, routing)

        # replica-aware retry (self-healing): a shard that failed at the
        # connection level re-scatters to replicas not yet tried, under
        # capped exponential backoff, for as long as the query's own budget
        # allows. Terminal errors never retry: a RemoteError would fail the
        # same way on any replica, a TimeoutError means the budget is gone
        # — both degrade (partial mode) or fail the query now.
        tried: dict[str, set] = {}
        for inst, segs in plan.items():
            for s in segs:
                tried.setdefault(s, set()).add(inst)
        attempt = 0
        while failed:
            retry_routing: dict[str, list[str]] = {}
            last_err: dict[str, tuple[str, Exception]] = {}
            for inst, segs, err in failed:
                if isinstance(err, (TimeoutError, RemoteError)):
                    if not budget.partial_ok:
                        raise err
                    degrade(inst, segs, err)
                    continue
                for s in segs:
                    replicas = [i for i in routing.get(s, [])
                                if i not in tried.get(s, ())]
                    if replicas:
                        retry_routing[s] = replicas
                        last_err[s] = (inst, err)
                    else:
                        exhausted = TransportError(
                            f"segment {s} unreachable on all replicas: "
                            f"{err}")
                        if not budget.partial_ok:
                            raise exhausted
                        degrade(inst, [s], exhausted)
            if not retry_routing:
                break
            if attempt >= self.max_scatter_retries:
                for s, (inst, err) in last_err.items():
                    exhausted = TransportError(
                        f"segment {s}: scatter retries exhausted "
                        f"({self.max_scatter_retries}): {err}")
                    if not budget.partial_ok:
                        raise exhausted
                    degrade(inst, [s], exhausted)
                break
            self._backoff_sleep(attempt, budget)
            retry_plan = self._select_instances(retry_routing)
            stats_sum["scatter_retries"] += len(retry_plan)
            BROKER_METRICS.add_meter(BrokerMeter.SCATTER_RETRIES,
                                     len(retry_plan))
            for inst, segs in retry_plan.items():
                for s in segs:
                    tried.setdefault(s, set()).add(inst)
            more, failed = self._dispatch_round(
                retry_plan, table, query, budget, stats_sum, retry_routing)
            results.extend(more)
            attempt += 1
        combineds = []
        cold_segs: set = set()

        def absorb(inst, r, missing_sink):
            # decoded at the scatter edge (_call_one) where a bad payload
            # can still fail over; hitting the fallback means the result
            # bypassed that gate somehow
            combined, st = r["decoded"] if "decoded" in r \
                else decode_datatable(r["datatable"])
            combineds.append(combined)
            stats_sum["servers_responded"].append(inst)
            if r.get("trace"):
                stats_sum.setdefault("server_traces", []).append(
                    (inst, r["trace"]))
            stats_sum["total_docs"] += st["total_docs"]
            stats_sum["num_segments_processed"] += st["num_segments_processed"]
            stats_sum["num_segments_pruned"] += st["num_segments_pruned"]
            for k in ("num_device_dispatches", "num_compiles",
                      "num_segments_cache_hit", "num_segments_cache_miss",
                      "num_coalesced_queries", "coalesce_wait_ms"):
                stats_sum[k] += st.get(k, 0)
            # tiered storage: segments the server reported COLD (still
            # warming) ride the missing-segments retry below, but are
            # counted/hinted so routing and the response reflect the warm
            for s in st.get("cold_segments", []):
                cold_segs.add(s)
                self._note_cold(inst, s)
            stats_sum["cold_segments_warming"] = \
                stats_sum.get("cold_segments_warming", 0) \
                + len(st.get("cold_segments", []))
            for s in st.get("missing_segments", []):
                missing_sink.setdefault(inst, []).append(s)

        missing_by_inst: dict[str, list[str]] = {}
        for inst, r in results:
            absorb(inst, r, missing_by_inst)
        if missing_by_inst:
            # a routed segment the server no longer hosts — normal during a
            # rebalance (the routing snapshot raced the unload): refresh the
            # routing and retry those segments on their CURRENT replicas,
            # excluding the instance that just reported them gone
            # (reference: broker retry with updated routing)
            fresh = self.routing_table(table)
            sub_routing = {}
            for inst, segs in missing_by_inst.items():
                for s in segs:
                    if s not in fresh:
                        # the segment left the routing table entirely: a
                        # lineage swap (or drop) committed under us — the
                        # whole snapshot is stale, restart the query
                        # (always, even in partial mode: a restart gives a
                        # FULL answer on the new routing generation)
                        raise _StaleRoutingError(
                            f"segment {s} replaced mid-query")
                    replicas = [i for i in fresh[s] if i != inst]
                    if not replicas and s in cold_segs:
                        # the only replica is still WARMING the segment:
                        # retry the same instance — its background warm
                        # (bounded by our remaining budget server-side)
                        # usually lands before the retry does
                        replicas = [inst]
                    if not replicas:
                        if budget.partial_ok:
                            degrade(inst, [s], RuntimeError(
                                "no remaining replicas"))
                            continue
                        raise RuntimeError(
                            f"segment {s} has no remaining replicas")
                    sub_routing[s] = replicas
            still_missing: dict[str, list[str]] = {}
            more, failed = self._dispatch_round(
                self._select_instances(sub_routing), table, query, budget,
                stats_sum, sub_routing)
            for inst, out in more:
                absorb(inst, out, still_missing)
            if failed:
                # the retry pass keeps replica failover too: a transient
                # connection failure re-routes once more to the segment's
                # remaining replicas before the query fails — unless the
                # error is terminal (deadline / deterministic remote error)
                fo_routing = {}
                for inst, segs, err in failed:
                    if isinstance(err, (TimeoutError, RemoteError)):
                        if not budget.partial_ok:
                            raise err
                        degrade(inst, segs, err)
                        continue
                    for s in segs:
                        replicas = [i for i in sub_routing.get(s, [])
                                    if i != inst]
                        if not replicas:
                            if budget.partial_ok:
                                degrade(inst, [s], TransportError(
                                    "unreachable on retry"))
                                continue
                            raise TransportError(
                                f"segment {s} unreachable on retry")
                        fo_routing[s] = replicas
                fo_more, fo_failed = self._dispatch_round(
                    self._select_instances(fo_routing), table, query,
                    budget, stats_sum, fo_routing)
                for inst, out in fo_more:
                    absorb(inst, out, still_missing)
                for inst, segs, err in fo_failed:
                    if budget.partial_ok:
                        degrade(inst, segs, err)
                        continue
                    raise TransportError(
                        f"segments {segs} unreachable on retry")
            if still_missing:
                # twice-missing → genuinely gone; fail loudly (or degrade)
                # rather than silently dropping rows
                gone = sorted(s for v in still_missing.values() for s in v)
                if budget.partial_ok:
                    stats_sum["partial_exceptions"].append(
                        f"RuntimeError: servers missing routed segments "
                        f"after retry: {gone}")
                else:
                    raise RuntimeError(
                        f"servers missing routed segments after retry: "
                        f"{gone}")
        return combineds

    def _call_one(self, inst: str, segs: list, table: str,
                  query: QueryContext, budget: _QueryBudget,
                  stats_sum: dict, shard_qid: str):
        """One scatter RPC shard. Returns ``(inst, segs, out, err)`` —
        never raises — and feeds the circuit breaker and the scatterRpcMs
        histogram (which drives the hedge delay)."""
        remaining = budget.remaining_s()
        if remaining <= 0:
            return inst, segs, None, TimeoutError(
                f"deadline exceeded before dispatch to {inst}")
        # deadline propagation: the server clamps its scheduler wait
        # and per-segment loop to this remaining budget; the socket
        # timeout gets a little slack so the server-side timeout
        # (which carries a real error message) fires first
        request = {"type": "query", "table": table, "segments": segs,
                   "query": query, "deadlineMs": remaining * 1000.0,
                   "queryId": shard_qid}
        stats_sum["servers_queried"].append(inst)
        with self._lock:
            stats = self._server_stats.setdefault(inst, _ServerStats())
            stats.inflight += 1
        t0 = time.perf_counter()
        try:
            out = self._client(inst).call(request,
                                          timeout=remaining + 2.0)
            blob = out.get("datatable") if isinstance(out, dict) else None
            if blob is not None:
                try:
                    # decode at the edge: the crc trailer catches damaged
                    # bytes, the structural parse catches truncation and
                    # framing garbage — the gather stage reuses this
                    # result, so the happy path decodes exactly once
                    out["decoded"] = decode_datatable(blob)
                except DataTableError as e:
                    # wire-integrity failure: the RPC completed but the
                    # payload doesn't hold together. Reclassified as a
                    # connection-level failure so the replica-retry
                    # machinery re-dispatches the shard — the corrupt
                    # response never enters the merge, and the final
                    # answer stays exact.
                    BROKER_METRICS.add_meter(
                        BrokerMeter.DATATABLE_CORRUPTIONS)
                    self.breakers.record_failure(inst)
                    with self._lock:
                        stats_sum["corrupt_shards_retried"] += 1
                        self._clients.pop(inst, None)
                    return inst, segs, None, TransportError(
                        f"corrupt DataTable from {inst}: {e}")
            self.breakers.record_success(inst)
            latency_ms = (time.perf_counter() - t0) * 1000
            BROKER_METRICS.update_timer(BrokerTimer.SCATTER_RPC_MS,
                                        latency_ms)
            with self._lock:
                stats.record(latency_ms)
            return inst, segs, out, None
        except RemoteError as e:
            # the server is alive — its handler raised. A replica
            # retry would deterministically fail the same way, so no
            # failover and no breaker signal.
            return inst, segs, None, e
        except TransportError as e:
            self.breakers.record_failure(inst)
            with self._lock:
                self._clients.pop(inst, None)
            if time.monotonic() >= budget.deadline:
                # a slow server is indistinguishable from a dead one
                # once the budget is gone — classify as deadline, not
                # failover fodder
                return inst, segs, None, TimeoutError(
                    f"deadline exceeded waiting on {inst}: {e}")
            return inst, segs, None, e
        finally:
            with self._lock:
                stats.inflight -= 1

    def _dispatch_round(self, plan: dict, table: str, query: QueryContext,
                        budget: _QueryBudget, stats_sum: dict,
                        routing: dict):
        """One scatter round with hedging: each (instance, segments) shard
        goes out as one RPC; a shard in flight past the hedge delay (fixed
        PINOT_TPU_HEDGE_MS, or the scatterRpcMs histogram quantile) gets a
        duplicate on another full-coverage replica. First complete
        response wins — exactly one response per shard enters the merge,
        in shard submission order, so a hedged run stays bit-identical to
        an unhedged one — and the loser is cancelled by its shard id.

        Returns ``(results, failed)``: ``results`` = [(instance, out)] in
        plan order, ``failed`` = [(instance, segments, error)], one entry
        per shard whose every attempt failed."""
        hedge_delay = self._hedge_delay_s()
        shards = []
        pending: dict = {}  # future → (shard, inst, shard_qid)
        for idx, (inst, segs) in enumerate(plan.items()):
            sh = {"idx": idx, "primary": inst, "segs": segs,
                  "t0": time.monotonic(), "resolved": False,
                  "hedged": hedge_delay is None, "outstanding": 1,
                  "errors": []}
            qid = budget.next_shard_id()
            fut = self._pool.submit(self._call_one, inst, segs, table,
                                    query, budget, stats_sum, qid)
            pending[fut] = (sh, inst, qid)
            shards.append(sh)
        out_by_idx: dict[int, tuple] = {}
        failed: list[tuple[str, list, Exception]] = []
        while pending:
            timeout = None
            if hedge_delay is not None:
                due = [sh["t0"] + hedge_delay for sh in shards
                       if not sh["resolved"] and not sh["hedged"]]
                if due:
                    timeout = max(0.0, min(due) - time.monotonic())
            done, _ = wait(set(pending), timeout=timeout,
                           return_when=FIRST_COMPLETED)
            if not done:
                # a straggler crossed the hedge delay: duplicate its RPC
                # onto another replica (at most one hedge per shard)
                now = time.monotonic()
                for sh in shards:
                    if sh["resolved"] or sh["hedged"] or \
                            now - sh["t0"] < hedge_delay:
                        continue
                    sh["hedged"] = True
                    target = self._hedge_target(sh, routing)
                    if target is None or budget.remaining_s() <= 0:
                        continue
                    BROKER_METRICS.add_meter(BrokerMeter.HEDGED_REQUESTS)
                    stats_sum["hedged_requests"] += 1
                    qid = budget.next_shard_id()
                    fut = self._pool.submit(
                        self._call_one, target, sh["segs"], table, query,
                        budget, stats_sum, qid)
                    pending[fut] = (sh, target, qid)
                    sh["outstanding"] += 1
                continue
            for fut in done:
                entry = pending.pop(fut, None)
                if entry is None:
                    # its shard already resolved in this same batch and the
                    # winner's cleanup dropped this duplicate
                    continue
                sh, inst, qid = entry
                sh["outstanding"] -= 1
                if sh["resolved"]:
                    continue  # a duplicate of an already-won shard
                _i, _s, out, err = fut.result()
                if err is None:
                    sh["resolved"] = True
                    if inst != sh["primary"]:
                        BROKER_METRICS.add_meter(BrokerMeter.HEDGE_WINS)
                        stats_sum["hedge_wins"] += 1
                    out_by_idx[sh["idx"]] = (inst, out)
                    # first-complete-wins: drop + cancel the outstanding
                    # duplicate so it stops burning server/device time
                    for ofut, (osh, oinst, oqid) in list(pending.items()):
                        if osh is sh:
                            del pending[ofut]
                            self._cancel_shard(oinst, oqid)
                else:
                    sh["errors"].append((inst, err))
                    if sh["outstanding"] == 0:
                        # every attempt failed: classify on the primary's
                        # error when it is among them (the hedge may have
                        # failed differently)
                        pick = next((p for p in sh["errors"]
                                     if p[0] == sh["primary"]),
                                    sh["errors"][0])
                        failed.append((pick[0], sh["segs"], pick[1]))
        results = [out_by_idx[i] for i in sorted(out_by_idx)]
        return results, failed

    def _hedge_delay_s(self) -> Optional[float]:
        """Straggler threshold before a duplicate RPC goes out. A fixed
        PINOT_TPU_HEDGE_MS wins ("0" disables); otherwise the configured
        quantile of the scatterRpcMs histogram, once it has enough samples
        to mean something. None = hedging off (the default)."""
        if self.hedge_fixed_ms is not None:
            return self.hedge_fixed_ms / 1000.0 \
                if self.hedge_fixed_ms > 0 else None
        if self.hedge_quantile <= 0:
            return None
        count, _total = BROKER_METRICS.timer_stats(BrokerTimer.SCATTER_RPC_MS)
        if count < self.hedge_min_samples:
            return None
        q_ms = BROKER_METRICS.timer_quantile(BrokerTimer.SCATTER_RPC_MS,
                                             self.hedge_quantile)
        return max(q_ms / 1000.0, 0.001)

    def _hedge_target(self, sh: dict, routing: dict) -> Optional[str]:
        """Another replica hosting EVERY segment of the straggling shard
        (never the primary, breaker permitting); None when the shard has
        no full-coverage alternative."""
        candidates: Optional[set] = None
        for s in sh["segs"]:
            replicas = set(routing.get(s, ()))
            candidates = replicas if candidates is None \
                else candidates & replicas
        picks = [i for i in (candidates or ())
                 if i != sh["primary"] and self.breakers.allow(i)]
        if not picks:
            return None
        with self._lock:
            return min(picks, key=lambda i: (
                self._server_stats.setdefault(i, _ServerStats()).score(),
                i))

    def _backoff_sleep(self, attempt: int, budget: _QueryBudget) -> None:
        """Capped exponential backoff before a retry round, never past the
        remaining budget. Jitter is deterministic (hashed from query id +
        attempt) so fault-schedule tests replay identically while
        concurrent queries still decorrelate."""
        delay = min(self.backoff_base_s * (2 ** attempt), self.backoff_cap_s)
        frac = zlib.crc32(f"{budget.query_id}:{attempt}".encode()) % 1000
        delay *= 0.5 + frac / 2000.0  # jitter in [0.5, 1.0)
        remaining = budget.remaining_s()
        if delay > 0 and remaining > 0:
            time.sleep(min(delay, remaining))

    def server_health(self) -> dict:
        """Breaker + adaptive-selection state per server, for
        GET /debug/servers."""
        breakers = self.breakers.snapshot()
        with self._lock:
            stats = {i: {"ewmaLatencyMs": round(s.ewma_ms, 3),
                         "inflight": s.inflight}
                     for i, s in self._server_stats.items()}
        out = {}
        for inst in sorted(set(breakers) | set(stats)):
            entry = dict(breakers.get(inst) or {
                "state": "closed", "consecutiveFailures": 0,
                "cooldownS": self.breakers.base_cooldown_s,
                "timesOpened": 0})
            entry.update(stats.get(inst, {}))
            out[inst] = entry
        return out

    def _merge(self, query: QueryContext, per_server: list):
        semantics = [semantics_for(a) for a in query.aggregations]
        groupish = [r for r in per_server if isinstance(r, GroupByIntermediate)]
        aggish = [r for r in per_server if isinstance(r, AggIntermediate)]
        selish = [r for r in per_server if isinstance(r, SelectionIntermediate)]
        if groupish:
            return combine_group_by(groupish, semantics)
        if aggish:
            return combine_aggregation(aggish, semantics)
        if selish:
            return combine_selection(selish)
        if query.is_aggregation_query and not query.is_group_by and not query.distinct:
            return AggIntermediate([])
        if query.is_group_by or query.distinct or query.is_aggregation_query:
            return GroupByIntermediate({})
        return SelectionIntermediate(
            [e.identifier for e in query.select_expressions if e.is_identifier], [])

    # -- hybrid time boundary ----------------------------------------------
    def _time_boundary(self, offline_table: str) -> Optional[int]:
        """max(endTimeMs) - 1 across offline segments (reference
        TimeBoundaryManager subtracts one time unit so the boundary instant
        itself is served from REALTIME: offline ≤ boundary, realtime >)."""
        best = None
        for seg in self.store.children(f"/SEGMENTS/{offline_table}"):
            meta = self.store.get(f"/SEGMENTS/{offline_table}/{seg}") or {}
            end = meta.get("endTimeMs")
            if end is not None:
                best = end if best is None else max(best, end)
        return None if best is None else best - 1


def _range_filter(column: str, gt: Optional[int], lte: Optional[int]) -> FilterContext:
    """time > gt AND time <= lte (None = unbounded)."""
    pred = Predicate(
        PredicateType.RANGE, ExpressionContext.for_identifier(column),
        lower=gt, lower_inclusive=False, upper=lte, upper_inclusive=True)
    return FilterContext.pred(pred)


def _with_filter(query: QueryContext, table: str,
                 extra: Optional[FilterContext]) -> QueryContext:
    import copy

    if extra is None:
        q = copy.copy(query)
        q.table_name = table
        return q
    q = copy.deepcopy(query)
    q.table_name = table
    q.filter = extra if q.filter is None else FilterContext.and_(q.filter, extra)
    return q
