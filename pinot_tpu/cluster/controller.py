"""Cluster controller: table/segment lifecycle + assignment + rebalance.

Reference analogue: PinotHelixResourceManager (pinot-controller/.../helix/
core/PinotHelixResourceManager.java, 4.6K LoC — create/delete tables, add
segments, ideal-state updates, instance management), segment assignment
strategies (.../helix/core/assignment/segment/BaseSegmentAssignment.java),
TableRebalancer (.../helix/core/rebalance/TableRebalancer.java) and
RetentionManager (.../helix/core/retention/).

State layout in the property store (ZK-analogue paths):
  /CONFIGS/TABLE/{tableNameWithType}   table config JSON
  /SCHEMAS/{rawName}                   schema JSON
  /IDEALSTATES/{tableNameWithType}     {segment: {instance: state}}
  /EXTERNALVIEW/{tableNameWithType}    same shape, written by servers
  /LIVEINSTANCES/{instanceId}          ephemeral {host, port}
  /INSTANCECONFIGS/{instanceId}        {host, port, tags}
  /SEGMENTS/{tableNameWithType}/{seg}  segment metadata (location, docs, time range)
"""

from __future__ import annotations

import time
from typing import Optional

from .store import PropertyStore

ONLINE = "ONLINE"
OFFLINE = "OFFLINE"
CONSUMING = "CONSUMING"


def table_name_with_type(name: str, table_type: str = "OFFLINE") -> str:
    if name.endswith("_OFFLINE") or name.endswith("_REALTIME"):
        return name
    return f"{name}_{table_type}"


def raw_table_name(name_with_type: str) -> str:
    for suffix in ("_OFFLINE", "_REALTIME"):
        if name_with_type.endswith(suffix):
            return name_with_type[: -len(suffix)]
    return name_with_type


class ClusterController:
    def __init__(self, store: PropertyStore):
        self.store = store

    # -- instances ---------------------------------------------------------
    def list_instances(self, tag: Optional[str] = None) -> list[str]:
        out = []
        for inst in self.store.children("/INSTANCECONFIGS"):
            cfg = self.store.get(f"/INSTANCECONFIGS/{inst}") or {}
            if tag is None or tag in cfg.get("tags", []):
                out.append(inst)
        return out

    def live_instances(self) -> list[str]:
        return self.store.children("/LIVEINSTANCES")

    # -- schemas / tables ---------------------------------------------------
    def add_schema(self, schema_json: dict) -> None:
        self.store.set(f"/SCHEMAS/{schema_json['schemaName']}", schema_json)

    def create_table(self, table_config: dict) -> str:
        """table_config needs at least tableName; optional tableType
        (OFFLINE default), replication (1), serverTag, timeColumn,
        retentionDays."""
        name = table_name_with_type(table_config["tableName"],
                                    table_config.get("tableType", "OFFLINE"))
        table_config = dict(table_config, tableNameWithType=name)
        self.store.set(f"/CONFIGS/TABLE/{name}", table_config)
        if self.store.get(f"/IDEALSTATES/{name}") is None:
            self.store.set(f"/IDEALSTATES/{name}", {})
        return name

    def drop_table(self, name_with_type: str) -> None:
        for seg in self.store.children(f"/SEGMENTS/{name_with_type}"):
            self.store.delete(f"/SEGMENTS/{name_with_type}/{seg}")
        self.store.delete(f"/IDEALSTATES/{name_with_type}")
        self.store.delete(f"/CONFIGS/TABLE/{name_with_type}")

    def table_config(self, name_with_type: str) -> Optional[dict]:
        return self.store.get(f"/CONFIGS/TABLE/{name_with_type}")

    # -- segments -----------------------------------------------------------
    def add_segment(self, name_with_type: str, segment_name: str,
                    metadata: dict) -> list[str]:
        """metadata: {location: dir path (deep-store address), numDocs,
        startTimeMs?, endTimeMs?, crc?}. Assigns replicas and updates the
        ideal state; servers converge and load. Returns assigned instances."""
        cfg = self.table_config(name_with_type)
        if cfg is None:
            raise KeyError(f"table {name_with_type} not found")
        metadata = dict(metadata, segmentName=segment_name,
                        pushTimeMs=int(time.time() * 1000))
        self.store.set(f"/SEGMENTS/{name_with_type}/{segment_name}", metadata)
        assigned = self._assign_segment(cfg)
        state = CONSUMING if metadata.get("consuming") else ONLINE

        def upd(ideal):
            ideal = ideal or {}
            ideal[segment_name] = {inst: state for inst in assigned}
            return ideal

        self.store.update(f"/IDEALSTATES/{name_with_type}", upd)
        return assigned

    def drop_segment(self, name_with_type: str, segment_name: str) -> None:
        def upd(ideal):
            ideal = ideal or {}
            ideal.pop(segment_name, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{name_with_type}", upd)
        self.store.delete(f"/SEGMENTS/{name_with_type}/{segment_name}")

    def segment_metadata(self, name_with_type: str, segment_name: str) -> Optional[dict]:
        return self.store.get(f"/SEGMENTS/{name_with_type}/{segment_name}")

    # -- assignment ---------------------------------------------------------
    def _assign_segment(self, cfg: dict) -> list[str]:
        """Balanced assignment: pick the `replication` least-loaded eligible
        live instances (reference: BalancedNumSegmentAssignmentStrategy)."""
        replication = int(cfg.get("replication", 1))
        tag = cfg.get("serverTag")
        candidates = [i for i in self.list_instances(tag)
                      if i in set(self.live_instances())]
        if len(candidates) < replication:
            raise RuntimeError(
                f"not enough live servers: need {replication}, have {candidates}")
        load = {i: 0 for i in candidates}
        name = cfg["tableNameWithType"]
        ideal = self.store.get(f"/IDEALSTATES/{name}") or {}
        for seg_map in ideal.values():
            for inst in seg_map:
                if inst in load:
                    load[inst] += 1
        return sorted(candidates, key=lambda i: (load[i], i))[:replication]

    # -- rebalance ----------------------------------------------------------
    def rebalance(self, name_with_type: str, dry_run: bool = False) -> dict:
        """Recompute a balanced target assignment with minimal movement and
        write it to the ideal state (reference: TableRebalancer — target
        computed then applied; servers converge; min-available-replica
        stepping is not needed since the store update is atomic)."""
        cfg = self.table_config(name_with_type)
        if cfg is None:
            raise KeyError(name_with_type)
        replication = int(cfg.get("replication", 1))
        candidates = sorted(set(self.list_instances(cfg.get("serverTag")))
                            & set(self.live_instances()))
        if len(candidates) < replication:
            raise RuntimeError("not enough live servers to rebalance")
        ideal = self.store.get(f"/IDEALSTATES/{name_with_type}") or {}
        load = {i: 0 for i in candidates}
        target: dict[str, dict] = {}
        moves = 0
        for seg in sorted(ideal):
            keep = [i for i in ideal[seg] if i in candidates][:replication]
            target[seg] = {i: ideal[seg][i] for i in keep}
            for i in keep:
                load[i] += 1
        for seg in sorted(ideal):
            while len(target[seg]) < replication:
                pick = min((i for i in candidates if i not in target[seg]),
                           key=lambda i: (load[i], i))
                target[seg][pick] = ONLINE
                load[pick] += 1
                moves += 1
        # level loads: move replicas from the most- to the least-loaded host
        # until spread ≤ 1 (balanced target, minimal movement)
        for _ in range(len(ideal) * replication):
            hi = max(candidates, key=lambda i: (load[i], i))
            lo = min(candidates, key=lambda i: (load[i], i))
            if load[hi] - load[lo] <= 1:
                break
            movable = next((s for s in sorted(ideal)
                            if hi in target[s] and lo not in target[s]), None)
            if movable is None:
                break
            target[movable][lo] = target[movable].pop(hi)
            load[hi] -= 1
            load[lo] += 1
            moves += 1
        result = {"table": name_with_type, "moves": moves, "target": target}
        if not dry_run:
            self.store.set(f"/IDEALSTATES/{name_with_type}", target)
        return result

    # -- retention ----------------------------------------------------------
    def run_retention(self, now_ms: Optional[int] = None) -> list[str]:
        """Drop segments past the table's retentionDays (reference:
        RetentionManager periodic task)."""
        now_ms = now_ms or int(time.time() * 1000)
        dropped = []
        for table in self.store.children("/CONFIGS/TABLE"):
            cfg = self.table_config(table) or {}
            days = cfg.get("retentionDays")
            if not days:
                continue
            cutoff = now_ms - int(days) * 86_400_000
            for seg in self.store.children(f"/SEGMENTS/{table}"):
                meta = self.segment_metadata(table, seg) or {}
                end = meta.get("endTimeMs")
                if end is not None and end < cutoff:
                    self.drop_segment(table, seg)
                    dropped.append(f"{table}/{seg}")
        return dropped
