"""Cluster controller: table/segment lifecycle + assignment + rebalance.

Reference analogue: PinotHelixResourceManager (pinot-controller/.../helix/
core/PinotHelixResourceManager.java, 4.6K LoC — create/delete tables, add
segments, ideal-state updates, instance management), segment assignment
strategies (.../helix/core/assignment/segment/BaseSegmentAssignment.java),
TableRebalancer (.../helix/core/rebalance/TableRebalancer.java) and
RetentionManager (.../helix/core/retention/).

State layout in the property store (ZK-analogue paths):
  /CONFIGS/TABLE/{tableNameWithType}   table config JSON
  /SCHEMAS/{rawName}                   schema JSON
  /IDEALSTATES/{tableNameWithType}     {segment: {instance: state}}
  /EXTERNALVIEW/{tableNameWithType}    same shape, written by servers
  /LIVEINSTANCES/{instanceId}          ephemeral {host, port}
  /INSTANCECONFIGS/{instanceId}        {host, port, tags}
  /SEGMENTS/{tableNameWithType}/{seg}  segment metadata (location, docs, time range)
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ..spi.metrics import CONTROLLER_METRICS, ControllerMeter
from .leader import LeadControllerManager
from .store import PropertyStore

ONLINE = "ONLINE"
OFFLINE = "OFFLINE"
CONSUMING = "CONSUMING"
# external-view-only state: the replica failed integrity verification and
# is quarantined — never advertised ONLINE, excluded from broker routing
# (reference: Helix ERROR state on a failed state transition)
ERROR = "ERROR"


def table_name_with_type(name: str, table_type: str = "OFFLINE") -> str:
    if name.endswith("_OFFLINE") or name.endswith("_REALTIME"):
        return name
    return f"{name}_{table_type}"


def raw_table_name(name_with_type: str) -> str:
    for suffix in ("_OFFLINE", "_REALTIME"):
        if name_with_type.endswith(suffix):
            return name_with_type[: -len(suffix)]
    return name_with_type


class ClusterController:
    """``instance_id=None`` (the default) keeps the legacy single-
    controller mode: no election, every helper available. With an
    ``instance_id`` the controller joins the leader election
    (cluster/leader.py) and hosts the realtime SegmentCompletionManager
    only while it leads — the Helix arrangement where exactly one
    controller runs periodic tasks and owns segment completion."""

    def __init__(self, store: PropertyStore,
                 instance_id: Optional[str] = None,
                 completion_config: Optional[dict] = None):
        self.store = store
        self.instance_id = instance_id
        self.completion_config = completion_config or {}
        self._completion = None
        self._completion_lock = threading.Lock()
        self.leader: Optional[LeadControllerManager] = None
        if instance_id is not None:
            self.leader = LeadControllerManager(
                store, instance_id, on_change=self._on_leadership)
            self.leader.start()

    # -- leadership / completion hosting ------------------------------------
    def _on_leadership(self, is_leader: bool) -> None:
        CONTROLLER_METRICS.add_meter(ControllerMeter.LEADER_CHANGES)
        if not is_leader:
            # drop the hosted completion manager: its in-memory FSMs belong
            # to the seat, not the process. The next leader starts clean —
            # replicas re-poll, the lease model re-elects, and the durable
            # DONE record keeps already-committed segments idempotent.
            with self._completion_lock:
                self._completion = None

    def is_leader(self) -> bool:
        return self.leader is None or self.leader.is_leader

    def completion_manager(self):
        """The leader-hosted SegmentCompletionManager; None while this
        controller is not the leader (callers hold and retry)."""
        if not self.is_leader():
            return None
        with self._completion_lock:
            if self._completion is None:
                from ..realtime.completion import SegmentCompletionManager

                self._completion = SegmentCompletionManager(
                    self.store, **self.completion_config)
            return self._completion

    def stop(self) -> None:
        """Graceful shutdown: resign leadership (atomic delete_if) and drop
        hosted state. Crash-death is modeled by ``leader.disconnect()`` +
        ``store.expire_session`` instead."""
        if self.leader is not None:
            self.leader.stop()
        with self._completion_lock:
            self._completion = None

    # -- instances ---------------------------------------------------------
    def list_instances(self, tag: Optional[str] = None) -> list[str]:
        out = []
        for inst in self.store.children("/INSTANCECONFIGS"):
            cfg = self.store.get(f"/INSTANCECONFIGS/{inst}") or {}
            if tag is None or tag in cfg.get("tags", []):
                out.append(inst)
        return out

    def live_instances(self) -> list[str]:
        return self.store.children("/LIVEINSTANCES")

    def server_instances(self, tag: Optional[str] = None) -> list[str]:
        """Segment-hosting candidates: registered instances that are
        servers. Minions/brokers register with an explicit non-SERVER type
        and must never be assigned segments (reference: Helix instance
        tags — segments go to server-tenant-tagged instances only)."""
        out = []
        for inst in self.list_instances(tag):
            cfg = self.store.get(f"/INSTANCECONFIGS/{inst}") or {}
            if cfg.get("type", "SERVER") == "SERVER":
                out.append(inst)
        return out

    # -- schemas / tables ---------------------------------------------------
    def add_schema(self, schema_json: dict) -> None:
        self.store.set(f"/SCHEMAS/{schema_json['schemaName']}", schema_json)

    def create_table(self, table_config: dict) -> str:
        """table_config needs at least tableName; optional tableType
        (OFFLINE default), replication (1), serverTag, timeColumn,
        retentionDays."""
        name = table_name_with_type(table_config["tableName"],
                                    table_config.get("tableType", "OFFLINE"))
        table_config = dict(table_config, tableNameWithType=name)
        self.store.set(f"/CONFIGS/TABLE/{name}", table_config)
        if self.store.get(f"/IDEALSTATES/{name}") is None:
            self.store.set(f"/IDEALSTATES/{name}", {})
        return name

    def drop_table(self, name_with_type: str) -> None:
        for seg in self.store.children(f"/SEGMENTS/{name_with_type}"):
            self.store.delete(f"/SEGMENTS/{name_with_type}/{seg}")
        self.store.delete(f"/IDEALSTATES/{name_with_type}")
        self.store.delete(f"/CONFIGS/TABLE/{name_with_type}")

    def table_config(self, name_with_type: str) -> Optional[dict]:
        return self.store.get(f"/CONFIGS/TABLE/{name_with_type}")

    # -- segments -----------------------------------------------------------
    def add_segment(self, name_with_type: str, segment_name: str,
                    metadata: dict) -> list[str]:
        """metadata: {location: dir path (deep-store address), numDocs,
        startTimeMs?, endTimeMs?, crc?}. Assigns replicas and updates the
        ideal state; servers converge and load. Returns assigned instances."""
        cfg = self.table_config(name_with_type)
        if cfg is None:
            raise KeyError(f"table {name_with_type} not found")
        metadata = dict(metadata, segmentName=segment_name,
                        pushTimeMs=int(time.time() * 1000))
        self.store.set(f"/SEGMENTS/{name_with_type}/{segment_name}", metadata)
        assigned = self._assign_segment(cfg, metadata)
        state = CONSUMING if metadata.get("consuming") else ONLINE

        def upd(ideal):
            ideal = ideal or {}
            ideal[segment_name] = {inst: state for inst in assigned}
            return ideal

        self.store.update(f"/IDEALSTATES/{name_with_type}", upd)
        # lineage epoch bump (cache/results.py): every upload/refresh —
        # including minion refresh/merge tasks, which land here — makes
        # broker result-cache entries for this table unreachable
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, name_with_type)
        return assigned

    def drop_segment(self, name_with_type: str, segment_name: str) -> None:
        def upd(ideal):
            ideal = ideal or {}
            ideal.pop(segment_name, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{name_with_type}", upd)
        self.store.delete(f"/SEGMENTS/{name_with_type}/{segment_name}")
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, name_with_type)

    def segment_metadata(self, name_with_type: str, segment_name: str) -> Optional[dict]:
        return self.store.get(f"/SEGMENTS/{name_with_type}/{segment_name}")

    # -- instance partitions (replica groups) --------------------------------
    def configure_instance_partitions(self, name_with_type: str,
                                      num_replica_groups: int,
                                      instances_per_group: Optional[int] = None,
                                      num_partitions: Optional[int] = None) -> dict:
        """Partition the table's eligible instances into replica groups
        (reference: InstanceAssignmentDriver +
        InstanceReplicaGroupPartitionSelector — each replica of a segment
        lands in a DISTINCT group, so one group can serve a full copy of
        the table and queries fan out within a single group). Selection is
        deterministic (sorted instances, round-robin into groups) so
        re-running after membership changes moves as little as possible."""
        cfg = self.table_config(name_with_type)
        if cfg is None:
            raise KeyError(name_with_type)
        candidates = sorted(set(self.server_instances(cfg.get("serverTag")))
                            & set(self.live_instances()))
        per_group = instances_per_group or len(candidates) // num_replica_groups
        need = num_replica_groups * per_group
        if per_group < 1 or len(candidates) < need:
            raise RuntimeError(
                f"need {num_replica_groups}x{per_group} instances, "
                f"have {candidates}")
        # sticky re-run: instances keep their previous group when still
        # eligible, so new capacity fills gaps instead of reshuffling
        # whole groups (and the follow-up rebalance moves the minimum)
        prev = (self.instance_partitions(name_with_type)
                or {}).get("replicaGroups", [])
        eligible = set(candidates)
        groups: list[list] = []
        taken: set = set()
        for g in range(num_replica_groups):
            kept = [i for i in (prev[g] if g < len(prev) else [])
                    if i in eligible and i not in taken][:per_group]
            groups.append(kept)
            taken.update(kept)
        pool = [i for i in candidates if i not in taken]
        for g in range(num_replica_groups):
            while len(groups[g]) < per_group:
                groups[g].append(pool.pop(0))
        record = {"replicaGroups": groups}
        if num_partitions:
            record["numPartitions"] = int(num_partitions)
        self.store.set(f"/INSTANCEPARTITIONS/{name_with_type}", record)
        return record

    def instance_partitions(self, name_with_type: str) -> Optional[dict]:
        return self.store.get(f"/INSTANCEPARTITIONS/{name_with_type}")

    @staticmethod
    def _segment_partition_id(metadata: Optional[dict]) -> Optional[int]:
        """First stamped partition id on the segment's push metadata."""
        for info in ((metadata or {}).get("partitions") or {}).values():
            parts = info.get("partitions") if isinstance(info, dict) else None
            if parts:
                return int(parts[0])
        return None

    # -- assignment ---------------------------------------------------------
    def _assign_segment(self, cfg: dict,
                        metadata: Optional[dict] = None) -> list[str]:
        """Replica-group assignment when instance partitions are configured
        (one instance from EACH group — partition-stamped segments pin to
        group member p % group_size, reference
        BaseSegmentAssignment.assignSegment replica-group path); otherwise
        balanced least-loaded assignment
        (BalancedNumSegmentAssignmentStrategy)."""
        name = cfg["tableNameWithType"]
        ideal = self.store.get(f"/IDEALSTATES/{name}") or {}
        ip = self.instance_partitions(name)
        if ip:
            live = set(self.live_instances())
            load = {}
            for seg_map in ideal.values():
                for inst in seg_map:
                    load[inst] = load.get(inst, 0) + 1
            pid = self._segment_partition_id(metadata)
            out = []
            for group in ip["replicaGroups"]:
                members = [i for i in group if i in live]
                if not members:
                    raise RuntimeError(f"replica group {group} has no live "
                                       f"members for {name}")
                if pid is not None:
                    out.append(members[pid % len(members)])
                else:
                    out.append(min(members,
                                   key=lambda i: (load.get(i, 0), i)))
            return out
        replication = int(cfg.get("replication", 1))
        tag = cfg.get("serverTag")
        candidates = [i for i in self.server_instances(tag)
                      if i in set(self.live_instances())]
        if len(candidates) < replication:
            raise RuntimeError(
                f"not enough live servers: need {replication}, have {candidates}")
        load = {i: 0 for i in candidates}
        for seg_map in ideal.values():
            for inst in seg_map:
                if inst in load:
                    load[inst] += 1
        return sorted(candidates, key=lambda i: (load[i], i))[:replication]

    # -- rebalance ----------------------------------------------------------
    def _rebalance_target(self, name_with_type: str, cfg: dict,
                          ideal: dict) -> tuple[dict, int]:
        """Minimal-movement balanced target (replica-group aware when
        instance partitions are configured)."""
        ip = self.instance_partitions(name_with_type)
        if ip:
            live = set(self.live_instances())
            target: dict[str, dict] = {}
            moves = 0
            load: dict[str, int] = {}
            for seg in sorted(ideal):
                pid = self._segment_partition_id(
                    self.segment_metadata(name_with_type, seg))
                current = set(ideal[seg])
                chosen = []
                for group in ip["replicaGroups"]:
                    members = [i for i in group if i in live]
                    if not members:
                        raise RuntimeError(
                            f"replica group {group} has no live members")
                    if pid is not None:
                        pick = members[pid % len(members)]
                    else:
                        # keep the current in-group replica when possible
                        keep = [i for i in members if i in current]
                        pick = keep[0] if keep else min(
                            members, key=lambda i: (load.get(i, 0), i))
                    chosen.append(pick)
                    load[pick] = load.get(pick, 0) + 1
                moves += len(set(chosen) - current)
                # preserve the segment's state (a moved CONSUMING replica
                # must re-enter as CONSUMING, not as a deep-store load)
                state = CONSUMING if CONSUMING in ideal[seg].values() else ONLINE
                target[seg] = {i: state for i in chosen}
            return target, moves

        replication = int(cfg.get("replication", 1))
        candidates = sorted(set(self.server_instances(cfg.get("serverTag")))
                            & set(self.live_instances()))
        if len(candidates) < replication:
            raise RuntimeError("not enough live servers to rebalance")
        load = {i: 0 for i in candidates}
        target = {}
        moves = 0
        for seg in sorted(ideal):
            keep = [i for i in ideal[seg] if i in candidates][:replication]
            target[seg] = {i: ideal[seg][i] for i in keep}
            for i in keep:
                load[i] += 1
        for seg in sorted(ideal):
            state = CONSUMING if CONSUMING in ideal[seg].values() else ONLINE
            while len(target[seg]) < replication:
                pick = min((i for i in candidates if i not in target[seg]),
                           key=lambda i: (load[i], i))
                target[seg][pick] = state
                load[pick] += 1
                moves += 1
        # level loads: move replicas from the most- to the least-loaded host
        # until spread ≤ 1 (balanced target, minimal movement)
        for _ in range(len(ideal) * replication):
            hi = max(candidates, key=lambda i: (load[i], i))
            lo = min(candidates, key=lambda i: (load[i], i))
            if load[hi] - load[lo] <= 1:
                break
            movable = next((s for s in sorted(ideal)
                            if hi in target[s] and lo not in target[s]), None)
            if movable is None:
                break
            target[movable][lo] = target[movable].pop(hi)
            load[hi] -= 1
            load[lo] += 1
            moves += 1
        return target, moves

    def rebalance(self, name_with_type: str, dry_run: bool = False,
                  min_available_replicas: int = 1,
                  ev_timeout_s: float = 30.0,
                  include_consuming: bool = False) -> dict:
        """Safe rebalance (reference: TableRebalancer.rebalance —
        .../helix/core/rebalance/TableRebalancer.java): compute a
        minimal-movement target, then converge the ideal state in TWO
        phases per changed segment — first ADD the target replicas
        (ideal = current ∪ target) and wait for the external view to show
        every target replica ONLINE, only then REMOVE the departing ones.
        A segment's routable replica count therefore never drops below
        min(current availability, min_available_replicas) at any point:
        queries keep succeeding throughout the move. Progress is tracked
        in the store (/REBALANCE/{table}) like the reference's
        ZK-persisted rebalance job context."""
        cfg = self.table_config(name_with_type)
        if cfg is None:
            raise KeyError(name_with_type)
        self._check_upsert_movable(name_with_type, cfg)
        ideal = self.store.get(f"/IDEALSTATES/{name_with_type}") or {}
        # CONSUMING segments sit out by default (reference: rebalance
        # includeConsuming=false) — moving an active consumer means
        # restarting consumption on the new host
        frozen = {} if include_consuming else {
            s: m for s, m in ideal.items() if CONSUMING in m.values()}
        movable = {s: m for s, m in ideal.items() if s not in frozen}
        target, moves = self._rebalance_target(name_with_type, cfg, movable)
        target.update({s: dict(m) for s, m in frozen.items()})
        changed = [s for s in sorted(ideal)
                   if set(target.get(s, {})) != set(ideal[s])]
        result = {"table": name_with_type, "moves": moves, "target": target,
                  "segments_changed": len(changed)}
        if dry_run:
            return result
        return dict(result, **self._apply_target_safely(
            name_with_type, target, changed, min_available_replicas,
            ev_timeout_s, moves))

    def _apply_target_safely(self, name_with_type: str, target: dict,
                             changed: list, min_available_replicas: int,
                             ev_timeout_s: float, moves: int) -> dict:
        """Two-phase ideal-state convergence shared by rebalance and the
        tier relocator: ADD target replicas, wait for the external view,
        then REMOVE departing ones — availability never dips."""
        for seg in target:
            if len(target[seg]) < min_available_replicas:
                raise RuntimeError(
                    f"target for {seg} has {len(target[seg])} replicas "
                    f"< minAvailableReplicas={min_available_replicas}")

        job_id = f"rb_{int(time.time() * 1000)}"
        job_path = f"/REBALANCE/{name_with_type}"
        # the durable rebalance engine (cluster/rebalance.py) journals its
        # move plan at this same path: overwriting an active engine job
        # would orphan its in-flight moves (leaked ADDING replicas, lost
        # crash-resume state) while both engines mutate the ideal state
        existing = self.store.get(job_path)
        if existing and existing.get("status") in ("IN_PROGRESS",
                                                   "ABORTING") \
                and "movePlan" in existing:
            raise RuntimeError(
                f"{name_with_type}: durable rebalance job "
                f"{existing.get('jobId')} is {existing.get('status')}; "
                "wait for the actuator to finish it or abort it first")
        job = {"jobId": job_id, "status": "IN_PROGRESS",
               "segmentsTotal": len(changed), "segmentsDone": 0,
               "moves": moves, "startedMs": int(time.time() * 1000)}
        self.store.set(job_path, job)
        if not changed:
            job["status"] = "DONE"
            self.store.set(job_path, job)
            return {"jobId": job_id, "status": "DONE"}

        # phase 1: additive union — nothing is ever removed here, so
        # availability only grows. Segments deleted concurrently (retention,
        # drop) are SKIPPED, not resurrected: the closures re-read current
        # membership under the store's atomic update.
        def add_union(cur):
            cur = cur or {}
            for seg in changed:
                if seg not in cur:
                    continue
                merged = dict(cur[seg])
                merged.update(target[seg])
                cur[seg] = merged
            return cur

        self.store.update(f"/IDEALSTATES/{name_with_type}", add_union)

        # wait: every ONLINE-target replica of every changed segment shows
        # ONLINE in the external view (CONSUMING replicas never report
        # ONLINE — their handoff is the realtime manager's job, not ours)
        def ev_wait_insts(seg):
            return [i for i, st in target[seg].items() if st == ONLINE]

        deadline = time.time() + ev_timeout_s
        pending = set(changed)
        while pending and time.time() < deadline:
            view = self.store.get(f"/EXTERNALVIEW/{name_with_type}") or {}
            ideal_now = self.store.get(f"/IDEALSTATES/{name_with_type}") or {}
            pending = {s for s in pending if s in ideal_now
                       and any((view.get(s) or {}).get(i) != ONLINE
                               for i in ev_wait_insts(s))}
            if pending:
                time.sleep(0.05)
        if pending:
            job["status"] = "STUCK"
            job["pending"] = sorted(pending)
            self.store.set(job_path, job)
            raise TimeoutError(
                f"rebalance {job_id}: replicas not ONLINE after "
                f"{ev_timeout_s}s: {sorted(pending)}")

        # phase 2: drop the departing replicas (targets are serving)
        def to_target(cur):
            cur = cur or {}
            for seg in changed:
                if seg in cur:
                    cur[seg] = dict(target[seg])
            return cur

        self.store.update(f"/IDEALSTATES/{name_with_type}", to_target)
        job.update(status="DONE", segmentsDone=len(changed),
                   finishedMs=int(time.time() * 1000))
        self.store.set(job_path, job)
        return {"jobId": job_id, "status": "DONE"}

    def rebalance_status(self, name_with_type: str) -> Optional[dict]:
        return self.store.get(f"/REBALANCE/{name_with_type}")

    def _check_upsert_movable(self, name_with_type: str, cfg: dict) -> None:
        """Upsert tables keep a per-server primary-key map: every segment
        of a pk partition must live on the same server or validity planes
        diverge. Moves are only safe under partition-pinned placement, so
        rebalance/relocation REFUSES without instance partitions
        (reference: TableRebalancer requires strict replica groups for
        upsert tables)."""
        mode = ((cfg.get("upsertConfig") or {}).get("mode") or "NONE").upper()
        if mode != "NONE" and not self.instance_partitions(name_with_type):
            raise RuntimeError(
                f"{name_with_type} is an upsert table: configure instance "
                "partitions (partition-pinned placement) before rebalancing "
                "so pk partitions stay colocated")

    # -- tiered storage ------------------------------------------------------
    @staticmethod
    def _parse_age_ms(age: str) -> int:
        """'7d' / '12h' / '30m' / bare ms (reference TierConfig segmentAge
        TimeUtils period format)."""
        age = str(age).strip().lower()
        mult = {"d": 86_400_000, "h": 3_600_000, "m": 60_000, "s": 1000}
        if age and age[-1] in mult:
            return int(float(age[:-1]) * mult[age[-1]])
        return int(age)

    def _tier_for_segment(self, cfg: dict, seg: str, meta: dict,
                          now_ms: int) -> Optional[dict]:
        """First matching tier config wins (reference TierConfigUtils
        ordering). Selectors: 'time' (segment end time older than
        segmentAge) and 'fixed' (explicit segment list)."""
        tiers = cfg.get("tierConfigs") or []
        # oldest-threshold tier first, so a segment past several thresholds
        # lands on the coldest matching tier (reference TierConfigUtils
        # comparator)
        def age_of(t):
            return self._parse_age_ms(t.get("segmentAge",
                                            t.get("segmentAgeMs", "0d")))

        for tier in sorted(tiers, key=lambda t: -age_of(t)):
            sel = str(tier.get("segmentSelectorType", "time")).lower()
            if sel == "fixed":
                if seg in (tier.get("segmentList") or []):
                    return tier
            else:
                end = meta.get("endTimeMs") or meta.get("pushTimeMs")
                if end is not None and now_ms - int(end) >= age_of(tier):
                    return tier
        return None

    def relocate_tiers(self, name_with_type: str, dry_run: bool = False,
                       now_ms: Optional[int] = None,
                       min_available_replicas: int = 1,
                       ev_timeout_s: float = 30.0) -> dict:
        """Move segments whose tier selector matches onto the tier's
        tagged servers (reference: SegmentRelocator — relocate ONLINE
        segments to tiers via a tier-aware rebalance, at most one replica
        unavailable). Uses the same safe two-phase apply as rebalance."""
        cfg = self.table_config(name_with_type)
        if cfg is None:
            raise KeyError(name_with_type)
        if not cfg.get("tierConfigs"):
            return {"table": name_with_type, "moves": 0, "status": "DONE"}
        self._check_upsert_movable(name_with_type, cfg)
        now_ms = now_ms or int(time.time() * 1000)
        replication = int(cfg.get("replication", 1))
        ideal = self.store.get(f"/IDEALSTATES/{name_with_type}") or {}
        live = set(self.live_instances())
        load: dict[str, int] = {}
        for seg_map in ideal.values():
            for inst in seg_map:
                load[inst] = load.get(inst, 0) + 1
        target: dict[str, dict] = {}
        tiers_of: dict[str, Optional[str]] = {}
        moves = 0
        for seg in sorted(ideal):
            if CONSUMING in ideal[seg].values():
                target[seg] = dict(ideal[seg])
                continue
            meta = self.segment_metadata(name_with_type, seg) or {}
            tier = self._tier_for_segment(cfg, seg, meta, now_ms)
            tag = (tier or {}).get("serverTag") or cfg.get("serverTag")
            tiers_of[seg] = (tier or {}).get("name")
            candidates = [i for i in self.server_instances(tag) if i in live]
            if len(candidates) < replication:
                raise RuntimeError(
                    f"tier {tag!r} has {len(candidates)} live servers, "
                    f"need {replication} for {seg}")
            keep = [i for i in ideal[seg] if i in candidates][:replication]
            chosen = list(keep)
            while len(chosen) < replication:
                pick = min((i for i in candidates if i not in chosen),
                           key=lambda i: (load.get(i, 0), i))
                chosen.append(pick)
                load[pick] = load.get(pick, 0) + 1
                moves += 1
            target[seg] = {i: ONLINE for i in chosen}
        changed = [s for s in sorted(ideal)
                   if set(target.get(s, {})) != set(ideal[s])]
        result = {"table": name_with_type, "moves": moves,
                  "segments_changed": len(changed), "tiers": tiers_of,
                  "target": target}
        if dry_run:
            return result
        return dict(result, **self._apply_target_safely(
            name_with_type, target, changed, min_available_replicas,
            ev_timeout_s, moves))

    # -- retention ----------------------------------------------------------
    def run_retention(self, now_ms: Optional[int] = None) -> list[str]:
        """Drop segments past the table's retentionDays (reference:
        RetentionManager periodic task)."""
        now_ms = now_ms or int(time.time() * 1000)
        dropped = []
        for table in self.store.children("/CONFIGS/TABLE"):
            cfg = self.table_config(table) or {}
            days = cfg.get("retentionDays")
            if not days:
                continue
            cutoff = now_ms - int(days) * 86_400_000
            for seg in self.store.children(f"/SEGMENTS/{table}"):
                meta = self.segment_metadata(table, seg) or {}
                end = meta.get("endTimeMs")
                if end is not None and end < cutoff:
                    self.drop_segment(table, seg)
                    dropped.append(f"{table}/{seg}")
        return dropped
