"""Binary DataTable: the server→broker intermediate wire format.

Reference: DataTableImplV4 (pinot-core/.../common/datatable/
DataTableImplV4.java:82) — a versioned binary container carrying the
server's combined intermediate plus a metadata map, with a custom object
SerDe for sketch types (ObjectSerDeUtils type ids). The transport used to
pickle intermediates; this module replaces that with an explicit, versioned
contract: tagged scalars, numpy buffers shipped as dtype+shape+raw bytes,
and a type-id registry for the sketch state objects (utils/sketches.py).
No pickle anywhere — every byte on the query data plane is accounted for.

Layout (little-endian):

    magic  b"PTDT"
    u16    version (=2)
    u8     kind    (GroupArrays | GroupByDict | Agg | Selection)
    u32    metadata JSON length, then the JSON (stats map)
    ...    kind-specific payload built from the tagged value encoding
    u32    crc32 of everything above   ┐ integrity trailer, tagged by the
    4s     b"PTcs" trailer magic       ┘ magic (see below)

The integrity trailer is deliberately NOT a header version bump: the
body is self-delimiting, so pre-trailer readers parse it and never look
at the trailing 8 bytes — a new server's payload stays readable by a
previous-release broker mid-rolling-upgrade (tests/test_upgrade_matrix).
New readers detect the trailer by its magic and verify the crc.
"""

from __future__ import annotations

import dataclasses
import json
import struct
import zlib
from typing import Any

import numpy as np

from ..engine.results import (
    AggIntermediate,
    GroupArrays,
    GroupByIntermediate,
    SelectionIntermediate,
)
from ..utils import sketches

MAGIC = b"PTDT"
# v2: groups_trimmed flag on group intermediates
VERSION = 2
# wire-integrity trailer: little-endian crc32 over everything before it,
# tagged by a trailing magic so old readers (which ignore trailing bytes)
# stay compatible. Checked at broker decode — a corrupt payload surfaces
# as DataTableCorruptionError, which the broker reclassifies as a
# connection-level shard failure so replica retry heals it.
TRAILER_MAGIC = b"PTcs"
_TRAILER = struct.Struct("<I4s")

KIND_GROUP_ARRAYS = 0
KIND_GROUP_DICT = 1
KIND_AGG = 2
KIND_SELECTION = 3

# value tags
_T_NONE, _T_BOOL, _T_INT, _T_FLOAT, _T_STR, _T_BYTES = 0, 1, 2, 3, 4, 5
_T_TUPLE, _T_LIST, _T_SET, _T_DICT, _T_NDARRAY, _T_OBJECT = 6, 7, 8, 9, 10, 11
_T_FROZENSET = 12

# sketch/state object registry (reference ObjectSerDeUtils type ids) —
# numpy-field dataclasses encode generically by field
OBJECT_TYPES: dict[int, type] = {
    1: sketches.HyperLogLog,
    2: sketches.ThetaSketch,
    3: sketches.SmartDistinctSet,
    4: sketches.TDigest,
    5: sketches.ValueHist,
}
_OBJECT_IDS = {cls: tid for tid, cls in OBJECT_TYPES.items()}


class DataTableError(ValueError):
    pass


class DataTableCorruptionError(DataTableError):
    """The payload's crc32 trailer (or framing) does not match its bytes:
    wire/memory corruption, not a version or encoding problem."""


# -- tagged value encoding ----------------------------------------------------

# lifetime count of tagged-value encodes (the row-wise wire path). The
# device-packed exchange ships one PTDP blob instead; its perf guard pins
# this counter's delta to ZERO across a packed send.
_ROW_ENCODES = [0]


def row_encodes() -> int:
    return _ROW_ENCODES[0]


def _w_value(out: bytearray, v: Any) -> None:
    _ROW_ENCODES[0] += 1
    if v is None:
        out.append(_T_NONE)
    elif isinstance(v, (bool, np.bool_)):
        out.append(_T_BOOL)
        out.append(1 if v else 0)
    elif isinstance(v, (int, np.integer)):
        out.append(_T_INT)
        b = str(int(v)).encode()  # arbitrary precision (sumprecision)
        out += struct.pack("<I", len(b)) + b
    elif isinstance(v, (float, np.floating)):
        out.append(_T_FLOAT)
        out += struct.pack("<d", float(v))
    elif isinstance(v, str):
        out.append(_T_STR)
        b = v.encode("utf-8")
        out += struct.pack("<I", len(b)) + b
    elif isinstance(v, (bytes, bytearray)):
        out.append(_T_BYTES)
        out += struct.pack("<I", len(v)) + bytes(v)
    elif isinstance(v, tuple):
        out.append(_T_TUPLE)
        out += struct.pack("<I", len(v))
        for x in v:
            _w_value(out, x)
    elif isinstance(v, list):
        out.append(_T_LIST)
        out += struct.pack("<I", len(v))
        for x in v:
            _w_value(out, x)
    elif isinstance(v, frozenset):
        out.append(_T_FROZENSET)
        out += struct.pack("<I", len(v))
        for x in sorted(v, key=repr):
            _w_value(out, x)
    elif isinstance(v, set):
        out.append(_T_SET)
        out += struct.pack("<I", len(v))
        for x in sorted(v, key=repr):
            _w_value(out, x)
    elif isinstance(v, dict):
        out.append(_T_DICT)
        out += struct.pack("<I", len(v))
        for k, x in v.items():
            _w_value(out, k)
            _w_value(out, x)
    elif isinstance(v, np.ndarray):
        out.append(_T_NDARRAY)
        _w_array(out, v)
    elif type(v) in _OBJECT_IDS:
        out.append(_T_OBJECT)
        out.append(_OBJECT_IDS[type(v)])
        fields = [(f.name, getattr(v, f.name))
                  for f in dataclasses.fields(v)]
        _w_value(out, fields)
    else:
        raise DataTableError(
            f"value of type {type(v).__name__} has no wire encoding; "
            f"register it in cluster/datatable.py OBJECT_TYPES")


def _w_array(out: bytearray, a: np.ndarray) -> None:
    if a.dtype.kind == "O":
        out += struct.pack("<B", 1)  # object array: element-tagged
        out += struct.pack("<I", a.size)
        for x in a.reshape(-1):
            _w_value(out, x)
        _w_value(out, list(a.shape))
        return
    a = np.ascontiguousarray(a)
    out += struct.pack("<B", 0)
    ds = a.dtype.str.encode()
    out += struct.pack("<B", len(ds)) + ds
    out += struct.pack("<B", a.ndim)
    for d in a.shape:
        out += struct.pack("<q", d)
    raw = a.tobytes()
    out += struct.pack("<Q", len(raw)) + raw


class _Reader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def take(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise DataTableCorruptionError("truncated DataTable")
        self.pos += n
        return b

    def u8(self) -> int:
        return self.take(1)[0]

    def unpack(self, fmt: str):
        size = struct.calcsize(fmt)
        return struct.unpack(fmt, self.take(size))


def _r_value(r: _Reader) -> Any:
    tag = r.u8()
    if tag == _T_NONE:
        return None
    if tag == _T_BOOL:
        return bool(r.u8())
    if tag == _T_INT:
        (n,) = r.unpack("<I")
        return int(r.take(n).decode())
    if tag == _T_FLOAT:
        return r.unpack("<d")[0]
    if tag == _T_STR:
        (n,) = r.unpack("<I")
        return r.take(n).decode("utf-8")
    if tag == _T_BYTES:
        (n,) = r.unpack("<I")
        return r.take(n)
    if tag in (_T_TUPLE, _T_LIST, _T_SET, _T_FROZENSET):
        (n,) = r.unpack("<I")
        items = [_r_value(r) for _ in range(n)]
        if tag == _T_TUPLE:
            return tuple(items)
        if tag == _T_SET:
            return set(items)
        if tag == _T_FROZENSET:
            return frozenset(items)
        return items
    if tag == _T_DICT:
        (n,) = r.unpack("<I")
        return {_r_value(r): _r_value(r) for _ in range(n)}
    if tag == _T_NDARRAY:
        return _r_array(r)
    if tag == _T_OBJECT:
        tid = r.u8()
        cls = OBJECT_TYPES.get(tid)
        if cls is None:
            raise DataTableError(f"unknown object type id {tid}")
        fields = _r_value(r)
        obj = cls.__new__(cls)
        for name, value in fields:
            setattr(obj, name, value)
        return obj
    raise DataTableError(f"unknown value tag {tag}")


def _r_array(r: _Reader) -> np.ndarray:
    is_obj = r.unpack("<B")[0]
    if is_obj:
        (size,) = r.unpack("<I")
        items = [_r_value(r) for _ in range(size)]
        shape = _r_value(r)
        a = np.empty(size, dtype=object)
        a[:] = items
        return a.reshape(shape)
    (dlen,) = r.unpack("<B")
    dtype = np.dtype(r.take(dlen).decode())
    (ndim,) = r.unpack("<B")
    shape = tuple(r.unpack("<q")[0] for _ in range(ndim))
    (rawlen,) = r.unpack("<Q")
    return np.frombuffer(r.take(rawlen), dtype=dtype).reshape(shape).copy()


# -- container ----------------------------------------------------------------


def encode(combined, stats: dict) -> bytes:
    out = bytearray(MAGIC)
    out += struct.pack("<H", VERSION)
    if isinstance(combined, GroupArrays):
        kind = KIND_GROUP_ARRAYS
    elif isinstance(combined, GroupByIntermediate):
        kind = KIND_GROUP_DICT
    elif isinstance(combined, AggIntermediate):
        kind = KIND_AGG
    elif isinstance(combined, SelectionIntermediate):
        kind = KIND_SELECTION
    else:
        raise DataTableError(f"cannot encode {type(combined).__name__}")
    out.append(kind)
    meta = json.dumps(stats).encode()
    out += struct.pack("<I", len(meta)) + meta

    if kind == KIND_GROUP_ARRAYS:
        _w_value(out, list(combined.key_cols))
        _w_value(out, [list(c) for c in combined.state_cols])
        _w_value(out, [list(s) for s in combined.vec_specs])
        _w_value(out, list(combined.fin_tags))
        _w_value(out, combined.num_docs_scanned)
        _w_value(out, bool(combined.groups_trimmed))
    elif kind == KIND_GROUP_DICT:
        _w_value(out, combined.groups)
        _w_value(out, combined.num_docs_scanned)
        _w_value(out, bool(combined.groups_trimmed))
    elif kind == KIND_AGG:
        _w_value(out, list(combined.states))
        _w_value(out, combined.num_docs_scanned)
    else:
        _w_value(out, list(combined.columns))
        _w_value(out, list(combined.rows))
        _w_value(out, combined.num_docs_scanned)
    # integrity trailer: crc32 of every byte before it, plus the magic
    # that lets new readers tell trailered from legacy payloads
    out += _TRAILER.pack(zlib.crc32(out), TRAILER_MAGIC)
    return bytes(out)


def _blob_version(blob: bytes) -> int:
    if blob[:4] != MAGIC:
        raise DataTableError("not a PTDT DataTable")
    if len(blob) < 6:
        raise DataTableCorruptionError("truncated DataTable header")
    return struct.unpack_from("<H", blob, 4)[0]


def _has_trailer(blob: bytes) -> bool:
    return len(blob) >= 6 + _TRAILER.size and blob[-4:] == TRAILER_MAGIC


def verify_blob(blob: bytes) -> bool:
    """Cheap wire-integrity check: True iff the blob frames as a PTDT
    payload whose crc32 trailer (when present) matches — legacy payloads
    without the trailer magic pass, they carry no checksum to verify.
    The broker runs a full decode per scatter RPC before counting the
    response; this is the standalone check for everything else."""
    try:
        _blob_version(blob)
    except DataTableError:
        return False
    if not _has_trailer(blob):
        return True
    want, _ = _TRAILER.unpack_from(blob, len(blob) - _TRAILER.size)
    return zlib.crc32(blob[:-_TRAILER.size]) == want


def decode(blob: bytes):
    """→ (combined_intermediate, stats dict)."""
    version = _blob_version(blob)
    if not 1 <= version <= VERSION:
        # a NEWER writer (rolling upgrade, new server → old broker) fails
        # loudly; OLDER versions decode below (old server → new broker —
        # the compatibility-verifier guarantee, compCheck.sh analogue)
        raise DataTableError(f"unsupported DataTable version {version}")
    if _has_trailer(blob):
        want, _ = _TRAILER.unpack_from(blob, len(blob) - _TRAILER.size)
        body = blob[:-_TRAILER.size]
        if zlib.crc32(body) != want:
            raise DataTableCorruptionError(
                f"DataTable checksum mismatch (crc32 "
                f"{zlib.crc32(body):08x} != trailer {want:08x})")
        blob = body
    r = _Reader(blob, 6)
    kind = r.u8()
    (mlen,) = r.unpack("<I")
    stats = json.loads(r.take(mlen).decode())

    if kind == KIND_GROUP_ARRAYS:
        key_cols = _r_value(r)
        state_cols = _r_value(r)
        vec_specs = _r_value(r)
        fin_tags = [_to_tag(t) for t in _r_value(r)]
        nds = _r_value(r)
        # v1 predates the groups_trimmed flag: absent → not trimmed
        trimmed = _r_value(r) if version >= 2 else False
        return GroupArrays(key_cols, [tuple(c) for c in state_cols],
                           [tuple(s) for s in vec_specs], fin_tags,
                           num_docs_scanned=nds,
                           groups_trimmed=trimmed), stats
    if kind == KIND_GROUP_DICT:
        groups = _r_value(r)
        nds = _r_value(r)
        trimmed = _r_value(r) if version >= 2 else False
        return GroupByIntermediate(groups, num_docs_scanned=nds,
                                   groups_trimmed=trimmed), stats
    if kind == KIND_AGG:
        states = _r_value(r)
        nds = _r_value(r)
        return AggIntermediate(states, num_docs_scanned=nds), stats
    if kind == KIND_SELECTION:
        columns = _r_value(r)
        rows = _r_value(r)
        nds = _r_value(r)
        return SelectionIntermediate(columns, rows, num_docs_scanned=nds), stats
    raise DataTableError(f"unknown DataTable kind {kind}")


def _to_tag(t):
    return tuple(t) if isinstance(t, list) else t


# -- device-packed exchange block (PTDP) --------------------------------------
#
# The MSE cross-server shuffle's fast wire format: every numeric column of
# an exchange block is byte-packed into ONE buffer by the device kernel
# (ops/kernels._pack_u8 — the PR-12 mesh combine pack), so the host path
# is memcpy→socket with zero per-row Python encodes. Its own magic keeps
# it loudly incompatible with the row-wise PTDT container: an old reader
# handed a PTDP blob raises DataTableError instead of misparsing.
#
# Layout (little-endian):
#
#     magic  b"PTDP"
#     u16    version (=1)
#     u32    column-header JSON length, then the JSON
#            {"cols": [{"name", "dtype", "shape"}, ...]}
#     u32    crc32 of the packed payload  ┐ integrity, checked before the
#     u64    payload length               ┘ receiver touches the bytes
#     ...    payload: the packed u8 buffer

PACKED_MAGIC = b"PTDP"
PACKED_VERSION = 1


def packable_block(block: dict) -> bool:
    """True iff every column is a 1-D numeric/bool numpy array — the
    shapes the device pack kernel serializes. Object (string) columns keep
    the row-wise path."""
    return bool(block) and all(
        isinstance(v, np.ndarray) and v.ndim == 1 and v.dtype.kind in "biuf"
        for v in block.values())


def is_packed_blob(blob) -> bool:
    return isinstance(blob, (bytes, bytearray, memoryview)) \
        and bytes(blob[:4]) == PACKED_MAGIC


def encode_packed_block(block: dict) -> bytes:
    """Pack an exchange block into one PTDP blob via the on-device byte
    pack. The only host work is the header JSON and one memcpy of the
    packed buffer."""
    import jax
    import jax.numpy as jnp

    from ..ops import kernels

    jax.config.update("jax_enable_x64", True)
    cols, arrs = [], []
    for name, v in block.items():
        a = np.ascontiguousarray(v)
        cols.append({"name": name, "dtype": a.dtype.str,
                     "shape": list(a.shape)})
        arrs.append(jnp.asarray(a))
    payload = np.asarray(kernels._pack_u8(tuple(arrs))).tobytes()
    header = json.dumps({"cols": cols}).encode()
    out = bytearray(PACKED_MAGIC)
    out += struct.pack("<H", PACKED_VERSION)
    out += struct.pack("<I", len(header)) + header
    out += struct.pack("<IQ", zlib.crc32(payload), len(payload))
    out += payload
    return bytes(out)


def decode_packed_block(blob: bytes) -> dict:
    """PTDP blob → column block (zero-copy views over the payload where
    the dtype allows; the receiver's device_put consumes them)."""
    from ..ops import kernels

    if bytes(blob[:4]) != PACKED_MAGIC:
        raise DataTableError("not a PTDP packed block")
    (version,) = struct.unpack_from("<H", blob, 4)
    if version != PACKED_VERSION:
        raise DataTableError(
            f"unsupported packed-block version {version}")
    (hlen,) = struct.unpack_from("<I", blob, 6)
    pos = 10
    header = json.loads(bytes(blob[pos:pos + hlen]).decode())
    pos += hlen
    crc, plen = struct.unpack_from("<IQ", blob, pos)
    pos += 12
    payload = bytes(blob[pos:pos + plen])
    if len(payload) != plen:
        raise DataTableCorruptionError("truncated packed block")
    if zlib.crc32(payload) != crc:
        raise DataTableCorruptionError(
            f"packed block checksum mismatch (crc32 "
            f"{zlib.crc32(payload):08x} != header {crc:08x})")
    flat = np.frombuffer(payload, dtype=np.uint8)
    metas = [(np.dtype(c["dtype"]), tuple(c["shape"]))
             for c in header["cols"]]
    arrs = kernels._split_flat(flat, metas)
    return {c["name"]: a for c, a in zip(header["cols"], arrs)}
