"""Controller lead election over the property store.

Reference: Helix leader election for controllers (LeadControllerManager,
pinot-controller/.../LeadControllerManager.java) — among N controllers,
exactly one leads periodic tasks and the realtime segment completion; when
the leader's session dies, another controller claims leadership.

Here leadership is an ephemeral store entry claimed by compare-and-set:
``/CONTROLLER/LEADER = {"instance": id}`` owned by the instance's session.
``expire_session`` (the ZK session-death analogue) deletes it, the watch
fires, and every standby races one CAS to claim — exactly one wins.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

LEADER_PATH = "/CONTROLLER/LEADER"


class LeadControllerManager:
    def __init__(self, store, instance_id: str,
                 on_change: Optional[Callable[[bool], None]] = None):
        self.store = store
        self.instance_id = instance_id
        self.on_change = on_change
        self._is_leader = False
        self._lock = threading.Lock()
        self._started = False
        self._watched = False

    def start(self) -> None:
        self._started = True
        if not self._watched:
            # watches are persistent: register ONCE even across
            # disconnect/rejoin cycles (re-registering would leak callbacks)
            self.store.watch(LEADER_PATH, self._on_event)
            self._watched = True
        self._try_claim()

    def disconnect(self) -> None:
        """Session loss / process death: stop reacting to events WITHOUT
        resigning — the ephemeral leader entry is reclaimed by the store's
        session expiry, and a real dead process can't respond to watches."""
        self._started = False
        with self._lock:
            self._is_leader = False

    def stop(self) -> None:
        """Graceful resignation (session stays alive, e.g. rolling restart)."""
        self._started = False
        try:
            self.store.unwatch(self._on_event)  # don't pin the elector
            self._watched = False  # a restart must re-register
        except AttributeError:
            pass
        with self._lock:
            was = self._is_leader
            self._is_leader = False
        if was:
            # atomic conditional delete: a plain get→check→delete races
            # with a concurrent session expiry + standby claim — the
            # delete would land on the NEW leader's entry
            self.store.delete_if(
                LEADER_PATH,
                lambda cur: isinstance(cur, dict)
                and cur.get("instance") == self.instance_id)
            self._notify(False)

    @property
    def is_leader(self) -> bool:
        with self._lock:
            return self._is_leader

    # -- internals -----------------------------------------------------------
    def _on_event(self, path: str, value) -> None:
        if not self._started:
            return
        if value is None:
            # leader vacated (session expiry or resignation): race to claim
            self._try_claim()
            return
        holder = value.get("instance")
        with self._lock:
            was = self._is_leader
            self._is_leader = holder == self.instance_id
            now = self._is_leader
        if was != now:
            self._notify(now)

    def _try_claim(self) -> None:
        # atomic exclusive create IS the election: exactly one racer's
        # create_if_absent returns True (ZK ephemeral-create semantics)
        self.store.create_if_absent(
            LEADER_PATH, {"instance": self.instance_id},
            ephemeral_owner=self.instance_id)
        cur = self.store.get(LEADER_PATH)
        if cur is not None:
            self._on_event(LEADER_PATH, cur)

    def _notify(self, is_leader: bool) -> None:
        if self.on_change is not None:
            try:
                self.on_change(is_leader)
            except Exception:
                pass
