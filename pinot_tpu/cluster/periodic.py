"""Controller periodic tasks + segment lineage + tier relocation.

Reference analogues (SURVEY.md §2.6):
- ControllerPeriodicTask framework + the scheduled jobs wired in
  BaseControllerStarter.java:865-896 (RetentionManager,
  SegmentStatusChecker, RebalanceChecker, SegmentRelocator).
- Segment lineage for atomic replacement
  (pinot-controller/.../helix/core/lineage/ — startReplaceSegments/
  endReplaceSegments; brokers exclude in-flight segments from routing).
- Tier configs moving aged segments onto differently-tagged servers
  (SegmentRelocator + TierConfig).
"""

from __future__ import annotations

import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from .controller import ERROR, ONLINE, ClusterController
from .store import PropertyStore


# -- periodic task framework -------------------------------------------------


@dataclass
class PeriodicTask:
    name: str
    interval_s: float
    fn: Callable[[], object]
    last_run: float = 0.0
    runs: int = 0
    last_result: object = None
    last_error: Optional[str] = None


class ControllerPeriodicTaskScheduler:
    """Fixed-interval controller jobs on one background thread (reference:
    ControllerPeriodicTask + PeriodicTaskScheduler)."""

    def __init__(self, tick_s: float = 0.05, leader=None):
        self.tick_s = tick_s
        self.tasks: dict[str, PeriodicTask] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # cluster/leader.py LeadControllerManager: with multiple controllers
        # only the elected leader runs periodic jobs (reference: controller
        # periodic tasks run on the lead controller only)
        self.leader = leader

    def register(self, name: str, interval_s: float, fn: Callable) -> None:
        self.tasks[name] = PeriodicTask(name, interval_s, fn)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="controller-periodic")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10)

    def run_once(self, name: Optional[str] = None) -> dict:
        """Synchronous trigger (tests + admin endpoint; reference:
        /periodictask/run)."""
        out = {}
        for t in self.tasks.values():
            if name is not None and t.name != name:
                continue
            self._run(t)
            out[t.name] = t.last_result if t.last_error is None else t.last_error
        return out

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            if self.leader is not None and not self.leader.is_leader:
                continue  # standby controller: the leader runs the jobs
            now = time.monotonic()
            for t in self.tasks.values():
                if now - t.last_run >= t.interval_s:
                    self._run(t)

    def _run(self, t: PeriodicTask) -> None:
        t.last_run = time.monotonic()
        t.runs += 1
        try:
            t.last_result = t.fn()
            t.last_error = None
        except Exception as e:  # periodic tasks must not kill the loop
            t.last_error = f"{type(e).__name__}: {e}"


# -- built-in controller jobs ------------------------------------------------


class SegmentStatusChecker:
    """Counts segments/replicas per table, flags ideal-vs-external drift;
    writes /STATS/{table} (reference: SegmentStatusChecker metrics:
    nonServingSegments, replicationFromConfig...)."""

    def __init__(self, store: PropertyStore, controller: ClusterController):
        self.store = store
        self.controller = controller

    def __call__(self) -> dict:
        report = {}
        for table in self.store.children("/IDEALSTATES"):
            ideal = self.store.get(f"/IDEALSTATES/{table}") or {}
            view = self.store.get(f"/EXTERNALVIEW/{table}") or {}
            missing = []
            under_replicated = []
            for seg, want in ideal.items():
                have = {i for i, st in (view.get(seg) or {}).items()
                        if st == ONLINE}
                if not have:
                    missing.append(seg)
                elif len(have) < len(want):
                    under_replicated.append(seg)
            stats = {
                "numSegments": len(ideal),
                "nonServingSegments": missing,
                "underReplicatedSegments": under_replicated,
                "checkedAtMs": int(time.time() * 1000),
            }
            self.store.set(f"/STATS/{table}", stats)
            report[table] = stats
        return report


class RebalanceChecker:
    """Re-runs rebalance for tables whose replication is not satisfiable
    from the ideal state (reference: RebalanceChecker retrying stuck
    rebalances)."""

    def __init__(self, controller: ClusterController):
        self.controller = controller

    def __call__(self) -> dict:
        fixed = {}
        live = set(self.controller.live_instances())
        for table in self.controller.store.children("/CONFIGS/TABLE"):
            cfg = self.controller.table_config(table) or {}
            replication = int(cfg.get("replication", 1))
            ideal = self.controller.store.get(f"/IDEALSTATES/{table}") or {}
            broken = any(
                len([i for i in m if i in live]) < replication
                for m in ideal.values())
            if broken and len(live) >= replication:
                fixed[table] = self.controller.rebalance(table)["moves"]
        return fixed


# -- segment lineage (atomic replacement) ------------------------------------


class SegmentLineageManager:
    """start/end/revert replace-segments protocol. While IN_PROGRESS the
    broker must route the FROM set and ignore the TO set; on end the swap
    commits atomically in the ideal state (reference:
    SegmentLineageAccessHelper + PinotHelixResourceManager
    startReplaceSegments/endReplaceSegments)."""

    def __init__(self, store: PropertyStore, controller: ClusterController):
        self.store = store
        self.controller = controller

    def start_replace(self, table: str, from_segments: list[str],
                      to_segments: list[str]) -> str:
        lineage_id = uuid.uuid4().hex[:12]
        # snapshot the FROM generation (push time) so trailing cleanup can
        # tell a replaced segment from one RE-pushed under the same name
        # after the swap — the latter must not be deleted
        from_push = {
            seg: (self.store.get(f"/SEGMENTS/{table}/{seg}") or {}).get(
                "pushTimeMs")
            for seg in from_segments}
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            **(cur or {}),
            lineage_id: {"state": "IN_PROGRESS", "from": from_segments,
                         "to": to_segments, "fromPushMs": from_push,
                         "tsMs": int(time.time() * 1000)}})
        return lineage_id

    def end_replace(self, table: str, lineage_id: str) -> None:
        entry = (self.store.get(f"/LINEAGE/{table}") or {}).get(lineage_id)
        if entry is None or entry["state"] != "IN_PROGRESS":
            raise KeyError(f"lineage {lineage_id} not in progress")
        # the state flip IS the atomic routing switch: brokers route the TO
        # set and hide the FROM set the instant this single key updates.
        # Ideal-state removal is trailing cleanup (servers unload); a crash
        # between flip and cleanup leaves a COMPLETED entry that cleanup()
        # (periodic LineageCleanupTask) finishes idempotently.
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            **(cur or {}), lineage_id: {**entry, "state": "COMPLETED"}})
        # the routing switch just happened — cached broker results built on
        # the FROM set are stale from this instant (cache/results.py)
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, table)
        self._finish_completed(table, lineage_id, entry)

    def _finish_completed(self, table: str, lineage_id: str,
                          entry: dict) -> None:
        """Idempotent trailing cleanup for a COMPLETED entry: drop the FROM
        set from the ideal state and metadata, then delete the entry itself
        so the FROM names become reusable (brokers hide FROM of COMPLETED
        entries only while this cleanup is pending). A FROM name whose
        current metadata no longer matches the generation snapshotted at
        start_replace was re-pushed after the swap and is left alone."""
        from_push = entry.get("fromPushMs", {})
        victims = []
        for seg in entry["from"]:
            meta = self.store.get(f"/SEGMENTS/{table}/{seg}")
            if meta is not None and seg in from_push and \
                    meta.get("pushTimeMs") != from_push[seg]:
                continue  # re-created under the same name — not ours
            victims.append(seg)

        def upd(ideal):
            ideal = ideal or {}
            for seg in victims:
                ideal.pop(seg, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{table}", upd)
        for seg in victims:
            self.store.delete(f"/SEGMENTS/{table}/{seg}")
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            k: v for k, v in (cur or {}).items() if k != lineage_id})

    def revert_replace(self, table: str, lineage_id: str) -> None:
        entry = (self.store.get(f"/LINEAGE/{table}") or {}).get(lineage_id)
        if entry is None or entry["state"] != "IN_PROGRESS":
            raise KeyError(f"lineage {lineage_id} not in progress")
        def upd(ideal):
            ideal = ideal or {}
            for seg in entry["to"]:
                ideal.pop(seg, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{table}", upd)
        for seg in entry["to"]:
            self.store.delete(f"/SEGMENTS/{table}/{seg}")
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            **(cur or {}), lineage_id: {**entry, "state": "REVERTED"}})
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, table)

    def routable_segments(self, table: str, all_segments: set) -> set:
        """Filter by lineage (reference: the broker's lineage-based segment
        selection)."""
        return set(all_segments) - hidden_segments(self.store, table)

    def cleanup(self, table: str, stale_in_progress_s: float = 86400.0) -> dict:
        """Crash recovery + GC, idempotent (reference: lineage cleanup in
        RetentionManager): finish trailing cleanup of COMPLETED entries
        (process died between the routing flip and the ideal-state sweep),
        drop REVERTED tombstones, and revert IN_PROGRESS entries stale
        enough that their task is certainly dead."""
        now_ms = time.time() * 1000
        report = {"finished": [], "dropped": [], "reverted": []}
        for lid, entry in dict(self.store.get(f"/LINEAGE/{table}") or {}).items():
            if entry["state"] == "COMPLETED":
                self._finish_completed(table, lid, entry)
                report["finished"].append(lid)
            elif entry["state"] == "REVERTED":
                self.store.update(f"/LINEAGE/{table}", lambda cur, lid=lid: {
                    k: v for k, v in (cur or {}).items() if k != lid})
                report["dropped"].append(lid)
            elif (entry["state"] == "IN_PROGRESS"
                  and now_ms - entry.get("tsMs", now_ms)
                  > stale_in_progress_s * 1000):
                self.revert_replace(table, lid)
                report["reverted"].append(lid)
        return report


def hidden_segments(store: PropertyStore, table: str) -> set:
    """Segments brokers must NOT route for this table, per lineage (reads a
    fresh snapshot; pass an already-read snapshot to
    hidden_from_lineage when bracketing reads for consistency)."""
    return hidden_from_lineage(store.get(f"/LINEAGE/{table}"))


def hidden_from_lineage(entries: Optional[dict]) -> set:
    """The TO set of IN_PROGRESS replacements (not yet committed) and the
    FROM set of COMPLETED ones (swap committed, ideal-state cleanup still
    trailing). The single lineage-entry state flip is the atomic routing
    switch; this is the one place that encodes it (used by the broker and
    by SegmentLineageManager.routable_segments)."""
    hidden = set()
    for entry in (entries or {}).values():
        if entry.get("state") == "IN_PROGRESS":
            hidden |= set(entry.get("to", []))
        elif entry.get("state") == "COMPLETED":
            hidden |= set(entry.get("from", []))
    return hidden


# -- data integrity ----------------------------------------------------------


class SegmentIntegrityChecker:
    """Notices replicas quarantined by load-verify failures (ERROR state in
    the external view) and drives self-repair: writes a
    /REPAIRS/{table}/{seg} nudge that the owning servers watch and answer
    with a fresh deep-store fetch + re-verify. Nudges are bounded
    (max_repair_triggers per replica); a replica still ERROR after that is
    flagged unrepairable in the /INTEGRITY/{table} report — the operator's
    signal that the deep-store copy itself may be bad. Healthy-again
    replicas get their nudge + trigger counters cleaned up.

    Reference analogue: SegmentStatusChecker's ERROR-replica accounting +
    RealtimeSegmentValidationManager-style repair kicks."""

    def __init__(self, store: PropertyStore, controller: ClusterController,
                 max_repair_triggers: int = 3):
        self.store = store
        self.controller = controller
        self.max_repair_triggers = max_repair_triggers
        # (table, seg, instance) → nudges issued so far
        self._triggers: dict[tuple, int] = {}

    def __call__(self) -> dict:
        report = {}
        for table in self.store.children("/IDEALSTATES"):
            view = self.store.get(f"/EXTERNALVIEW/{table}") or {}
            errored = {seg: sorted(i for i, st in m.items() if st == ERROR)
                       for seg, m in view.items()
                       if any(st == ERROR for st in m.values())}
            # forget healthy replicas so a future quarantine gets a fresh
            # trigger budget
            for key in [k for k in self._triggers if k[0] == table
                        and k[2] not in errored.get(k[1], ())]:
                self._triggers.pop(key)
            if not errored and self.store.get(f"/INTEGRITY/{table}") is None:
                continue
            nudged, unrepairable = [], []
            for seg, instances in sorted(errored.items()):
                for inst in instances:
                    key = (table, seg, inst)
                    n = self._triggers.get(key, 0)
                    if n >= self.max_repair_triggers:
                        unrepairable.append({"segment": seg,
                                             "instance": inst,
                                             "triggers": n})
                        continue
                    self._triggers[key] = n + 1
                    nudged.append({"segment": seg, "instance": inst})
            for seg in {e["segment"] for e in nudged}:
                # the nonce makes every nudge a distinct write so the
                # store's watch fires even for a repeat nudge
                self.store.set(f"/REPAIRS/{table}/{seg}",
                               {"requestedAtMs": int(time.time() * 1000),
                                "nonce": self._triggers.get(
                                    (table, seg, errored[seg][0]), 0)})
            for seg in self.store.children(f"/REPAIRS/{table}"):
                if seg not in errored:  # repaired (or dropped): clear nudge
                    self.store.delete(f"/REPAIRS/{table}/{seg}")
            integrity = {
                "erroredReplicas": {s: i for s, i in sorted(errored.items())},
                "unrepairable": unrepairable,
                "checkedAtMs": int(time.time() * 1000),
            }
            if errored:
                self.store.set(f"/INTEGRITY/{table}", integrity)
            else:
                self.store.delete(f"/INTEGRITY/{table}")
            report[table] = integrity
        return report


# -- tier relocation ---------------------------------------------------------


class SegmentRelocator:
    """Moves aged segments onto their tier's servers (reference:
    SegmentRelocator + TierConfig). Delegates to the controller's
    tier-aware safe relocation (controller.relocate_tiers: two-phase
    ideal-state convergence, availability never dips — "allow at most one
    replica unavailable during rebalance"). Tier configs accept the
    reference shape ({"name", "segmentSelectorType", "segmentAge",
    "serverTag", "segmentList"}) and the legacy segmentAgeMs key."""

    def __init__(self, controller: ClusterController):
        self.controller = controller

    def __call__(self) -> dict:
        moves = {}
        for table in self.controller.store.children("/CONFIGS/TABLE"):
            cfg = self.controller.table_config(table) or {}
            if not cfg.get("tierConfigs"):
                continue
            try:
                res = self.controller.relocate_tiers(table)
            except (RuntimeError, TimeoutError):
                continue  # tier servers down: retry on the next cycle
            if res.get("segments_changed"):
                moved = [(seg, res["tiers"].get(seg))
                         for seg in sorted(res["target"])
                         if res["tiers"].get(seg) is not None]
                moves[table] = [(s, t) for s, t in moved]
        return moves


def build_default_scheduler(store: PropertyStore, controller: ClusterController,
                            interval_s: float = 10.0,
                            leader=None) -> ControllerPeriodicTaskScheduler:
    """The standard job set (reference BaseControllerStarter wiring). Pass
    a LeadControllerManager so only the elected controller runs the jobs
    when several controllers share a cluster; defaults to the controller's
    own elector when it was built with an instance_id."""
    if leader is None:
        leader = getattr(controller, "leader", None)
    sched = ControllerPeriodicTaskScheduler(leader=leader)
    sched.register("RetentionManager", interval_s,
                   lambda: controller.run_retention())
    sched.register("SegmentStatusChecker", interval_s,
                   SegmentStatusChecker(store, controller))
    sched.register("SegmentIntegrityChecker", interval_s,
                   SegmentIntegrityChecker(store, controller))
    sched.register("RebalanceChecker", interval_s, RebalanceChecker(controller))
    sched.register("SegmentRelocator", interval_s, SegmentRelocator(controller))

    def _lineage_cleanup():
        mgr = SegmentLineageManager(store, controller)
        return {t: mgr.cleanup(t) for t in store.children("/LINEAGE")}

    sched.register("LineageCleanupTask", interval_s, _lineage_cleanup)
    return sched
