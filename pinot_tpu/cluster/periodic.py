"""Controller periodic tasks + segment lineage + tier relocation.

Reference analogues (SURVEY.md §2.6):
- ControllerPeriodicTask framework + the scheduled jobs wired in
  BaseControllerStarter.java:865-896 (RetentionManager,
  SegmentStatusChecker, RebalanceChecker, SegmentRelocator).
- Segment lineage for atomic replacement
  (pinot-controller/.../helix/core/lineage/ — startReplaceSegments/
  endReplaceSegments; brokers exclude in-flight segments from routing).
- Tier configs moving aged segments onto differently-tagged servers
  (SegmentRelocator + TierConfig).
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Optional

from ..spi.metrics import (CONTROLLER_METRICS, ControllerGauge,
                           ControllerMeter)
from .controller import ERROR, ONLINE, ClusterController
from .store import PropertyStore


# -- periodic task framework -------------------------------------------------


@dataclass
class PeriodicTask:
    name: str
    interval_s: float
    fn: Callable[[], object]
    last_run: float = 0.0
    runs: int = 0
    last_result: object = None
    last_error: Optional[str] = None


class ControllerPeriodicTaskScheduler:
    """Fixed-interval controller jobs on one background thread (reference:
    ControllerPeriodicTask + PeriodicTaskScheduler)."""

    def __init__(self, tick_s: float = 0.05, leader=None):
        self.tick_s = tick_s
        self.tasks: dict[str, PeriodicTask] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # cluster/leader.py LeadControllerManager: with multiple controllers
        # only the elected leader runs periodic jobs (reference: controller
        # periodic tasks run on the lead controller only)
        self.leader = leader

    def register(self, name: str, interval_s: float, fn: Callable) -> None:
        self.tasks[name] = PeriodicTask(name, interval_s, fn)

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="controller-periodic")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(10)

    def run_once(self, name: Optional[str] = None) -> dict:
        """Synchronous trigger (tests + admin endpoint; reference:
        /periodictask/run)."""
        out = {}
        for t in self.tasks.values():
            if name is not None and t.name != name:
                continue
            self._run(t)
            out[t.name] = t.last_result if t.last_error is None else t.last_error
        return out

    def _loop(self) -> None:
        while not self._stop.wait(self.tick_s):
            if self.leader is not None and not self.leader.is_leader:
                continue  # standby controller: the leader runs the jobs
            now = time.monotonic()
            for t in self.tasks.values():
                if now - t.last_run >= t.interval_s:
                    self._run(t)

    def _run(self, t: PeriodicTask) -> None:
        t.last_run = time.monotonic()
        t.runs += 1
        try:
            t.last_result = t.fn()
            t.last_error = None
        except Exception as e:  # periodic tasks must not kill the loop
            t.last_error = f"{type(e).__name__}: {e}"


# -- built-in controller jobs ------------------------------------------------


class SegmentStatusChecker:
    """Counts segments/replicas per table, flags ideal-vs-external drift;
    writes /STATS/{table} (reference: SegmentStatusChecker metrics:
    nonServingSegments, replicationFromConfig...)."""

    def __init__(self, store: PropertyStore, controller: ClusterController):
        self.store = store
        self.controller = controller

    def __call__(self) -> dict:
        report = {}
        for table in self.store.children("/IDEALSTATES"):
            ideal = self.store.get(f"/IDEALSTATES/{table}") or {}
            view = self.store.get(f"/EXTERNALVIEW/{table}") or {}
            missing = []
            under_replicated = []
            for seg, want in ideal.items():
                have = {i for i, st in (view.get(seg) or {}).items()
                        if st == ONLINE}
                if not have:
                    missing.append(seg)
                elif len(have) < len(want):
                    under_replicated.append(seg)
            stats = {
                "numSegments": len(ideal),
                "nonServingSegments": missing,
                "underReplicatedSegments": under_replicated,
                "checkedAtMs": int(time.time() * 1000),
            }
            self.store.set(f"/STATS/{table}", stats)
            report[table] = stats
        return report


class RebalanceChecker:
    """Re-runs rebalance for tables whose replication is not satisfiable
    from the ideal state (reference: RebalanceChecker retrying stuck
    rebalances)."""

    def __init__(self, controller: ClusterController):
        self.controller = controller

    def __call__(self) -> dict:
        fixed = {}
        live = set(self.controller.live_instances())
        for table in self.controller.store.children("/CONFIGS/TABLE"):
            # a durable rebalance job (movePlan journal) owns this table's
            # ideal state: the RebalanceActuator converges it move-by-move,
            # and a concurrent blocking rebalance here would fight the
            # journaled plan. Legacy movePlan-less records are NOT skipped:
            # an IN_PROGRESS one is a crash leftover of the synchronous
            # path, and skipping it would wedge healing forever.
            job = self.controller.store.get(f"/REBALANCE/{table}") or {}
            if job.get("status") in ("IN_PROGRESS", "ABORTING") \
                    and "movePlan" in job:
                continue
            cfg = self.controller.table_config(table) or {}
            replication = int(cfg.get("replication", 1))
            ideal = self.controller.store.get(f"/IDEALSTATES/{table}") or {}
            broken = any(
                len([i for i in m if i in live]) < replication
                for m in ideal.values())
            if broken and len(live) >= replication:
                fixed[table] = self.controller.rebalance(table)["moves"]
        return fixed


# -- segment lineage (atomic replacement) ------------------------------------


class SegmentLineageManager:
    """start/end/revert replace-segments protocol. While IN_PROGRESS the
    broker must route the FROM set and ignore the TO set; on end the swap
    commits atomically in the ideal state (reference:
    SegmentLineageAccessHelper + PinotHelixResourceManager
    startReplaceSegments/endReplaceSegments)."""

    def __init__(self, store: PropertyStore, controller: ClusterController):
        self.store = store
        self.controller = controller

    def start_replace(self, table: str, from_segments: list[str],
                      to_segments: list[str]) -> str:
        lineage_id = uuid.uuid4().hex[:12]
        # snapshot the FROM generation (push time) so trailing cleanup can
        # tell a replaced segment from one RE-pushed under the same name
        # after the swap — the latter must not be deleted
        from_push = {
            seg: (self.store.get(f"/SEGMENTS/{table}/{seg}") or {}).get(
                "pushTimeMs")
            for seg in from_segments}
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            **(cur or {}),
            lineage_id: {"state": "IN_PROGRESS", "from": from_segments,
                         "to": to_segments, "fromPushMs": from_push,
                         "tsMs": int(time.time() * 1000)}})
        return lineage_id

    def end_replace(self, table: str, lineage_id: str) -> None:
        entry = (self.store.get(f"/LINEAGE/{table}") or {}).get(lineage_id)
        if entry is None or entry["state"] != "IN_PROGRESS":
            raise KeyError(f"lineage {lineage_id} not in progress")
        # the state flip IS the atomic routing switch: brokers route the TO
        # set and hide the FROM set the instant this single key updates.
        # Ideal-state removal is trailing cleanup (servers unload); a crash
        # between flip and cleanup leaves a COMPLETED entry that cleanup()
        # (periodic LineageCleanupTask) finishes idempotently.
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            **(cur or {}), lineage_id: {**entry, "state": "COMPLETED"}})
        # the routing switch just happened — cached broker results built on
        # the FROM set are stale from this instant (cache/results.py)
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, table)
        self._finish_completed(table, lineage_id, entry)

    def _finish_completed(self, table: str, lineage_id: str,
                          entry: dict) -> None:
        """Idempotent trailing cleanup for a COMPLETED entry: drop the FROM
        set from the ideal state and metadata, then delete the entry itself
        so the FROM names become reusable (brokers hide FROM of COMPLETED
        entries only while this cleanup is pending). A FROM name whose
        current metadata no longer matches the generation snapshotted at
        start_replace was re-pushed after the swap and is left alone."""
        from_push = entry.get("fromPushMs", {})
        victims = []
        for seg in entry["from"]:
            meta = self.store.get(f"/SEGMENTS/{table}/{seg}")
            if meta is not None and seg in from_push and \
                    meta.get("pushTimeMs") != from_push[seg]:
                continue  # re-created under the same name — not ours
            victims.append(seg)

        def upd(ideal):
            ideal = ideal or {}
            for seg in victims:
                ideal.pop(seg, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{table}", upd)
        for seg in victims:
            self.store.delete(f"/SEGMENTS/{table}/{seg}")
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            k: v for k, v in (cur or {}).items() if k != lineage_id})

    def revert_replace(self, table: str, lineage_id: str) -> None:
        entry = (self.store.get(f"/LINEAGE/{table}") or {}).get(lineage_id)
        if entry is None or entry["state"] != "IN_PROGRESS":
            raise KeyError(f"lineage {lineage_id} not in progress")
        def upd(ideal):
            ideal = ideal or {}
            for seg in entry["to"]:
                ideal.pop(seg, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{table}", upd)
        for seg in entry["to"]:
            self.store.delete(f"/SEGMENTS/{table}/{seg}")
        self.store.update(f"/LINEAGE/{table}", lambda cur: {
            **(cur or {}), lineage_id: {**entry, "state": "REVERTED"}})
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, table)

    def routable_segments(self, table: str, all_segments: set) -> set:
        """Filter by lineage (reference: the broker's lineage-based segment
        selection)."""
        return set(all_segments) - hidden_segments(self.store, table)

    def cleanup(self, table: str, stale_in_progress_s: float = 86400.0) -> dict:
        """Crash recovery + GC, idempotent (reference: lineage cleanup in
        RetentionManager): finish trailing cleanup of COMPLETED entries
        (process died between the routing flip and the ideal-state sweep),
        drop REVERTED tombstones, and revert IN_PROGRESS entries stale
        enough that their task is certainly dead."""
        now_ms = time.time() * 1000
        report = {"finished": [], "dropped": [], "reverted": []}
        for lid, entry in dict(self.store.get(f"/LINEAGE/{table}") or {}).items():
            if entry["state"] == "COMPLETED":
                self._finish_completed(table, lid, entry)
                report["finished"].append(lid)
            elif entry["state"] == "REVERTED":
                self.store.update(f"/LINEAGE/{table}", lambda cur, lid=lid: {
                    k: v for k, v in (cur or {}).items() if k != lid})
                report["dropped"].append(lid)
            elif (entry["state"] == "IN_PROGRESS"
                  and now_ms - entry.get("tsMs", now_ms)
                  > stale_in_progress_s * 1000):
                self.revert_replace(table, lid)
                report["reverted"].append(lid)
        return report


def hidden_segments(store: PropertyStore, table: str) -> set:
    """Segments brokers must NOT route for this table, per lineage (reads a
    fresh snapshot; pass an already-read snapshot to
    hidden_from_lineage when bracketing reads for consistency)."""
    return hidden_from_lineage(store.get(f"/LINEAGE/{table}"))


def hidden_from_lineage(entries: Optional[dict]) -> set:
    """The TO set of IN_PROGRESS replacements (not yet committed) and the
    FROM set of COMPLETED ones (swap committed, ideal-state cleanup still
    trailing). The single lineage-entry state flip is the atomic routing
    switch; this is the one place that encodes it (used by the broker and
    by SegmentLineageManager.routable_segments)."""
    hidden = set()
    for entry in (entries or {}).values():
        if entry.get("state") == "IN_PROGRESS":
            hidden |= set(entry.get("to", []))
        elif entry.get("state") == "COMPLETED":
            hidden |= set(entry.get("from", []))
    return hidden


# -- data integrity ----------------------------------------------------------


class SegmentIntegrityChecker:
    """Notices replicas quarantined by load-verify failures (ERROR state in
    the external view) and drives self-repair: writes a
    /REPAIRS/{table}/{seg} nudge that the owning servers watch and answer
    with a fresh deep-store fetch + re-verify. Nudges are bounded
    (max_repair_triggers per replica); a replica still ERROR after that is
    flagged unrepairable in the /INTEGRITY/{table} report — the operator's
    signal that the deep-store copy itself may be bad. Healthy-again
    replicas get their nudge + trigger counters cleaned up.

    Reference analogue: SegmentStatusChecker's ERROR-replica accounting +
    RealtimeSegmentValidationManager-style repair kicks."""

    def __init__(self, store: PropertyStore, controller: ClusterController,
                 max_repair_triggers: int = 3):
        self.store = store
        self.controller = controller
        self.max_repair_triggers = max_repair_triggers
        # (table, seg, instance) → nudges issued so far
        self._triggers: dict[tuple, int] = {}

    def __call__(self) -> dict:
        report = {}
        for table in self.store.children("/IDEALSTATES"):
            view = self.store.get(f"/EXTERNALVIEW/{table}") or {}
            errored = {seg: sorted(i for i, st in m.items() if st == ERROR)
                       for seg, m in view.items()
                       if any(st == ERROR for st in m.values())}
            # forget healthy replicas so a future quarantine gets a fresh
            # trigger budget
            for key in [k for k in self._triggers if k[0] == table
                        and k[2] not in errored.get(k[1], ())]:
                self._triggers.pop(key)
            if not errored and self.store.get(f"/INTEGRITY/{table}") is None:
                continue
            nudged, unrepairable = [], []
            for seg, instances in sorted(errored.items()):
                for inst in instances:
                    key = (table, seg, inst)
                    n = self._triggers.get(key, 0)
                    if n >= self.max_repair_triggers:
                        unrepairable.append({"segment": seg,
                                             "instance": inst,
                                             "triggers": n})
                        continue
                    self._triggers[key] = n + 1
                    nudged.append({"segment": seg, "instance": inst})
            for seg in {e["segment"] for e in nudged}:
                # the nonce makes every nudge a distinct write so the
                # store's watch fires even for a repeat nudge
                self.store.set(f"/REPAIRS/{table}/{seg}",
                               {"requestedAtMs": int(time.time() * 1000),
                                "nonce": self._triggers.get(
                                    (table, seg, errored[seg][0]), 0)})
            for seg in self.store.children(f"/REPAIRS/{table}"):
                if seg not in errored:  # repaired (or dropped): clear nudge
                    self.store.delete(f"/REPAIRS/{table}/{seg}")
            integrity = {
                "erroredReplicas": {s: i for s, i in sorted(errored.items())},
                "unrepairable": unrepairable,
                "checkedAtMs": int(time.time() * 1000),
            }
            if errored:
                self.store.set(f"/INTEGRITY/{table}", integrity)
            else:
                self.store.delete(f"/INTEGRITY/{table}")
            report[table] = integrity
        return report


# -- tier relocation ---------------------------------------------------------


class SegmentRelocator:
    """Moves aged segments onto their tier's servers (reference:
    SegmentRelocator + TierConfig). Delegates to the controller's
    tier-aware safe relocation (controller.relocate_tiers: two-phase
    ideal-state convergence, availability never dips — "allow at most one
    replica unavailable during rebalance"). Tier configs accept the
    reference shape ({"name", "segmentSelectorType", "segmentAge",
    "serverTag", "segmentList"}) and the legacy segmentAgeMs key."""

    def __init__(self, controller: ClusterController):
        self.controller = controller

    def __call__(self) -> dict:
        moves = {}
        for table in self.controller.store.children("/CONFIGS/TABLE"):
            cfg = self.controller.table_config(table) or {}
            if not cfg.get("tierConfigs"):
                continue
            try:
                res = self.controller.relocate_tiers(table)
            except (RuntimeError, TimeoutError):
                continue  # tier servers down: retry on the next cycle
            if res.get("segments_changed"):
                moved = [(seg, res["tiers"].get(seg))
                         for seg in sorted(res["target"])
                         if res["tiers"].get(seg) is not None]
                moves[table] = [(s, t) for s, t in moved]
        return moves


# -- cluster health rollup ---------------------------------------------------


# where the leader materializes the fleet snapshot; GET /debug/cluster on
# any controller serves this key (standbys serve the leader's last scrape)
HEALTH_REPORT_PATH = "/HEALTH/cluster"

# fewest latency samples before an instance participates in straggler math
# (the absolute stragglerMinMs floor already filters small-sample noise)
_MIN_LATENCY_SAMPLES = 3
# fewest segment-cache lookups in a scrape window before the fleet hit
# rate is judged at all (a near-idle window says nothing about the cache)
_MIN_CACHE_LOOKUPS = 32


class ClusterHealthChecker:
    """Leader-side fleet scrape + anomaly detection (the tentpole of the
    observability PR). Each run RPCs every live server's ``status``
    endpoint (per-instance latency quantiles, HBM residency, cache
    counters, quarantine inventory), folds in any broker state published
    at ``/BROKERSTATE/*`` (cluster/broker.py publish_state), and
    materializes one fleet snapshot at ``HEALTH_REPORT_PATH`` — the body
    of ``GET /debug/cluster``.

    Anomaly rules (each flagged entry ticks the clusterHealthAnomalies
    meter; thresholds are env knobs, documented in the README operating
    guide):

    - ``straggler``            a server's p99 is ≥ PINOT_TPU_STRAGGLER_RATIO
                               × the fleet median p99 AND at least
                               PINOT_TPU_STRAGGLER_MIN_MS above it
    - ``hbm-pressure``         HBM used/budget ≥ PINOT_TPU_HBM_PRESSURE_RATIO,
                               or new hbmOomEvents since the last scrape
    - ``cache-collapse``       fleet segment-cache hit rate over the scrape
                               window fell below PINOT_TPU_CACHE_COLLAPSE_RATE
                               after a previously healthy (≥50%) window
    - ``breaker-flap``         a broker's breakers re-opened ≥
                               PINOT_TPU_BREAKER_FLAP_COUNT times in one window
    - ``instance-unreachable`` a live-instance entry did not answer the scrape

    All scrape work runs on the controller's periodic thread — never on a
    query thread — and only on the elected leader (double-gated: the
    scheduler loop skips standbys, and __call__ re-checks so a stray
    run_once on a standby stays a no-op)."""

    def __init__(self, store: PropertyStore, controller: ClusterController,
                 straggler_ratio: Optional[float] = None,
                 straggler_min_ms: Optional[float] = None,
                 hbm_pressure_ratio: Optional[float] = None,
                 cache_collapse_rate: Optional[float] = None,
                 breaker_flap_count: Optional[int] = None,
                 scrape_timeout_s: float = 2.0):
        self.store = store
        self.controller = controller
        self.straggler_ratio = straggler_ratio if straggler_ratio is not None \
            else float(os.environ.get("PINOT_TPU_STRAGGLER_RATIO", 3.0))
        self.straggler_min_ms = straggler_min_ms \
            if straggler_min_ms is not None \
            else float(os.environ.get("PINOT_TPU_STRAGGLER_MIN_MS", 50.0))
        self.hbm_pressure_ratio = hbm_pressure_ratio \
            if hbm_pressure_ratio is not None \
            else float(os.environ.get("PINOT_TPU_HBM_PRESSURE_RATIO", 0.9))
        self.cache_collapse_rate = cache_collapse_rate \
            if cache_collapse_rate is not None \
            else float(os.environ.get("PINOT_TPU_CACHE_COLLAPSE_RATE", 0.2))
        self.breaker_flap_count = breaker_flap_count \
            if breaker_flap_count is not None \
            else int(os.environ.get("PINOT_TPU_BREAKER_FLAP_COUNT", 3))
        self.scrape_timeout_s = scrape_timeout_s
        # previous-scrape counters for windowed (delta) rules
        self._prev_counters: dict[str, dict] = {}
        self._prev_breaker_opens: dict[str, int] = {}
        self._prev_window_hit_rate: Optional[float] = None
        self._last_reachable = 0
        CONTROLLER_METRICS.set_gauge(
            ControllerGauge.CLUSTER_SERVERS_REACHABLE,
            lambda: self._last_reachable)

    def __call__(self) -> dict:
        leader = getattr(self.controller, "leader", None)
        if leader is not None and not leader.is_leader:
            return {"skipped": "standby controller does not scrape"}
        t0 = time.perf_counter()
        servers, anomalies = self._scrape_servers()
        brokers = self._collect_brokers(anomalies)
        self._collect_perf_alerts(anomalies)
        fleet = self._fleet_rollup(servers, anomalies)
        self._last_reachable = fleet["serversReachable"]
        snapshot = {
            "checkedAtMs": int(time.time() * 1000),
            "scrapeMs": round((time.perf_counter() - t0) * 1000, 3),
            "fleet": fleet,
            "servers": servers,
            "brokers": brokers,
            "anomalies": anomalies,
            "thresholds": {
                "stragglerRatio": self.straggler_ratio,
                "stragglerMinMs": self.straggler_min_ms,
                "hbmPressureRatio": self.hbm_pressure_ratio,
                "cacheCollapseRate": self.cache_collapse_rate,
                "breakerFlapCount": self.breaker_flap_count,
            },
        }
        if anomalies:
            CONTROLLER_METRICS.add_meter(
                ControllerMeter.CLUSTER_HEALTH_ANOMALIES, len(anomalies))
        self.store.set(HEALTH_REPORT_PATH, snapshot)
        return snapshot

    # -- scrape side ---------------------------------------------------------
    def _scrape_servers(self) -> tuple[dict, list]:
        from .transport import RemoteError, RpcClient, TransportError

        servers: dict[str, dict] = {}
        anomalies: list[dict] = []
        for inst in sorted(self.store.children("/LIVEINSTANCES")):
            cfg = self.store.get(f"/LIVEINSTANCES/{inst}") or {}
            if "port" not in cfg:
                continue  # minions and other non-query instances
            client = RpcClient(cfg.get("host", "127.0.0.1"), cfg["port"],
                               timeout=self.scrape_timeout_s,
                               connect_timeout=self.scrape_timeout_s)
            try:
                status = client.call({"type": "status"}, retry=False)
                servers[inst] = dict(status, reachable=True)
            except (TransportError, RemoteError, OSError) as e:
                servers[inst] = {"instanceId": inst, "reachable": False,
                                 "error": str(e)}
                anomalies.append({
                    "type": "instance-unreachable", "instance": inst,
                    "detail": f"health scrape failed: {e}"})
            finally:
                client.close()
        return servers, anomalies

    def _collect_brokers(self, anomalies: list) -> dict:
        brokers: dict[str, dict] = {}
        for bid in sorted(self.store.children("/BROKERSTATE")):
            state = self.store.get(f"/BROKERSTATE/{bid}") or {}
            brokers[bid] = state
            opens = sum(int((b or {}).get("timesOpened", 0))
                        for b in (state.get("breakers") or {}).values())
            prev = self._prev_breaker_opens.get(bid)
            if prev is not None and opens - prev >= self.breaker_flap_count:
                anomalies.append({
                    "type": "breaker-flap", "instance": bid,
                    "detail": f"circuit breakers opened {opens - prev} "
                              f"times since the last scrape "
                              f"(threshold {self.breaker_flap_count})"})
            self._prev_breaker_opens[bid] = opens
        return brokers

    def _collect_perf_alerts(self, anomalies: list) -> None:
        """Fold the regression sentinel's active alerts into the fleet
        snapshot so GET /debug/cluster shows perf drift next to infra
        anomalies (lazy import: periodic.py must not pull the engine in)."""
        from ..engine.perf_ledger import ALERTS

        if not ALERTS.active_count:
            return
        for rec in ALERTS.active():
            anomalies.append({
                "type": rec["type"], "instance": rec.get("table", ""),
                "alertId": rec["id"],
                "detail": rec.get("summary", "")})

    # -- anomaly math --------------------------------------------------------
    def _fleet_rollup(self, servers: dict, anomalies: list) -> dict:
        reachable = {i: s for i, s in servers.items() if s.get("reachable")}
        # straggler: per-server p99 vs the fleet median p99
        p99s = {i: s["queryLatencyMs"]["p99"] for i, s in reachable.items()
                if s.get("queryLatencyMs", {}).get("count", 0)
                >= _MIN_LATENCY_SAMPLES}
        median_p99 = _median(list(p99s.values()))
        if len(p99s) >= 2:
            for inst, p99 in sorted(p99s.items()):
                # leave-one-out median: with small fleets the overall
                # median is dragged toward the straggler itself, hiding it
                rest = _median([v for i, v in p99s.items() if i != inst])
                if rest > 0 and p99 >= self.straggler_ratio * rest \
                        and p99 - rest >= self.straggler_min_ms:
                    anomalies.append({
                        "type": "straggler", "instance": inst,
                        "detail": f"p99 {p99:.1f}ms vs rest-of-fleet "
                                  f"median {rest:.1f}ms (ratio "
                                  f"{p99 / rest:.1f}x >= "
                                  f"{self.straggler_ratio}x)"})
        # hbm pressure: residency vs budget, plus fresh OOM events
        window_hits = window_misses = 0
        for inst, s in sorted(reachable.items()):
            hbm = s.get("hbm") or {}
            used = int(hbm.get("hbmBytesUsed", 0) or 0)
            budget = hbm.get("hbmBudgetBytes")
            if budget and used / budget >= self.hbm_pressure_ratio:
                anomalies.append({
                    "type": "hbm-pressure", "instance": inst,
                    "detail": f"HBM {used}/{budget} bytes "
                              f"({used / budget:.0%} >= "
                              f"{self.hbm_pressure_ratio:.0%} of budget)"})
            prev = self._prev_counters.get(inst, {})
            oom_delta = s.get("hbmOomEvents", 0) - prev.get("oom", 0)
            if prev and oom_delta > 0:
                anomalies.append({
                    "type": "hbm-pressure", "instance": inst,
                    "detail": f"{oom_delta} hbmOomEvents since the last "
                              f"scrape"})
            cache = s.get("segmentCache") or {}
            window_hits += cache.get("hits", 0) - prev.get("hits", 0) \
                if prev else 0
            window_misses += cache.get("misses", 0) - prev.get("misses", 0) \
                if prev else 0
            self._prev_counters[inst] = {
                "hits": cache.get("hits", 0),
                "misses": cache.get("misses", 0),
                "oom": s.get("hbmOomEvents", 0),
            }
        # cache collapse: fleet hit rate over THIS window, judged only
        # against a previously healthy window with real traffic
        lookups = window_hits + window_misses
        window_rate = window_hits / lookups if lookups else None
        if lookups >= _MIN_CACHE_LOOKUPS and window_rate is not None:
            prev_rate = self._prev_window_hit_rate
            if prev_rate is not None and prev_rate >= 0.5 \
                    and window_rate < self.cache_collapse_rate:
                anomalies.append({
                    "type": "cache-collapse", "instance": "",
                    "detail": f"fleet segment-cache hit rate fell to "
                              f"{window_rate:.0%} (was {prev_rate:.0%}) "
                              f"over {lookups} lookups"})
            self._prev_window_hit_rate = window_rate
        quarantined = sum(len(segs)
                          for s in reachable.values()
                          for segs in (s.get("quarantined") or {}).values())
        return {
            "serversTotal": len(servers),
            "serversReachable": len(reachable),
            "medianP50Ms": _median([s["queryLatencyMs"]["p50"]
                                    for s in reachable.values()
                                    if s.get("queryLatencyMs", {}).get(
                                        "count", 0)]),
            "medianP99Ms": median_p99,
            "maxP99Ms": max(p99s.values()) if p99s else 0.0,
            "windowCacheHitRate": round(window_rate, 4)
            if window_rate is not None else None,
            "quarantinedSegments": quarantined,
        }


def _median(values: list) -> float:
    if not values:
        return 0.0
    values = sorted(values)
    mid = len(values) // 2
    if len(values) % 2:
        return float(values[mid])
    return (values[mid - 1] + values[mid]) / 2.0


def build_default_scheduler(store: PropertyStore, controller: ClusterController,
                            interval_s: float = 10.0,
                            leader=None) -> ControllerPeriodicTaskScheduler:
    """The standard job set (reference BaseControllerStarter wiring). Pass
    a LeadControllerManager so only the elected controller runs the jobs
    when several controllers share a cluster; defaults to the controller's
    own elector when it was built with an instance_id."""
    if leader is None:
        leader = getattr(controller, "leader", None)
    sched = ControllerPeriodicTaskScheduler(leader=leader)
    sched.register("RetentionManager", interval_s,
                   lambda: controller.run_retention())
    sched.register("SegmentStatusChecker", interval_s,
                   SegmentStatusChecker(store, controller))
    sched.register("SegmentIntegrityChecker", interval_s,
                   SegmentIntegrityChecker(store, controller))
    sched.register("RebalanceChecker", interval_s, RebalanceChecker(controller))
    sched.register("SegmentRelocator", interval_s, SegmentRelocator(controller))

    def _lineage_cleanup():
        mgr = SegmentLineageManager(store, controller)
        return {t: mgr.cleanup(t) for t in store.children("/LINEAGE")}

    sched.register("LineageCleanupTask", interval_s, _lineage_cleanup)

    def _rebalance_actuator():
        # built lazily so importing periodic.py never pulls the engine in
        from .rebalance import RebalanceActuator, SegmentRebalancer

        if not hasattr(_rebalance_actuator, "task"):
            _rebalance_actuator.task = RebalanceActuator(
                SegmentRebalancer(controller))
        return _rebalance_actuator.task()

    # actuation wants a tighter cadence than housekeeping: a move's EV wait
    # advances at most one step per tick
    actuate_s = float(os.environ.get("PINOT_TPU_REBALANCE_TICK_S",
                                     min(1.0, interval_s)))
    sched.register("RebalanceActuator", actuate_s, _rebalance_actuator)
    # fleet scrape can run on its own cadence (operators tune how fresh
    # GET /debug/cluster is, independent of segment housekeeping)
    scrape_s = float(os.environ.get("PINOT_TPU_HEALTH_SCRAPE_S", interval_s))
    sched.register("ClusterHealthChecker", scrape_s,
                   ClusterHealthChecker(store, controller))

    def _storage_prefetcher():
        # built lazily so importing periodic.py never pulls the storage
        # package in; walks broker /BROKERSTATE cost beacons and writes
        # /PREFETCH/{table} nudges for tables entering the hot set
        from ..storage.prefetch import StoragePrefetcher

        if not hasattr(_storage_prefetcher, "task"):
            _storage_prefetcher.task = StoragePrefetcher(store)
        return _storage_prefetcher.task()

    prefetch_s = float(os.environ.get("PINOT_TPU_PREFETCH_TICK_S",
                                      interval_s))
    sched.register("StoragePrefetcher", prefetch_s, _storage_prefetcher)

    def _perf_sentinel():
        # built lazily so importing periodic.py never pulls the engine in
        from .sentinel import SCRAPE_S_ENV, PerfRegressionSentinel  # noqa: F401

        if not hasattr(_perf_sentinel, "task"):
            _perf_sentinel.task = PerfRegressionSentinel(store, controller)
        return _perf_sentinel.task()

    sentinel_s = float(os.environ.get("PINOT_TPU_SENTINEL_SCRAPE_S",
                                      interval_s))
    sched.register("PerfRegressionSentinel", sentinel_s, _perf_sentinel)
    return sched
