"""Broker query log with rate throttling.

Reference analogue: pinot-broker/.../querylog/QueryLogger.java — one
structured log line per completed query (requestId, table, latency,
docs scanned/table size, exceptions), throttled by a token-bucket rate
limiter so a hot broker can't melt the log volume; dropped lines are
counted and surfaced periodically.
"""

from __future__ import annotations

import itertools
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger("pinot_tpu.querylog")


class QueryLogger:
    """Token-bucket-throttled per-query log (default 10 lines/s), plus a
    slow-query ring buffer: every completed query over
    ``slow_threshold_ms`` (PINOT_TPU_SLOW_QUERY_MS, default 500) is kept —
    with its full phase breakdown when it ran traced — in a bounded deque
    served by the broker's GET /debug/queries. The slow capture is NOT
    throttled: the worst queries are exactly the ones a drop would hide."""

    def __init__(self, max_lines_per_s: float = 10.0, max_sql_len: int = 200,
                 slow_threshold_ms: float = None, slow_buffer_size: int = 50,
                 trace_store=None):
        self.rate = float(max_lines_per_s)
        self.max_sql_len = max_sql_len
        # flight-recorder linkage: when the broker wires its TraceStore in,
        # slow entries reference the retained trace by id instead of
        # embedding the span list (one copy of the bytes, in the store)
        self.trace_store = trace_store
        self.slow_threshold_ms = float(
            os.environ.get("PINOT_TPU_SLOW_QUERY_MS", 500.0)
            if slow_threshold_ms is None else slow_threshold_ms)
        self._slow: deque = deque(maxlen=slow_buffer_size)
        # cap ≥ 1.0: with a sub-1 rate a rate-sized cap could never reach
        # one token and the logger would be permanently, silently mute
        self._cap = max(self.rate, 1.0)
        self._tokens = self._cap
        self._last = time.monotonic()
        self._dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)

    def _note_slow(self, rid: int, sql: str, response, table: str) -> None:
        time_ms = getattr(response, "time_used_ms", 0) or 0
        if time_ms < self.slow_threshold_ms:
            return
        sql_part = sql if len(sql) <= self.max_sql_len else \
            sql[: self.max_sql_len] + "..."
        entry = {
            "requestId": rid,
            "table": table,
            "timeMs": round(time_ms, 3),
            "docsScanned": getattr(response, "num_docs_scanned", 0),
            "segmentsQueried": getattr(response, "num_segments_queried", 0),
            "numDeviceDispatches": getattr(response,
                                           "num_device_dispatches", 0),
            "numCompiles": getattr(response, "num_compiles", 0),
            "exceptions": len(getattr(response, "exceptions", []) or []),
            "timestamp": time.time(),
            "sql": sql_part,
        }
        if getattr(response, "partial_result", False):
            entry["partialResult"] = True
        if getattr(response, "num_servers_queried", 0):
            entry["numServersQueried"] = response.num_servers_queried
            entry["numServersResponded"] = response.num_servers_responded
        # self-healing scatter/gather: a slow query that healed (retried or
        # hedged its way to a full answer) says so in the log
        if getattr(response, "num_scatter_retries", 0):
            entry["scatterRetries"] = response.num_scatter_retries
        if getattr(response, "num_hedged_requests", 0):
            entry["hedgedRequests"] = response.num_hedged_requests
            entry["hedgeWins"] = response.num_hedge_wins
        # wire-integrity healing: shards whose DataTable failed its
        # checksum and were re-dispatched to another replica
        if getattr(response, "num_corrupt_shards_retried", 0):
            entry["corruptShardsRetried"] = response.num_corrupt_shards_retried
        # tiered storage: the query raced a cold segment's warm — slow (or
        # partial) because the bytes were still on their way up the tiers
        if getattr(response, "cold_segments_warming", 0):
            entry["coldSegmentsWarming"] = response.cold_segments_warming
        if getattr(response, "query_rejected", False):
            entry["queryRejected"] = True
        from ..spi import faults

        if faults.ACTIVE:
            # chaos runs: stamp the cumulative injected-fault count so a
            # slow entry can be correlated with the fault schedule
            entry["injectedFaults"] = faults.FAULTS.total_fired()
        # regression-sentinel cross-link: a slow query whose plan or table
        # has an active alert names the alert ids, so /debug/queries and
        # /debug/alerts triangulate without a third lookup. active_count
        # is a plain attribute read — the no-alerts path pays nothing.
        from ..engine.perf_ledger import ALERTS

        if ALERTS.active_count:
            alert_ids = ALERTS.active_ids_for(
                getattr(response, "_ledger_key", "") or "", table)
            if alert_ids:
                entry["alertIds"] = alert_ids
        outcome = getattr(response, "cache_outcome", None)
        if outcome:
            # a "slow but cached" query is an anomaly worth seeing: the
            # result cache answered yet the request still crossed the
            # slow threshold (serialization? lock contention?)
            entry["cacheOutcome"] = outcome
        trace_info = getattr(response, "trace_info", None)
        if trace_info:
            from ..spi.trace import phase_breakdown

            entry["phases"] = phase_breakdown(trace_info)
            trace_id = getattr(response, "trace_id", None)
            if trace_id and self.trace_store is not None \
                    and self.trace_store.get(trace_id) is not None:
                # the broker retained this trace already (sampled or
                # tail-captured): link it — GET /debug/traces/{traceId}
                entry["traceId"] = trace_id
            else:
                entry["trace"] = trace_info
        with self._lock:
            self._slow.append(entry)

    def slow_queries(self) -> list:
        """Ring contents, worst (slowest) first."""
        with self._lock:
            entries = list(self._slow)
        return sorted(entries, key=lambda e: -e["timeMs"])

    def log(self, sql: str, response, table: str = "") -> None:
        rid = next(self._ids)
        self._note_slow(rid, sql, response, table)
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self._cap, self._tokens
                               + (now - self._last) * self.rate)
            self._last = now
            if self._tokens < 1.0:
                self._dropped += 1
                return
            self._tokens -= 1.0
            dropped, self._dropped = self._dropped, 0
        sql_part = sql if len(sql) <= self.max_sql_len else \
            sql[: self.max_sql_len] + "..."
        parts = [
            f"requestId={rid}",
            f"table={table}" if table else None,
            f"timeMs={getattr(response, 'time_used_ms', 0):.1f}",
            f"docsScanned={getattr(response, 'num_docs_scanned', 0)}",
            f"totalDocs={getattr(response, 'total_docs', 0)}",
            f"segmentsQueried={getattr(response, 'num_segments_queried', 0)}",
            f"exceptions={len(getattr(response, 'exceptions', []) or [])}",
            f"droppedSinceLast={dropped}" if dropped else None,
            f"query={sql_part!r}",
        ]
        logger.info("%s", " ".join(p for p in parts if p))
