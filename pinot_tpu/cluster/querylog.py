"""Broker query log with rate throttling.

Reference analogue: pinot-broker/.../querylog/QueryLogger.java — one
structured log line per completed query (requestId, table, latency,
docs scanned/table size, exceptions), throttled by a token-bucket rate
limiter so a hot broker can't melt the log volume; dropped lines are
counted and surfaced periodically.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time

logger = logging.getLogger("pinot_tpu.querylog")


class QueryLogger:
    """Token-bucket-throttled per-query log (default 10 lines/s)."""

    def __init__(self, max_lines_per_s: float = 10.0, max_sql_len: int = 200):
        self.rate = float(max_lines_per_s)
        self.max_sql_len = max_sql_len
        # cap ≥ 1.0: with a sub-1 rate a rate-sized cap could never reach
        # one token and the logger would be permanently, silently mute
        self._cap = max(self.rate, 1.0)
        self._tokens = self._cap
        self._last = time.monotonic()
        self._dropped = 0
        self._lock = threading.Lock()
        self._ids = itertools.count(1)

    def log(self, sql: str, response, table: str = "") -> None:
        rid = next(self._ids)
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self._cap, self._tokens
                               + (now - self._last) * self.rate)
            self._last = now
            if self._tokens < 1.0:
                self._dropped += 1
                return
            self._tokens -= 1.0
            dropped, self._dropped = self._dropped, 0
        sql_part = sql if len(sql) <= self.max_sql_len else \
            sql[: self.max_sql_len] + "..."
        parts = [
            f"requestId={rid}",
            f"table={table}" if table else None,
            f"timeMs={getattr(response, 'time_used_ms', 0):.1f}",
            f"docsScanned={getattr(response, 'num_docs_scanned', 0)}",
            f"totalDocs={getattr(response, 'total_docs', 0)}",
            f"segmentsQueried={getattr(response, 'num_segments_queried', 0)}",
            f"exceptions={len(getattr(response, 'exceptions', []) or [])}",
            f"droppedSinceLast={dropped}" if dropped else None,
            f"query={sql_part!r}",
        ]
        logger.info("%s", " ".join(p for p in parts if p))
