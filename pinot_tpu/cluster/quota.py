"""Query quota + cursors (paginated results).

Reference analogues:
- HelixExternalViewBasedQueryQuotaManager (pinot-broker/.../queryquota/):
  per-table QPS quotas from table config, enforced with a hit counter over
  a sliding window.
- Cursors/response store (pinot-broker/.../cursors/FsResponseStore.java +
  pinot-spi/.../cursors/): a query's full result spools once, pages are
  served by cursor id.
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from typing import Optional


class QueryQuotaExceededError(Exception):
    pass


class QueryQuotaManager:
    """Sliding-window QPS enforcement per table (reference: HitCounter with
    per-second buckets)."""

    def __init__(self, window_s: float = 1.0):
        self.window_s = window_s
        self._lock = threading.Lock()
        self._limits: dict[str, float] = {}
        self._hits: dict[str, deque] = {}

    def set_qps_limit(self, table: str, qps: Optional[float]) -> None:
        with self._lock:
            if qps is None:
                self._limits.pop(table, None)
            else:
                self._limits[table] = float(qps)

    def acquire(self, table: str) -> None:
        """Record a hit; raises when the table is over its QPS quota."""
        with self._lock:
            limit = self._limits.get(table)
            if limit is None:
                return
            now = time.monotonic()
            dq = self._hits.setdefault(table, deque())
            while dq and now - dq[0] > self.window_s:
                dq.popleft()
            if len(dq) >= limit * self.window_s:
                raise QueryQuotaExceededError(
                    f"table {table} exceeded {limit} qps")
            dq.append(now)


class ResponseStore:
    """Spooled query results served page-by-page (reference:
    FsResponseStore + the broker's /resultStore cursor endpoints)."""

    def __init__(self, ttl_s: float = 300.0, max_entries: int = 256):
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._store: dict[str, tuple[float, list, list, list]] = {}

    def create_cursor(self, column_names: list, column_types: list,
                      rows: list) -> str:
        cursor_id = uuid.uuid4().hex
        with self._lock:
            self._evict_locked()
            self._store[cursor_id] = (time.monotonic(), column_names,
                                      column_types, rows)
        return cursor_id

    def fetch(self, cursor_id: str, offset: int, num_rows: int) -> dict:
        with self._lock:
            entry = self._store.get(cursor_id)
        if entry is None:
            raise KeyError(f"cursor {cursor_id} not found or expired")
        _, names, types, rows = entry
        page = rows[offset:offset + num_rows]
        return {
            "resultTable": {
                "dataSchema": {"columnNames": names, "columnDataTypes": types},
                "rows": page},
            "offset": offset,
            "numRows": len(page),
            "totalRows": len(rows),
            "cursorId": cursor_id,
        }

    def delete(self, cursor_id: str) -> bool:
        with self._lock:
            return self._store.pop(cursor_id, None) is not None

    def _evict_locked(self) -> None:
        now = time.monotonic()
        dead = [k for k, (t, *_rest) in self._store.items()
                if now - t > self.ttl_s]
        for k in dead:
            del self._store[k]
        while len(self._store) >= self.max_entries:
            oldest = min(self._store, key=lambda k: self._store[k][0])
            del self._store[oldest]
