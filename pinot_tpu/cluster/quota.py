"""Query quota, broker admission control + cursors (paginated results).

Reference analogues:
- HelixExternalViewBasedQueryQuotaManager (pinot-broker/.../queryquota/):
  per-table QPS quotas from table config, enforced with a hit counter over
  a sliding window.
- The broker's maxConcurrentQueries admission gate: a semaphore over
  query execution that sheds load with a well-formed 429-style rejection
  instead of letting an overloaded broker collapse.
- Cursors/response store (pinot-broker/.../cursors/FsResponseStore.java +
  pinot-spi/.../cursors/): a query's full result spools once, pages are
  served by cursor id.
"""

from __future__ import annotations

import os
import threading
import time
import uuid
from collections import deque
from contextlib import contextmanager
from typing import Optional


class QueryQuotaExceededError(Exception):
    pass


class AdmissionRejectedError(Exception):
    """Broker admission control shed this query (queue full, or the queue
    wait would outlive the query's deadline)."""


class AdmissionController:
    """Broker-wide in-flight query gate (load shedding under overload).

    ``PINOT_TPU_MAX_INFLIGHT_QUERIES`` (or the ctor arg) bounds concurrent
    query executions; unset/0 disables the gate entirely — the warm path
    then pays a single attribute check. Waiters queue on the semaphore,
    but only for as long as the query's own deadline allows (queue-wait is
    bounded by the budget, never an unbounded pile-up), and the queue
    depth itself is capped (``PINOT_TPU_MAX_QUEUED_QUERIES``, default
    2×max-inflight) so a burst fails fast instead of accumulating."""

    def __init__(self, max_inflight: Optional[int] = None,
                 max_queued: Optional[int] = None,
                 heavy_query_ms: Optional[float] = None):
        if max_inflight is None:
            max_inflight = int(os.environ.get(
                "PINOT_TPU_MAX_INFLIGHT_QUERIES", 0)) or None
        if max_queued is None:
            env = os.environ.get("PINOT_TPU_MAX_QUEUED_QUERIES")
            max_queued = int(env) if env is not None else (
                2 * max_inflight if max_inflight else 0)
        if heavy_query_ms is None:
            heavy_query_ms = float(os.environ.get(
                "PINOT_TPU_HEAVY_QUERY_MS", 0.0))
        self.max_inflight = max_inflight
        self.max_queued = max_queued
        # cost-aware shedding (fed by cluster/workload.py): once the broker
        # is saturated, a query whose expected cost — the decayed mean
        # wall-time of its table's recent traffic — crosses this threshold
        # is rejected immediately instead of queueing, so cheap queries
        # keep their queue slots. 0 disables (count-only admission).
        self.heavy_query_ms = heavy_query_ms
        self._sem = (threading.Semaphore(max_inflight)
                     if max_inflight else None)
        self._lock = threading.Lock()
        self._inflight = 0
        self._queued = 0

    def inflight(self) -> int:
        with self._lock:
            return self._inflight

    def queued(self) -> int:
        with self._lock:
            return self._queued

    @contextmanager
    def admit(self, timeout_s: float = 0.0, cost_hint_ms: float = 0.0):
        """Hold one in-flight slot for the duration of the block; raises
        AdmissionRejectedError when the queue is full or no slot frees up
        within ``timeout_s`` (the query's remaining deadline).
        ``cost_hint_ms`` — the caller's expected cost for this query (the
        workload tracker's decayed per-table mean) — lets a saturated
        broker shed expensive queries without queueing them."""
        if self._sem is None:
            yield
            return
        # fast path: a free slot means no queueing at all — the queue-depth
        # cap only applies to queries that would actually have to wait
        ok = self._sem.acquire(blocking=False)
        if not ok:
            if self.heavy_query_ms and cost_hint_ms \
                    and cost_hint_ms >= self.heavy_query_ms:
                raise AdmissionRejectedError(
                    f"broker saturated and query's expected cost "
                    f"{cost_hint_ms:.0f}ms >= heavy threshold "
                    f"{self.heavy_query_ms:.0f}ms (cost-aware shedding)")
            with self._lock:
                if self._queued >= self.max_queued:
                    raise AdmissionRejectedError(
                        f"broker admission queue full "
                        f"({self._queued} queued, "
                        f"{self.max_inflight} in flight)")
                self._queued += 1
            t0 = time.perf_counter()
            try:
                ok = self._sem.acquire(timeout=max(0.0, timeout_s))
            finally:
                with self._lock:
                    self._queued -= 1
            wait_ms = (time.perf_counter() - t0) * 1000
            from ..spi.metrics import BROKER_METRICS, BrokerTimer

            BROKER_METRICS.update_timer(BrokerTimer.ADMISSION_WAIT_MS,
                                        wait_ms)
            if not ok:
                raise AdmissionRejectedError(
                    f"no broker capacity within deadline "
                    f"(waited {wait_ms:.0f}ms for one of "
                    f"{self.max_inflight} slots)")
        with self._lock:
            self._inflight += 1
        try:
            yield
        finally:
            with self._lock:
                self._inflight -= 1
            self._sem.release()


class QueryQuotaManager:
    """Sliding-window QPS enforcement per table (reference: HitCounter with
    per-second buckets)."""

    def __init__(self, window_s: float = 1.0):
        self.window_s = window_s
        self._lock = threading.Lock()
        self._limits: dict[str, float] = {}
        self._hits: dict[str, deque] = {}

    def set_qps_limit(self, table: str, qps: Optional[float]) -> None:
        with self._lock:
            if qps is None:
                self._limits.pop(table, None)
            else:
                self._limits[table] = float(qps)

    def acquire(self, table: str) -> None:
        """Record a hit; raises when the table is over its QPS quota."""
        with self._lock:
            limit = self._limits.get(table)
            if limit is None:
                return
            now = time.monotonic()
            dq = self._hits.setdefault(table, deque())
            while dq and now - dq[0] > self.window_s:
                dq.popleft()
            if len(dq) >= limit * self.window_s:
                raise QueryQuotaExceededError(
                    f"table {table} exceeded {limit} qps")
            dq.append(now)


class ResponseStore:
    """Spooled query results served page-by-page (reference:
    FsResponseStore + the broker's /resultStore cursor endpoints)."""

    def __init__(self, ttl_s: float = 300.0, max_entries: int = 256):
        self.ttl_s = ttl_s
        self.max_entries = max_entries
        self._lock = threading.Lock()
        self._store: dict[str, tuple[float, list, list, list]] = {}

    def create_cursor(self, column_names: list, column_types: list,
                      rows: list) -> str:
        cursor_id = uuid.uuid4().hex
        with self._lock:
            self._evict_locked()
            self._store[cursor_id] = (time.monotonic(), column_names,
                                      column_types, rows)
        return cursor_id

    def fetch(self, cursor_id: str, offset: int, num_rows: int) -> dict:
        with self._lock:
            entry = self._store.get(cursor_id)
        if entry is None:
            raise KeyError(f"cursor {cursor_id} not found or expired")
        _, names, types, rows = entry
        page = rows[offset:offset + num_rows]
        return {
            "resultTable": {
                "dataSchema": {"columnNames": names, "columnDataTypes": types},
                "rows": page},
            "offset": offset,
            "numRows": len(page),
            "totalRows": len(rows),
            "cursorId": cursor_id,
        }

    def delete(self, cursor_id: str) -> bool:
        with self._lock:
            return self._store.pop(cursor_id, None) is not None

    def _evict_locked(self) -> None:
        now = time.monotonic()
        dead = [k for k, (t, *_rest) in self._store.items()
                if now - t > self.ttl_s]
        for k in dead:
            del self._store[k]
        while len(self._store) >= self.max_entries:
            oldest = min(self._store, key=lambda k: self._store[k][0])
            del self._store[oldest]
