"""Self-healing capacity: durable, leader-gated, no-downtime segment moves.

Reference analogue: TableRebalancer (pinot-controller/.../helix/core/
rebalance/TableRebalancer.java) driving Helix ideal-state transitions with
ZK-persisted job context, plus RebalanceChecker resuming stuck jobs. The
controller's synchronous ``ClusterController.rebalance`` converges a whole
table in one blocking call; this module is the production actuation loop
layered on the same two-phase discipline:

- The PLAN is durable: ``/REBALANCE/{table}`` holds the target assignment
  plus one state-machine record per moved segment, journaled in the
  crash-consistent property store (cluster/store.py WAL). A controller
  failover resumes mid-rebalance from the journal instead of orphaning
  half-moved segments — the new leader's actuator just keeps ticking.
- Moves are strictly MAKE-BEFORE-BREAK: the destination deep-store-fetches,
  loads and integrity-verifies (ServerInstance._load_segment_verified, the
  PR-8 repair path) and shows ONLINE in the external view before the
  source replica leaves the ideal state. A segment's routable replica
  count never dips below its pre-move count.
- Per-move lifecycle::

      PENDING ──start──▶ ADDING ──dest ONLINE──▶ DROPPING ──▶ COMPLETED
         ▲                  │ timeout                 (resumed idempotently
         └───retry/backoff──┘                          after a crash)
               │ attempts exhausted: blacklist dest, repick or
               ▼
             FAILED                PENDING/ADDING ──abort──▶ CANCELLED

- Bounded concurrency (``PINOT_TPU_REBALANCE_MAX_MOVES`` in-flight moves),
  per-move retry with exponential backoff, destination blacklisted after
  ``PINOT_TPU_REBALANCE_RETRIES`` failed attempts and a replacement chosen.
- Target assignment is minimal-movement and replica-count-preserving, and
  weighs hosts by the PR-10 per-table cost rollups that brokers publish at
  ``/BROKERSTATE/*`` — hot segments are placed and spread FIRST so new
  capacity absorbs the expensive traffic before the cold tail moves.
- Each completed move bumps the table's ``/CACHEEPOCH`` lineage epoch
  (broker result-cache invalidation) and the departing server's converge
  drops its partials AND name-matched stacked batch-family views
  (DeviceSegmentCache.drop_named), so no cache tier serves from a
  moved-away segment.

Triggers (RebalanceActuator, registered as a leader-gated periodic task):
operator REST (``POST /tables/{t}/rebalance``, ``GET /debug/rebalance``,
abort via ``POST /tables/{t}/rebalance/abort``), automatic dead-server
rebuild and server-add spreading, and an opt-in health loop
(``PINOT_TPU_HEALTH_REBALANCE``) draining ``straggler``/``hbm-pressure``
instances under cooldown + hysteresis so it can never flap.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Optional

from ..spi.metrics import (CONTROLLER_METRICS, ControllerGauge,
                           ControllerMeter, ControllerTimer)
from .controller import CONSUMING, ERROR, ONLINE, ClusterController, \
    raw_table_name
from .store import BadVersionError, PropertyStore

log = logging.getLogger("pinot_tpu.rebalance")

REBALANCE_PREFIX = "/REBALANCE"
# durable last-seen live-server set for the server-add trigger, so a
# controller failover/restart still fires for servers added during the
# outage (deliberately OUTSIDE the job prefix: children(REBALANCE_PREFIX)
# must only ever yield table names)
SEEN_SERVERS_PATH = "/REBALANCEMETA/seenServers"

# process-wide per-(store, table) actuation locks, shared by every
# SegmentRebalancer wrapping the same store (the REST handler and the
# periodic actuator each build their own engine instance): only one
# thread may advance a table's move state machine at a time, so inline
# drive() and the actuator's tick() can't both act on one stale
# view/journal read
_LOCKS_GUARD = threading.Lock()

# job statuses
IN_PROGRESS = "IN_PROGRESS"
DONE = "DONE"
PARTIAL = "PARTIAL"          # finished, but some moves FAILED
ABORTING = "ABORTING"
ABORTED = "ABORTED"
ACTIVE_STATUSES = (IN_PROGRESS, ABORTING)

# per-move states
MOVE_PENDING = "PENDING"
MOVE_ADDING = "ADDING"
MOVE_DROPPING = "DROPPING"
MOVE_COMPLETED = "COMPLETED"
MOVE_FAILED = "FAILED"
MOVE_CANCELLED = "CANCELLED"
MOVE_TERMINAL = (MOVE_COMPLETED, MOVE_FAILED, MOVE_CANCELLED)


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


def _env_float(name: str, default: float) -> float:
    return float(os.environ.get(name, default))


class RebalanceInProgress(RuntimeError):
    """A durable rebalance job for the table is already active."""


def _is_engine_job(job: Optional[dict]) -> bool:
    """True for journal records this engine owns. The legacy blocking
    rebalance (ClusterController._apply_target_safely) shares the
    /REBALANCE/{table} path but never writes a movePlan — the engine must
    neither tick nor finalize those records, and the legacy path must not
    overwrite an active engine journal (it checks the same predicate)."""
    return job is not None and "movePlan" in job


class SegmentRebalancer:
    """Leader-gated, crash-resumable rebalance engine. Stateless between
    ticks by design: every decision re-reads the journaled job from the
    property store, so ANY controller holding the leader seat can advance
    any job — that is what makes failover resume free."""

    def __init__(self, controller: ClusterController,
                 max_moves: Optional[int] = None,
                 move_timeout_s: Optional[float] = None,
                 max_attempts: Optional[int] = None,
                 backoff_ms: Optional[float] = None):
        self.controller = controller
        self.store: PropertyStore = controller.store
        self.max_moves = max_moves if max_moves is not None else \
            max(1, _env_int("PINOT_TPU_REBALANCE_MAX_MOVES", 2))
        self.move_timeout_s = move_timeout_s if move_timeout_s is not None \
            else _env_float("PINOT_TPU_REBALANCE_MOVE_TIMEOUT_S", 30.0)
        self.max_attempts = max_attempts if max_attempts is not None else \
            max(1, _env_int("PINOT_TPU_REBALANCE_RETRIES", 3))
        self.backoff_ms = backoff_ms if backoff_ms is not None else \
            _env_float("PINOT_TPU_REBALANCE_BACKOFF_MS", 100.0)
        CONTROLLER_METRICS.set_gauge(ControllerGauge.REBALANCE_ACTIVE,
                                     self.active_jobs)

    def _table_lock(self, nwt: str) -> threading.Lock:
        """Per-(store, table) actuation lock shared across every engine
        instance in this process (REST builds one, the actuator another)."""
        with _LOCKS_GUARD:
            locks = getattr(self.store, "_rebalance_table_locks", None)
            if locks is None:
                locks = {}
                self.store._rebalance_table_locks = locks
            return locks.setdefault(nwt, threading.Lock())

    # -- observation ---------------------------------------------------------
    def job_path(self, nwt: str) -> str:
        return f"{REBALANCE_PREFIX}/{nwt}"

    def job(self, nwt: str) -> Optional[dict]:
        return self.store.get(self.job_path(nwt))

    def active_jobs(self) -> int:
        n = 0
        for table in self.store.children(REBALANCE_PREFIX):
            if (self.store.get(f"{REBALANCE_PREFIX}/{table}") or {}).get(
                    "status") in ACTIVE_STATUSES:
                n += 1
        return n

    def debug(self) -> dict:
        """GET /debug/rebalance: every journaled job, active first."""
        jobs = {t: self.store.get(f"{REBALANCE_PREFIX}/{t}")
                for t in self.store.children(REBALANCE_PREFIX)}
        return {
            "active": {t: j for t, j in jobs.items()
                       if (j or {}).get("status") in ACTIVE_STATUSES},
            "finished": {t: j for t, j in jobs.items()
                         if (j or {}).get("status") not in ACTIVE_STATUSES},
            "knobs": {
                "maxMoves": self.max_moves,
                "moveTimeoutS": self.move_timeout_s,
                "maxAttempts": self.max_attempts,
                "backoffMs": self.backoff_ms,
            },
        }

    # -- cost-aware target computation ---------------------------------------
    def table_heat(self) -> dict:
        """raw table → decayed expected query cost (ms), folded across
        every broker beacon at /BROKERSTATE/* (the PR-10 workload rollups).
        Empty when no broker publishes costs — weights then degrade to
        doc counts."""
        heat: dict[str, float] = {}
        for bid in self.store.children("/BROKERSTATE"):
            state = self.store.get(f"/BROKERSTATE/{bid}") or {}
            for table, cost in (state.get("tableCostsMs") or {}).items():
                try:
                    heat[table] = max(heat.get(table, 0.0), float(cost))
                except (TypeError, ValueError):
                    continue
        return heat

    def _segment_weights(self, nwt: str, ideal: dict,
                         heat: dict) -> dict[str, float]:
        """Move-ordering weight: docs scaled by table heat, so the hot
        table's big segments spread onto new capacity first."""
        factor = 1.0 + heat.get(raw_table_name(nwt), 0.0)
        weights = {}
        for seg in ideal:
            meta = self.store.get(f"/SEGMENTS/{nwt}/{seg}") or {}
            weights[seg] = max(1.0, float(meta.get("numDocs", 1))) * factor
        return weights

    def compute_target(self, nwt: str, exclude: frozenset = frozenset()
                       ) -> tuple[dict, dict, int]:
        """Minimal-movement, replica-count-preserving target.

        Returns (target, weights, moves). CONSUMING segments are frozen
        (moving an active consumer restarts consumption); replica-group
        tables delegate to the controller's group-aware math. ``exclude``
        drains instances (health loop) — refused when it would leave
        fewer candidates than the replication factor."""
        cfg = self.controller.table_config(nwt)
        if cfg is None:
            raise KeyError(nwt)
        self.controller._check_upsert_movable(nwt, cfg)
        ideal = self.store.get(f"/IDEALSTATES/{nwt}") or {}
        heat = self.table_heat()
        weights = self._segment_weights(nwt, ideal, heat)
        frozen = {s: dict(m) for s, m in ideal.items()
                  if CONSUMING in m.values()}
        movable = {s: m for s, m in ideal.items() if s not in frozen}

        if self.controller.instance_partitions(nwt):
            if exclude:
                raise RuntimeError(
                    f"{nwt}: cannot drain instances {sorted(exclude)} from "
                    "a replica-group table — group membership pins placement"
                )
            target, moves = self.controller._rebalance_target(
                nwt, cfg, movable)
            target.update(frozen)
            return target, weights, moves

        replication = int(cfg.get("replication", 1))
        candidates = sorted(
            (set(self.controller.server_instances(cfg.get("serverTag")))
             & set(self.controller.live_instances())) - set(exclude))
        if len(candidates) < replication:
            raise RuntimeError(
                f"{nwt}: {len(candidates)} usable servers "
                f"{candidates} < replication {replication}")
        # weighted load per host (hot tables dominate); count load keeps
        # the final spread levelled like the synchronous rebalancer
        wload = {i: 0.0 for i in candidates}
        cload = {i: 0 for i in candidates}
        target: dict[str, dict] = {}
        moves = 0
        hot_first = sorted(movable, key=lambda s: (-weights[s], s))
        for seg in hot_first:
            keep = [i for i in movable[seg] if i in candidates][:replication]
            target[seg] = {i: movable[seg][i] for i in keep}
            for i in keep:
                wload[i] += weights[seg]
                cload[i] += 1
        for seg in hot_first:
            state = ONLINE
            while len(target[seg]) < replication:
                pick = min((i for i in candidates if i not in target[seg]),
                           key=lambda i: (cload[i], wload[i], i))
                target[seg][pick] = state
                wload[pick] += weights[seg]
                cload[pick] += 1
                moves += 1
        # level counts (spread <= 1), shedding the HOTTEST movable replica
        # from the most-loaded host each step
        for _ in range(len(movable) * max(1, replication)):
            hi = max(candidates, key=lambda i: (cload[i], wload[i], i))
            lo = min(candidates, key=lambda i: (cload[i], wload[i], i))
            if cload[hi] - cload[lo] <= 1:
                break
            movable_here = [s for s in hot_first
                            if hi in target[s] and lo not in target[s]]
            if not movable_here:
                break
            seg = movable_here[0]
            target[seg][lo] = target[seg].pop(hi)
            wload[hi] -= weights[seg]
            wload[lo] += weights[seg]
            cload[hi] -= 1
            cload[lo] += 1
            moves += 1
        target.update(frozen)
        return target, weights, moves

    # -- planning ------------------------------------------------------------
    def plan(self, nwt: str, trigger: str = "rest",
             exclude: frozenset = frozenset(),
             dry_run: bool = False) -> Optional[dict]:
        """Compute and journal a durable rebalance job. Returns None when
        the table is already balanced; raises RebalanceInProgress when an
        active job exists (abort it first)."""
        existing, existing_version = self.store.get_with_version(
            self.job_path(nwt))
        if existing and existing.get("status") in ACTIVE_STATUSES:
            raise RebalanceInProgress(
                f"{nwt}: job {existing.get('jobId')} is "
                f"{existing.get('status')}")
        ideal = self.store.get(f"/IDEALSTATES/{nwt}") or {}
        target, weights, moves = self.compute_target(nwt, exclude=exclude)
        changed = [s for s in ideal
                   if set(target.get(s, {})) != set(ideal[s])]
        changed.sort(key=lambda s: (-weights.get(s, 1.0), s))
        now_ms = int(time.time() * 1000)
        move_plan = []
        for seg in changed:
            adds = {i: st for i, st in target[seg].items()
                    if i not in ideal[seg]}
            drops = sorted(i for i in ideal[seg] if i not in target[seg])
            move_plan.append({
                "segment": seg,
                "adds": adds,
                "drops": drops,
                "state": MOVE_PENDING,
                "attempts": 0,
                "blacklist": [],
                "weight": round(weights.get(seg, 1.0), 3),
            })
        job = {
            "jobId": f"rb_{now_ms}_{len(changed)}",
            "status": IN_PROGRESS if changed else DONE,
            "trigger": trigger,
            "startedMs": now_ms,
            "segmentsTotal": len(changed),
            "segmentsDone": 0,
            "moves": moves,
            "target": target,
            "movePlan": move_plan,
        }
        if not changed:
            job["finishedMs"] = now_ms
        if exclude:
            job["excluded"] = sorted(exclude)
        if dry_run:
            return job
        # CAS on the version read above: two planners racing past the
        # active check (e.g. REST on two controllers) cannot both journal —
        # the loser would silently overwrite a plan already being actuated
        try:
            if existing_version < 0:
                if not self.store.create_if_absent(self.job_path(nwt), job):
                    raise BadVersionError(self.job_path(nwt))
            else:
                self.store.set(self.job_path(nwt), job,
                               expected_version=existing_version)
        except BadVersionError:
            raise RebalanceInProgress(
                f"{nwt}: a concurrent plan journaled first") from None
        log.info("%s: journaled rebalance %s (%d segments, trigger=%s)",
                 nwt, job["jobId"], len(changed), trigger)
        return job

    # -- actuation -----------------------------------------------------------
    def tick(self) -> dict:
        """Advance every active job by at most one state transition per
        move. Safe to call from any controller; standbys no-op. Each tick
        re-reads the journal, so the loop is resumable at every point."""
        if not self.controller.is_leader():
            return {"skipped": "standby controller does not actuate"}
        report = {}
        for table in self.store.children(REBALANCE_PREFIX):
            job = self.store.get(f"{REBALANCE_PREFIX}/{table}")
            if not job or job.get("status") not in ACTIVE_STATUSES:
                continue
            if not _is_engine_job(job):
                # legacy blocking-rebalance record: its owner drives it
                # synchronously; finalizing or ticking it here would let
                # both engines mutate the table's ideal state at once
                continue
            try:
                with self._table_lock(table):
                    report[table] = self._tick_table(
                        table, self.job(table) or job)
            except Exception as e:  # one stuck table must not wedge others
                log.exception("%s: rebalance tick failed", table)
                report[table] = f"{type(e).__name__}: {e}"
        return report

    def drive(self, nwt: str, timeout_s: float = 30.0,
              tick_interval_s: float = 0.02) -> dict:
        """Synchronously tick one table's job to a terminal status (REST
        default mode + tests). The job stays durable throughout — killing
        the driver mid-way leaves a journal any leader resumes. Leader-only
        like tick(): a standby driving inline would actuate concurrently
        with the real leader's periodic actuator."""
        if not self.controller.is_leader():
            raise RuntimeError(
                f"{nwt}: standby controller does not actuate; the leader's "
                "RebalanceActuator drives the journaled job")
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            job = self.job(nwt)
            if not job or job.get("status") not in ACTIVE_STATUSES:
                return job or {"status": DONE, "segmentsTotal": 0}
            if not _is_engine_job(job):
                raise RebalanceInProgress(
                    f"{nwt}: journal holds a legacy blocking-rebalance job "
                    f"{job.get('jobId')} ({job.get('status')}); the engine "
                    "cannot drive it")
            with self._table_lock(nwt):
                job = self.job(nwt)
                if job and job.get("status") in ACTIVE_STATUSES:
                    self._tick_table(nwt, job)
            job = self.job(nwt)
            if job and job.get("status") in ACTIVE_STATUSES:
                time.sleep(tick_interval_s)
        raise TimeoutError(
            f"rebalance for {nwt} still {self.job(nwt).get('status')} "
            f"after {timeout_s}s")

    def run(self, nwt: str, trigger: str = "rest",
            timeout_s: float = 30.0) -> dict:
        """plan + drive: the synchronous operator entry point."""
        job = self.plan(nwt, trigger=trigger)
        if job is None or job.get("status") != IN_PROGRESS:
            return job
        return self.drive(nwt, timeout_s=timeout_s)

    def abort(self, nwt: str) -> dict:
        """Roll an active job back: in-flight destinations leave the ideal
        state (their replicas were additive, so availability only shrinks
        back to the pre-move set), pending moves cancel, completed moves
        stay (the segment already lives at its new home)."""
        def to_aborting(job):
            if job and job.get("status") == IN_PROGRESS:
                job["status"] = ABORTING
            return job

        self.store.update(self.job_path(nwt), to_aborting)
        job = self.job(nwt)
        # marking ABORTING is a durable request any controller may journal;
        # the rollback itself is actuation and stays leader-only (a standby
        # returns the ABORTING job and the leader's next tick rolls back)
        if job and job.get("status") == ABORTING \
                and self.controller.is_leader():
            with self._table_lock(nwt):
                job = self.job(nwt)
                if job and job.get("status") == ABORTING:
                    self._tick_table(nwt, job)
            job = self.job(nwt)
        return job

    # -- per-table state machine ---------------------------------------------
    def _tick_table(self, nwt: str, job: dict) -> dict:
        if job.get("status") == ABORTING:
            return self._abort_table(nwt, job)
        now_ms = int(time.time() * 1000)
        summary = {"advanced": 0, "started": 0, "retried": 0}
        plan = job.get("movePlan") or []
        view = self.store.get(f"/EXTERNALVIEW/{nwt}") or {}
        for idx, move in enumerate(plan):
            if move["state"] == MOVE_DROPPING:
                self._finish_move(nwt, idx, move)
                summary["advanced"] += 1
            elif move["state"] == MOVE_ADDING:
                summary["advanced"] += self._check_adding(
                    nwt, idx, move, view, now_ms)
        job = self.job(nwt) or job
        plan = job.get("movePlan") or []
        active = sum(1 for m in plan
                     if m["state"] in (MOVE_ADDING, MOVE_DROPPING))
        for idx, move in enumerate(plan):
            if active >= self.max_moves:
                break
            if move["state"] != MOVE_PENDING:
                continue
            if move.get("backoffUntilMs", 0) > now_ms:
                continue
            self._start_move(nwt, idx, move, now_ms)
            active += 1
            summary["started"] += 1
        self._maybe_finish_job(nwt)
        return summary

    def _start_move(self, nwt: str, idx: int, move: dict,
                    now_ms: int) -> None:
        """Phase 1: additive union — the destination joins the ideal state
        while every current replica stays. Availability can only grow."""
        seg = move["segment"]
        adds = dict(move["adds"])

        def add_union(ideal):
            ideal = ideal or {}
            if seg in ideal:  # deleted concurrently → nothing to move
                merged = dict(ideal[seg])
                merged.update(adds)
                ideal[seg] = merged
            return ideal

        self.store.update(f"/IDEALSTATES/{nwt}", add_union)
        if seg not in (self.store.get(f"/IDEALSTATES/{nwt}") or {}):
            self._update_move(nwt, idx, state=MOVE_CANCELLED,
                              error="segment deleted during rebalance")
            return
        first_attempt = move["attempts"] == 0
        self._update_move(nwt, idx, state=MOVE_ADDING,
                          attempts=move["attempts"] + 1,
                          attemptStartedMs=now_ms,
                          startedMs=move.get("startedMs", now_ms))
        if first_attempt:
            CONTROLLER_METRICS.add_meter(
                ControllerMeter.SEGMENT_MOVES_STARTED)

    def _check_adding(self, nwt: str, idx: int, move: dict, view: dict,
                      now_ms: int) -> int:
        """Destination ONLINE in the external view → break the source;
        timeout → retry with backoff, blacklisting after exhaustion."""
        seg = move["segment"]
        ev = view.get(seg) or {}
        wanted = [i for i, st in move["adds"].items() if st == ONLINE]
        if wanted and all(ev.get(i) == ONLINE for i in wanted):
            self._update_move(nwt, idx, state=MOVE_DROPPING)
            move = dict(move, state=MOVE_DROPPING)
            self._finish_move(nwt, idx, move)
            return 1
        if not wanted:
            # pure-drop move (e.g. shrinking onto fewer replicas): nothing
            # to wait for, the remaining replicas are already serving
            self._update_move(nwt, idx, state=MOVE_DROPPING)
            self._finish_move(nwt, idx, dict(move, state=MOVE_DROPPING))
            return 1
        elapsed_ms = now_ms - move.get("attemptStartedMs", now_ms)
        errored = [i for i in wanted if ev.get(i) == ERROR]
        if elapsed_ms < self.move_timeout_s * 1000:
            return 0
        self._retry_move(nwt, idx, move, now_ms,
                         reason=f"destination not ONLINE after "
                                f"{elapsed_ms}ms"
                                + (f" (ERROR on {errored})" if errored
                                   else ""))
        return 0

    def _retry_move(self, nwt: str, idx: int, move: dict, now_ms: int,
                    reason: str) -> None:
        seg = move["segment"]
        adds = dict(move["adds"])

        def remove_adds(ideal):
            ideal = ideal or {}
            if seg in ideal:
                for inst in adds:
                    # make-before-break: the destination never served, so
                    # retracting it cannot dip availability
                    ideal[seg].pop(inst, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{nwt}", remove_adds)
        attempts = move["attempts"]
        if attempts < self.max_attempts:
            backoff = self.backoff_ms * (2 ** max(0, attempts - 1))
            self._update_move(nwt, idx, state=MOVE_PENDING,
                              backoffUntilMs=now_ms + int(backoff),
                              error=reason)
            return
        # attempts exhausted: blacklist the destination and repick —
        # honouring the job's drained instances (a health-drain job must
        # never repick the straggler it exists to empty)
        blacklist = sorted(set(move.get("blacklist", [])) | set(adds))
        excluded = set((self.job(nwt) or {}).get("excluded", ()))
        ideal_now = (self.store.get(f"/IDEALSTATES/{nwt}") or {}).get(seg, {})
        cfg = self.controller.table_config(nwt) or {}
        candidates = sorted(
            set(self.controller.server_instances(cfg.get("serverTag")))
            & set(self.controller.live_instances()))
        fresh = [i for i in candidates
                 if i not in blacklist and i not in ideal_now
                 and i not in excluded]
        if not fresh:
            self._update_move(nwt, idx, state=MOVE_FAILED,
                              blacklist=blacklist,
                              error=f"{reason}; no replacement destination "
                                    f"outside blacklist {blacklist}",
                              finishedMs=now_ms)
            CONTROLLER_METRICS.add_meter(
                ControllerMeter.SEGMENT_MOVES_FAILED)
            log.error("%s: move of %s FAILED (%s)", nwt, seg, reason)
            return
        state = next(iter(adds.values()), ONLINE)
        replacement = {fresh[0]: state}
        self._update_move(nwt, idx, state=MOVE_PENDING, attempts=0,
                          adds=replacement, blacklist=blacklist,
                          backoffUntilMs=now_ms + int(self.backoff_ms),
                          error=f"{reason}; destination blacklisted, "
                                f"retrying via {fresh[0]}")
        log.warning("%s: move of %s blacklisted %s, repicked %s",
                    nwt, seg, sorted(adds), fresh[0])

    def _finish_move(self, nwt: str, idx: int, move: dict) -> None:
        """Phase 2 (journaled as DROPPING first, so a crash between the
        journal write and the ideal-state update replays this idempotent
        step): retract the departing replicas, bump the table's cache
        lineage epoch, and mark the move COMPLETED."""
        seg = move["segment"]
        drops = list(move.get("drops", []))

        def break_source(ideal):
            ideal = ideal or {}
            if seg in ideal:
                for inst in drops:
                    ideal[seg].pop(inst, None)
            return ideal

        self.store.update(f"/IDEALSTATES/{nwt}", break_source)
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, nwt)
        now_ms = int(time.time() * 1000)
        self._update_move(nwt, idx, state=MOVE_COMPLETED,
                          finishedMs=now_ms, error=None)
        CONTROLLER_METRICS.add_meter(ControllerMeter.SEGMENT_MOVES_COMPLETED)
        CONTROLLER_METRICS.update_timer(
            ControllerTimer.SEGMENT_MOVE_MS,
            max(0.0, now_ms - move.get("startedMs", now_ms)))

    def _abort_table(self, nwt: str, job: dict) -> dict:
        cancelled = 0
        for idx, move in enumerate(job.get("movePlan") or []):
            if move["state"] in MOVE_TERMINAL:
                continue
            if move["state"] == MOVE_DROPPING:
                # past the point of no return: the destination is serving,
                # finishing is the rollback-safe direction
                self._finish_move(nwt, idx, move)
                continue
            if move["state"] == MOVE_ADDING:
                seg, adds = move["segment"], dict(move["adds"])

                def remove_adds(ideal):
                    ideal = ideal or {}
                    if seg in ideal:
                        for inst in adds:
                            ideal[seg].pop(inst, None)
                    return ideal

                self.store.update(f"/IDEALSTATES/{nwt}", remove_adds)
            self._update_move(nwt, idx, state=MOVE_CANCELLED,
                              finishedMs=int(time.time() * 1000))
            cancelled += 1
        from ..cache.results import bump_lineage_epoch

        bump_lineage_epoch(self.store, nwt)

        def finish(j):
            if j and j.get("status") == ABORTING:
                j["status"] = ABORTED
                j["finishedMs"] = int(time.time() * 1000)
            return j

        self.store.update(self.job_path(nwt), finish)
        log.info("%s: rebalance aborted (%d moves rolled back)", nwt,
                 cancelled)
        return {"aborted": cancelled}

    def _maybe_finish_job(self, nwt: str) -> None:
        def finalize(job):
            if not job or job.get("status") != IN_PROGRESS:
                return job
            if not _is_engine_job(job):
                # legacy blocking-rebalance record mid-flight: finalizing
                # it to DONE here would defeat the RebalanceInProgress
                # guard and let both engines mutate the ideal state
                return job
            plan = job.get("movePlan") or []
            if any(m["state"] not in MOVE_TERMINAL for m in plan):
                job["segmentsDone"] = sum(
                    1 for m in plan if m["state"] == MOVE_COMPLETED)
                return job
            failed = [m["segment"] for m in plan
                      if m["state"] == MOVE_FAILED]
            job["segmentsDone"] = sum(
                1 for m in plan if m["state"] == MOVE_COMPLETED)
            job["status"] = PARTIAL if failed else DONE
            if failed:
                job["failedSegments"] = failed
            job["finishedMs"] = int(time.time() * 1000)
            return job

        self.store.update(self.job_path(nwt), finalize)

    def _update_move(self, nwt: str, idx: int, **fields) -> None:
        def upd(job):
            if not job:
                return job
            plan = job.get("movePlan") or []
            if idx < len(plan):
                for k, v in fields.items():
                    if v is None:
                        plan[idx].pop(k, None)
                    else:
                        plan[idx][k] = v
            return job

        self.store.update(self.job_path(nwt), upd)


class RebalanceActuator:
    """The leader-gated periodic task wrapping the engine: ticks active
    jobs forward and fires the automatic triggers.

    - dead-server: a table whose ideal state references non-live instances
      gets a durable rebuild job (replicas re-fetch from deep store).
    - server-add: when NEW servers join the live set, tables whose dry-run
      plan has moves spread onto them.
    - health loop (opt-in, ``PINOT_TPU_HEALTH_REBALANCE``): drains the
      instance named by ``straggler``/``hbm-pressure`` anomalies in the
      leader's /HEALTH/cluster snapshot — only after the anomaly persists
      ``PINOT_TPU_REBALANCE_HYSTERESIS`` consecutive scrapes, and never
      within ``PINOT_TPU_REBALANCE_COOLDOWN_S`` of the last health-driven
      job, so a borderline server can't make the cluster flap."""

    def __init__(self, rebalancer: SegmentRebalancer):
        self.rebalancer = rebalancer
        self.controller = rebalancer.controller
        self.store = rebalancer.store
        self._seen_servers: Optional[set] = None
        # instance → consecutive health scrapes naming it
        self._anomaly_streak: dict[str, int] = {}
        self._last_health_checked_ms = 0
        self._last_health_trigger = 0.0

    def __call__(self) -> dict:
        if not self.controller.is_leader():
            return {"skipped": "standby controller does not actuate"}
        report = {"ticked": self.rebalancer.tick()}
        try:
            report["auto"] = self._auto_triggers()
        except Exception as e:
            report["auto"] = f"{type(e).__name__}: {e}"
        try:
            report["health"] = self._health_loop()
        except Exception as e:
            report["health"] = f"{type(e).__name__}: {e}"
        return report

    # -- membership-driven triggers ------------------------------------------
    def _auto_triggers(self) -> dict:
        live = set(self.controller.live_instances())
        if self._seen_servers is None:
            # fresh actuator (controller restart/failover): baseline from
            # the durable last-seen set, so servers added DURING the outage
            # still fire a server-add spread on the first leader tick —
            # only the very first actuator in a cluster's life has nothing
            # to compare against
            stored = self.store.get(SEEN_SERVERS_PATH)
            self._seen_servers = set(stored) if stored is not None else None
        added = set() if self._seen_servers is None \
            else live - self._seen_servers
        self._seen_servers = live
        if self.store.get(SEEN_SERVERS_PATH) != sorted(live):
            self.store.set(SEEN_SERVERS_PATH, sorted(live))
        out: dict[str, str] = {}
        for nwt in self.store.children("/CONFIGS/TABLE"):
            job = self.rebalancer.job(nwt)
            if job and job.get("status") in ACTIVE_STATUSES:
                continue
            ideal = self.store.get(f"/IDEALSTATES/{nwt}") or {}
            if not ideal:
                continue
            cfg = self.controller.table_config(nwt) or {}
            replication = int(cfg.get("replication", 1))
            dead_refs = any(
                sum(1 for i in m if i in live) < min(replication, len(m))
                for m in ideal.values())
            trigger = None
            if dead_refs and len(live) >= replication:
                trigger = "dead-server"
            elif added:
                try:
                    dry = self.rebalancer.plan(nwt, dry_run=True,
                                               trigger="server-add")
                except (RebalanceInProgress, RuntimeError, KeyError):
                    dry = None
                if dry and dry.get("segmentsTotal", 0) > 0:
                    trigger = "server-add"
            if trigger is None:
                continue
            try:
                job = self.rebalancer.plan(nwt, trigger=trigger)
            except (RebalanceInProgress, RuntimeError) as e:
                out[nwt] = f"skipped: {e}"
                continue
            if job and job.get("status") == IN_PROGRESS:
                out[nwt] = f"{trigger}:{job['jobId']}"
        return out

    # -- health-driven drain -------------------------------------------------
    @staticmethod
    def _health_enabled() -> bool:
        return os.environ.get("PINOT_TPU_HEALTH_REBALANCE", "").lower() \
            in ("1", "true", "yes", "on")

    def _health_loop(self) -> dict:
        if not self._health_enabled():
            return {"enabled": False}
        from .periodic import HEALTH_REPORT_PATH

        snap = self.store.get(HEALTH_REPORT_PATH) or {}
        checked = int(snap.get("checkedAtMs", 0))
        out: dict = {"enabled": True, "triggered": {}}
        if checked <= self._last_health_checked_ms:
            return out  # same scrape as last tick: no new evidence
        self._last_health_checked_ms = checked
        hysteresis = max(1, _env_int("PINOT_TPU_REBALANCE_HYSTERESIS", 2))
        cooldown_s = _env_float("PINOT_TPU_REBALANCE_COOLDOWN_S", 300.0)
        flagged = {a.get("instance") for a in snap.get("anomalies", ())
                   if a.get("type") in ("straggler", "hbm-pressure")
                   and a.get("instance")}
        for inst in list(self._anomaly_streak):
            if inst not in flagged:
                del self._anomaly_streak[inst]
        for inst in flagged:
            self._anomaly_streak[inst] = self._anomaly_streak.get(inst, 0) + 1
        out["streaks"] = dict(self._anomaly_streak)
        if time.monotonic() - self._last_health_trigger < cooldown_s \
                and self._last_health_trigger > 0:
            out["cooldown"] = True
            return out
        ripe = sorted(i for i, n in self._anomaly_streak.items()
                      if n >= hysteresis)
        if not ripe:
            return out
        victim = ripe[0]  # one drain at a time — the opposite of flapping
        live = set(self.controller.live_instances())
        for nwt in self.store.children("/CONFIGS/TABLE"):
            ideal = self.store.get(f"/IDEALSTATES/{nwt}") or {}
            if not any(victim in m for m in ideal.values()):
                continue
            cfg = self.controller.table_config(nwt) or {}
            if len(live - {victim}) < int(cfg.get("replication", 1)):
                continue  # draining would break replication: refuse
            job = self.rebalancer.job(nwt)
            if job and job.get("status") in ACTIVE_STATUSES:
                continue
            try:
                planned = self.rebalancer.plan(
                    nwt, trigger="health", exclude=frozenset({victim}))
            except (RebalanceInProgress, RuntimeError):
                continue
            if planned and planned.get("status") == IN_PROGRESS:
                out["triggered"][nwt] = planned["jobId"]
        if out["triggered"]:
            self._last_health_trigger = time.monotonic()
            self._anomaly_streak.pop(victim, None)
        return out
