"""Rule-based config recommender.

Reference: pinot-controller/.../recommender/ (RecommenderDriver + rule
engine: InvertedSortedIndexJointRule, BloomFilterRule, NoDictionaryOnHeapRule,
AggregateMetricsRule, KafkaPartitionRule...). Input: the table schema, a
sample of query patterns with frequencies, and data characteristics
(cardinalities, qps); output: recommended indexing/partitioning config with
per-recommendation rationale.

Input shape::

    recommend(
        schema,                       # spi Schema
        queries=[{"sql"| parsed parts..., "freq": 0.5}, ...]  OR
        query_stats={"eq_filters": {"col": weight}, "range_filters": {...},
                     "group_by": {...}, "aggregations": ["sum(v)", ...]},
        cardinalities={"col": n_distinct},
        num_rows=..., qps=...)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..query.parser.sql import SqlParseError, parse_sql
from ..query.filter import FilterContext, FilterNodeType, PredicateType

# rule thresholds (reference RecommenderConstants)
INVERTED_MAX_CARD_FRACTION = 0.3   # dict id postings pay off below this
BLOOM_MIN_CARD = 10_000            # bloom pruning needs high cardinality
NO_DICT_CARD_FRACTION = 0.7        # mostly-unique strings: dict is waste
SORTED_MIN_WEIGHT = 0.4            # dominant filter column gets the sort
STAR_TREE_MIN_GROUP_WEIGHT = 0.3
RANGE_MIN_WEIGHT = 0.05
INVERTED_MIN_WEIGHT = 0.05


@dataclass
class Recommendation:
    indexing: dict = field(default_factory=dict)
    partition_column: Optional[str] = None
    rationale: list = field(default_factory=list)

    def to_json(self) -> dict:
        return {"tableIndexConfig": self.indexing,
                "partitionColumn": self.partition_column,
                "rationale": self.rationale}


def _collect_filter_weights(f: Optional[FilterContext], freq: float,
                            eq: dict, rng: dict) -> None:
    if f is None:
        return
    if f.type == FilterNodeType.PREDICATE:
        p = f.predicate
        if not p.lhs.is_identifier:
            return
        col = p.lhs.identifier
        if p.type in (PredicateType.EQ, PredicateType.IN,
                      PredicateType.NOT_EQ, PredicateType.NOT_IN):
            eq[col] = eq.get(col, 0.0) + freq
        elif p.type == PredicateType.RANGE:
            rng[col] = rng.get(col, 0.0) + freq
        return
    for c in f.children:
        _collect_filter_weights(c, freq, eq, rng)


def analyze_queries(queries: list[dict]) -> dict:
    """[{sql, freq}] → aggregated pattern stats (the recommender's input
    extraction — reference: QueryInvertedSortedIndexRecommender parsing)."""
    eq: dict[str, float] = {}
    rng: dict[str, float] = {}
    group: dict[str, float] = {}
    aggs: set[str] = set()
    for q in queries:
        freq = float(q.get("freq", 1.0))
        try:
            ctx = parse_sql(q["sql"])
        except SqlParseError:
            continue
        _collect_filter_weights(ctx.filter, freq, eq, rng)
        for g in ctx.group_by_expressions:
            if g.is_identifier:
                group[g.identifier] = group.get(g.identifier, 0.0) + freq
        for a in ctx.aggregations:
            aggs.add(str(a))
    total = sum(float(q.get("freq", 1.0)) for q in queries) or 1.0
    return {
        "eq_filters": {c: w / total for c, w in eq.items()},
        "range_filters": {c: w / total for c, w in rng.items()},
        "group_by": {c: w / total for c, w in group.items()},
        "aggregations": sorted(aggs),
    }


def recommend(schema, queries: Optional[list[dict]] = None,
              query_stats: Optional[dict] = None,
              cardinalities: Optional[dict] = None,
              num_rows: int = 1_000_000, qps: float = 10.0) -> Recommendation:
    stats = query_stats if query_stats is not None else \
        analyze_queries(queries or [])
    cards = cardinalities or {}
    rec = Recommendation()
    idx = rec.indexing
    eq = stats.get("eq_filters", {})
    rng = stats.get("range_filters", {})
    group = stats.get("group_by", {})
    aggs = stats.get("aggregations", [])

    def card(col: str) -> int:
        return int(cards.get(col, num_rows // 10))

    dims = set(schema.dimension_names())

    # sorted column: the single dominant equality filter (reference
    # InvertedSortedIndexJointRule picks sorted for the top column)
    sorted_col = None
    if eq:
        top, w = max(eq.items(), key=lambda kv: kv[1])
        if w >= SORTED_MIN_WEIGHT and top in dims:
            sorted_col = top
            idx["sortedColumn"] = top
            rec.rationale.append(
                f"sortedColumn={top}: dominates equality filters "
                f"(weight {w:.2f}) — sorted runs give range-slice filtering")

    inverted, blooms, ranges = [], [], []
    for col, w in sorted(eq.items(), key=lambda kv: -kv[1]):
        if col == sorted_col or w < INVERTED_MIN_WEIGHT:
            continue
        c = card(col)
        if c <= num_rows * INVERTED_MAX_CARD_FRACTION:
            inverted.append(col)
            rec.rationale.append(
                f"invertedIndex on {col}: equality weight {w:.2f}, "
                f"cardinality {c} — postings beat scans")
        if c >= BLOOM_MIN_CARD:
            blooms.append(col)
            rec.rationale.append(
                f"bloomFilter on {col}: cardinality {c} — prunes segments "
                f"on point lookups")
    for col, w in sorted(rng.items(), key=lambda kv: -kv[1]):
        if w >= RANGE_MIN_WEIGHT:
            ranges.append(col)
            rec.rationale.append(
                f"rangeIndex on {col}: range-filter weight {w:.2f}")
    if inverted:
        idx["invertedIndexColumns"] = inverted
    if blooms:
        idx["bloomFilterColumns"] = blooms
    if ranges:
        idx["rangeIndexColumns"] = ranges

    # no-dictionary for mostly-unique strings never used in group-by/eq
    no_dict = []
    for col in dims:
        if col in eq or col in group or col == sorted_col:
            continue
        if card(col) >= num_rows * NO_DICT_CARD_FRACTION:
            no_dict.append(col)
            rec.rationale.append(
                f"noDictionary + LZ4 on {col}: ~unique values make the "
                f"dictionary pure overhead")
    if no_dict:
        idx["noDictionaryColumns"] = sorted(no_dict)
        idx["compressionConfigs"] = {c: "LZ4" for c in sorted(no_dict)}

    # star-tree for heavy repeated group-by over low-card dims
    st_dims = [c for c, w in sorted(group.items(), key=lambda kv: -kv[1])
               if w >= STAR_TREE_MIN_GROUP_WEIGHT and card(c) <= 10_000]
    if st_dims and aggs:
        idx["starTreeIndexConfigs"] = [{
            "dimensionsSplitOrder": st_dims,
            "functionColumnPairs": aggs,
        }]
        rec.rationale.append(
            f"star-tree over {st_dims}: group-by weight ≥ "
            f"{STAR_TREE_MIN_GROUP_WEIGHT} and qps {qps} amortize the "
            f"pre-aggregation")

    # partitioning: route point lookups to one server
    if eq:
        top, w = max(eq.items(), key=lambda kv: kv[1])
        if card(top) >= 100:
            rec.partition_column = top
            rec.rationale.append(
                f"partition on {top}: equality-heavy — the broker prunes "
                f"partitions per query")
    return rec
