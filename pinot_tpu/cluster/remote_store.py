"""Networked property store: the cluster metadata plane across OS processes.

Reference analogue: ZooKeeper. The in-memory PropertyStore (store.py) plays
ZK's role for roles hosted in one process; `PropertyStoreServer` exposes it
over the framed-TCP RPC plane so roles in *other OS processes* join the same
cluster through a `RemoteStore` proxy with the identical interface
(get/set/CAS/children/ephemerals/watches).

Watches are poll-based: every mutation appends to a bounded event log with a
monotonically increasing sequence number; remote clients long-poll
``("poll", since)`` from a background thread and dispatch matching callbacks
locally. That trades watch latency (~poll interval) for a wire protocol with
no server→client channel — acceptable where ZK delivers watch events
asynchronously anyway.

CAS (`update`) runs client-side: read version, apply fn locally, write with
expected_version, retry on BadVersionError — the same ZkBaseDataAccessor
pattern, over the wire.
"""

from __future__ import annotations

import json
import threading
import time
from typing import Any, Callable, Optional

from .store import BadVersionError, PropertyStore, StoreError
from .transport import RemoteError, RpcClient, RpcServer

_MAX_EVENTS = 100_000


class PropertyStoreServer:
    """Wraps a PropertyStore with an RPC endpoint + change event log."""

    def __init__(self, store: Optional[PropertyStore] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.store = store if store is not None else PropertyStore()
        self._events: list[tuple[int, str, Any]] = []
        self._seq = 0
        self._lock = threading.Lock()
        self.store.watch("/", self._on_change)
        self._rpc = RpcServer(self._handle, host, port)

    @property
    def address(self) -> tuple[str, int]:
        return (self._rpc.host, self._rpc.port)

    def close(self) -> None:
        self._rpc.close()
        try:
            # a closed server must not keep accumulating the shared
            # store's events (or be pinned by its watch list)
            self.store.unwatch(self._on_change)
        except AttributeError:
            pass

    def _on_change(self, path: str, value) -> None:
        with self._lock:
            self._seq += 1
            self._events.append((self._seq, path, value))
            if len(self._events) > _MAX_EVENTS:
                del self._events[: _MAX_EVENTS // 10]

    def _handle(self, request):
        op = request[0]
        args = request[1:]
        if op == "get":
            return self.store.get(*args)
        if op == "get_with_version":
            return self.store.get_with_version(*args)
        if op == "set":
            path, value, expected_version, ephemeral_owner = args
            return self.store.set(path, value, expected_version, ephemeral_owner)
        if op == "delete":
            return self.store.delete(*args)
        if op == "create_if_absent":
            return self.store.create_if_absent(*args)
        if op == "children":
            return self.store.children(*args)
        if op == "list_paths":
            return self.store.list_paths(*args)
        if op == "expire_session":
            return self.store.expire_session(*args)
        if op == "poll":
            (since,) = args
            with self._lock:
                first = self._events[0][0] if self._events else self._seq + 1
                if since is None:
                    return self._seq, [], first
                return (self._seq,
                        [e for e in self._events if e[0] > since], first)
        raise ValueError(f"unknown store op {op!r}")


class RemoteStore:
    """PropertyStore-compatible client proxy over the RPC plane."""

    POLL_INTERVAL_S = 0.03

    def __init__(self, host: str, port: int):
        self._client = RpcClient(host, port)
        self._watches: list[tuple[str, Callable[[str, Optional[Any]], None]]] = []
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._poller: Optional[threading.Thread] = None
        self._last_seq: Optional[int] = None
        # paths this client has observed via events — lets a gap resync
        # deliver deletions that happened inside the trimmed window
        self._known_paths: set[str] = set()

    # -- basic ops ---------------------------------------------------------
    def _call(self, *request):
        try:
            return self._client.call(request)
        except RemoteError as e:
            msg = str(e)
            if msg.startswith("BadVersionError"):
                raise BadVersionError(msg) from None
            if msg.startswith(("StoreError", "KeyError", "ValueError")):
                raise StoreError(msg) from None
            raise

    def set(self, path: str, value: Any, expected_version: int = -1,
            ephemeral_owner: Optional[str] = None) -> int:
        json.dumps(value)
        return self._call("set", path, value, expected_version, ephemeral_owner)

    def get(self, path: str) -> Optional[Any]:
        return self._call("get", path)

    def get_with_version(self, path: str) -> tuple[Optional[Any], int]:
        value, version = self._call("get_with_version", path)
        return value, version

    def delete(self, path: str) -> bool:
        return self._call("delete", path)

    def create_if_absent(self, path: str, value: Any,
                         ephemeral_owner: Optional[str] = None) -> bool:
        return self._call("create_if_absent", path, value, ephemeral_owner)

    def children(self, prefix: str) -> list[str]:
        return self._call("children", prefix)

    def list_paths(self, prefix: str) -> list[str]:
        return self._call("list_paths", prefix)

    def expire_session(self, owner: str) -> None:
        self._call("expire_session", owner)

    # -- watches -----------------------------------------------------------
    def unwatch(self, callback: Callable) -> None:
        with self._lock:
            # equality, not identity: bound methods are re-created per
            # access, so `is` would never match
            self._watches = [(p, cb) for p, cb in self._watches
                             if cb != callback]

    def watch(self, prefix: str, callback: Callable[[str, Optional[Any]], None]) -> None:
        with self._lock:
            self._watches.append((prefix, callback))
            if self._poller is None:
                self._last_seq = self._call("poll", None)[0]
                self._poller = threading.Thread(
                    target=self._poll_loop, name="remote-store-poll", daemon=True)
                self._poller.start()

    def _poll_loop(self) -> None:
        while not self._closed.is_set():
            try:
                seq, events, first = self._call("poll", self._last_seq)
            except Exception:
                if self._closed.is_set():
                    return
                time.sleep(0.2)
                continue
            if self._last_seq is not None and self._last_seq + 1 < first \
                    and seq > self._last_seq:
                # the server trimmed events we never saw: resync every
                # watched prefix from current state instead of silently
                # missing transitions (ZK watchers re-read after gaps too)
                self._last_seq = seq
                self._resync()
                continue
            self._last_seq = seq
            for _, path, value in events:
                with self._lock:
                    targets = [cb for prefix, cb in self._watches
                               if path.startswith(prefix)]
                    if value is None:
                        self._known_paths.discard(path)
                    else:
                        self._known_paths.add(path)
                for cb in targets:
                    try:
                        cb(path, value)
                    except Exception:
                        pass
            self._closed.wait(self.POLL_INTERVAL_S)

    def _resync(self) -> None:
        """Re-deliver current state for every watched prefix after an event
        gap — including deletions: paths this client has seen that no longer
        exist fire cb(path, None)."""
        with self._lock:
            watches = list(self._watches)
            known = set(self._known_paths)
        for prefix, cb in watches:
            try:
                live = set(self._call("list_paths", prefix))
            except Exception:
                continue
            for path in sorted(known):
                if path.startswith(prefix) and path not in live:
                    with self._lock:
                        self._known_paths.discard(path)
                    try:
                        cb(path, None)
                    except Exception:
                        pass
            for path in sorted(live):
                with self._lock:
                    self._known_paths.add(path)
                try:
                    cb(path, self._call("get", path))
                except Exception:
                    pass

    # -- transactional helpers ---------------------------------------------
    def update(self, path: str, fn: Callable[[Optional[Any]], Any],
               max_retries: int = 20) -> Any:
        for _ in range(max_retries):
            cur, version = self.get_with_version(path)
            new = fn(json.loads(json.dumps(cur)) if cur is not None else None)
            try:
                self.set(path, new, expected_version=version)
                return new
            except BadVersionError:
                continue
        raise StoreError(f"update contention on {path}")

    def close(self) -> None:
        self._closed.set()
        self._client.close()
