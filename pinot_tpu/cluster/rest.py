"""HTTP REST layer for broker + controller roles.

Reference analogue: the broker's Jersey resources
(pinot-broker/.../api/resources/PinotClientRequest.java — POST /query/sql)
and the controller's 62 JAX-RS resources (pinot-controller/.../api/
resources/: tables, schemas, segments, rebalance). stdlib http.server keeps
the surface dependency-free; handlers delegate to the same objects the
in-proc tests drive.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from .broker import Broker
from .controller import ClusterController, table_name_with_type


def _referenced_tables(sql: str):
    """Raw table names a query reads, via the real parsers; None when the
    SQL cannot be parsed (callers deny for table-scoped principals)."""
    from ..query.parser.sql import SqlParseError, parse_sql
    from .controller import raw_table_name

    try:
        return {raw_table_name(parse_sql(sql).table_name)}
    except SqlParseError:
        pass
    try:
        from ..mse.ast import JoinRel, SetOpStmt, SubqueryRef, TableRef
        from ..mse.parser import parse_relational

        tables = set()

        def walk_rel(rel):
            if rel is None:
                return
            if isinstance(rel, TableRef):
                tables.add(rel.name)
            elif isinstance(rel, SubqueryRef):
                walk_stmt(rel.query)
            elif isinstance(rel, JoinRel):
                walk_rel(rel.left)
                walk_rel(rel.right)

        def walk_stmt(stmt):
            if isinstance(stmt, SetOpStmt):
                walk_stmt(stmt.left)
                walk_stmt(stmt.right)
                return
            walk_rel(getattr(stmt, "from_rel", None))

        walk_stmt(parse_relational(sql).statement)
        return tables
    except Exception:
        return None


class RawHtml(str):
    """Marker: a handler returning this gets text/html instead of JSON."""


class RawText(str):
    """Marker: a handler returning this gets Prometheus text exposition
    content-type instead of JSON (the /metrics routes)."""


class _JsonHandler(BaseHTTPRequestHandler):
    routes_get: list = []
    routes_post: list = []
    routes_delete: list = []

    def log_message(self, fmt, *args):  # quiet
        pass

    def _reply(self, code: int, payload) -> None:
        if isinstance(payload, RawHtml):
            body = str(payload).encode("utf-8")
            ctype = "text/html; charset=utf-8"
        elif isinstance(payload, RawText):
            from ..spi.metrics import PROMETHEUS_CONTENT_TYPE

            body = str(payload).encode("utf-8")
            ctype = PROMETHEUS_CONTENT_TYPE
        else:
            body = json.dumps(payload).encode("utf-8")
            ctype = "application/json"
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self):
        n = int(self.headers.get("Content-Length", 0))
        if n == 0:
            return {}
        return json.loads(self.rfile.read(n).decode("utf-8"))

    # set by the owning _RestServer; None → AllowAll (no auth layer)
    access_control = None

    def _dispatch(self, routes, access_type: str = "READ") -> None:
        from .auth import AllowAllAccessControl

        parsed = urlparse(self.path)
        ac = self.access_control
        self.principal = None
        routes = [r if len(r) == 3 else (r[0], r[1], access_type)
                  for r in routes]
        # health + metrics endpoints (incl. /health/liveness, /health/
        # readiness) are auth-exempt: orchestrator probes and Prometheus
        # scrapers carry no credentials (reference: health resources sit
        # outside the auth filter)
        if ac is not None and not isinstance(ac, AllowAllAccessControl) \
                and parsed.path not in ("/health", "/metrics") \
                and not parsed.path.startswith("/health/"):
            self.principal = ac.authenticate(self.headers)
            if self.principal is None:
                self.send_response(401)
                self.send_header("WWW-Authenticate", "Basic realm=\"pinot\"")
                self.end_headers()
                return
            # per-table refinement happens in the endpoints; here the
            # principal must hold the access TYPE at all (reference:
            # AccessControlUtils.validatePermission)
            for pattern, _fn, atype in routes:
                m = re.fullmatch(pattern, parsed.path)
                if m:
                    if atype not in self.principal.permissions:
                        self._reply(403,
                                    {"error": f"{atype} not permitted"})
                        return
                    # table-resource routes: first group is the table name
                    table = m.group(1) if m.groups() and pattern.startswith(
                        (r"/tables/", r"/segments/", r"/schemas/")) else None
                    if table and not self.principal.allows(table, atype):
                        self._reply(403, {
                            "error": f"{atype} on {table} not permitted"})
                        return
                    break
        for pattern, fn, _atype in routes:
            m = re.fullmatch(pattern, parsed.path)
            if m:
                try:
                    code, payload = fn(self, m, parse_qs(parsed.query))
                except Exception as e:  # surface as HTTP 500 JSON
                    code, payload = 500, {"error": f"{type(e).__name__}: {e}"}
                self._reply(code, payload)
                return
        self._reply(404, {"error": f"no route for {parsed.path}"})

    def do_GET(self):
        self._dispatch(self.routes_get, "READ")

    def do_POST(self):
        self._dispatch(self.routes_post, "WRITE")

    def do_DELETE(self):
        self._dispatch(self.routes_delete, "WRITE")


class _RestServer:
    def __init__(self, handler_cls, host: str = "127.0.0.1", port: int = 0):
        self._httpd = ThreadingHTTPServer((host, port), handler_cls)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()


class BrokerRestServer(_RestServer):
    """POST /query/sql {"sql": ...} → BrokerResponse JSON;
    POST /timeseries/api/v1/query_range for the timeseries engine;
    GET /health."""

    def __init__(self, broker: Broker, host: str = "127.0.0.1", port: int = 0,
                 timeseries_engine=None, access_control=None):
        srv = self

        class Handler(_JsonHandler):
            routes_get = [
                (r"/health/liveness",
                 lambda h, m, q: (200, {"status": "OK"})),
                (r"/health(/readiness)?", lambda h, m, q: srv._readiness()),
                (r"/metrics", lambda h, m, q: srv._metrics()),
                (r"/debug/queries", lambda h, m, q: srv._debug_queries()),
                (r"/debug/cache", lambda h, m, q: srv._debug_cache()),
                (r"/debug/servers", lambda h, m, q: srv._debug_servers()),
                (r"/debug/workload", lambda h, m, q: srv._debug_workload()),
                (r"/debug/traces", lambda h, m, q: srv._debug_traces()),
                (r"/debug/traces/([^/]+)",
                 lambda h, m, q: srv._debug_trace(m.group(1), q)),
                (r"/debug/compiles", lambda h, m, q: srv._debug_compiles()),
                (r"/debug/ledger", lambda h, m, q: srv._debug_ledger()),
                (r"/debug/alerts", lambda h, m, q: srv._debug_alerts()),
                (r"/debug/alerts/([^/]+)",
                 lambda h, m, q: srv._debug_alert(m.group(1))),
                # cursor ids are not table names: no group-based table check
                (r"/resultStore/([^/]+)", lambda h, m, q: srv._cursor_fetch(
                    m.group(1), int(q.get("offset", ["0"])[0]),
                    int(q.get("numRows", ["1000"])[0]), h.principal), "READ"),
            ]
            routes_post = [
                # queries are READs even though they POST
                (r"/query/sql",
                 lambda h, m, q: srv._query(h._body(), h.principal), "READ"),
                (r"/timeseries/api/v1/query_range",
                 lambda h, m, q: srv._timeseries(h._body(), h.principal),
                 "READ"),
            ]
            routes_delete = [
                (r"/resultStore/([^/]+)",
                 lambda h, m, q: srv._cursor_delete(m.group(1), h.principal),
                 "READ"),
                (r"/cache", lambda h, m, q: srv._cache_clear(), "WRITE"),
            ]

        Handler.access_control = access_control
        self.broker = broker
        self.timeseries_engine = timeseries_engine
        # cursor id → owning principal name (reference: response store
        # entries are owner-scoped); only the creator may fetch/delete
        self._cursor_owners = {}
        super().__init__(Handler, host, port)

    def _metrics(self):
        from ..spi.metrics import BROKER_METRICS, render_prometheus

        return 200, RawText(render_prometheus(BROKER_METRICS, role="broker"))

    def _readiness(self):
        """A broker is ready once it has materialized at least one routing
        snapshot — before that every query would fail routing anyway
        (reference: BrokerResourceOnlineOfflineStateModel readiness)."""
        ok = self.broker.is_ready()
        return (200 if ok else 503), {"status": "OK" if ok else "STARTING"}

    def _debug_workload(self):
        """Per-table/per-client decaying cost rollups + recent cost
        reports (cluster/workload.py) — the recommender-input section is
        POST /recommender body-compatible."""
        return 200, self.broker.workload.snapshot()

    def _debug_queries(self):
        """Slow-query ring buffer (worst traced queries over the
        threshold), fed by QueryLogger on every broker return path."""
        ql = self.broker.query_logger
        return 200, {"slowThresholdMs": ql.slow_threshold_ms,
                     "slowQueries": ql.slow_queries()}

    def _debug_cache(self):
        """All three cache tiers' live stats: the broker result cache plus
        (same process in this build) the server-side segment partial cache
        and device-resident partial residency (cache/ package)."""
        from ..cache.partial import GLOBAL_PARTIAL_CACHE
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE

        return 200, {"resultCache": self.broker.result_cache.stats(),
                     "segmentPartialCache": GLOBAL_PARTIAL_CACHE.stats(),
                     "devicePartials": GLOBAL_DEVICE_CACHE.hbm_stats()}

    def _debug_servers(self):
        """Per-server circuit-breaker + adaptive-selection state (the
        broker's routing health table)."""
        return 200, {"servers": self.broker.server_health(),
                     "unhealthy": self.broker.breakers.down_count()}

    def _debug_traces(self):
        """Flight-recorder inventory: retention stats + newest-first
        summaries of every retained trace (cluster/tracestore.py)."""
        ts = self.broker.trace_store
        return 200, {"stats": ts.stats(), "traces": ts.summaries()}

    def _debug_trace(self, query_id: str, q: dict):
        """One retained trace — the raw merged span list, or Chrome Trace
        Event JSON via ``?format=chrome`` (open in ui.perfetto.dev or
        chrome://tracing; spi/traceexport.py)."""
        ent = self.broker.trace_store.get(query_id)
        if ent is None:
            return 404, {"error": f"no retained trace for {query_id}"}
        fmt = (q.get("format", ["json"])[0] or "json").lower()
        if fmt == "chrome":
            from ..spi.traceexport import to_chrome_trace

            return 200, to_chrome_trace(ent["spans"], query_id=query_id)
        return 200, ent

    def _debug_ledger(self):
        """Per-plan performance ledger (engine/perf_ledger.py): rolling
        short/reference window summaries per fingerprint, global fallback
        events, per-table SLO burn rates, and the sentinel's last report
        when one has been published to the store."""
        from ..engine.perf_ledger import PERF_LEDGER

        out = PERF_LEDGER.snapshot()
        out["burnRates"] = {t: PERF_LEDGER.burn_rates(t)
                            for t in PERF_LEDGER.tables()}
        try:
            from .sentinel import SENTINEL_REPORT_PATH

            out["sentinel"] = self.broker.store.get(SENTINEL_REPORT_PATH)
        except Exception:
            out["sentinel"] = None
        return 200, out

    def _debug_alerts(self):
        """Regression-sentinel alert book: firing + recently cleared
        alerts, newest-first, each carrying its exemplar trace ids."""
        from ..engine.perf_ledger import ALERTS

        return 200, ALERTS.snapshot()

    def _debug_alert(self, alert_id: str):
        """One alert record; ``exemplarTraceIds`` resolve against
        GET /debug/traces/{id} (``?format=chrome`` for Perfetto)."""
        from ..engine.perf_ledger import ALERTS

        rec = ALERTS.get(alert_id)
        if rec is None:
            return 404, {"error": f"no alert {alert_id}"}
        return 200, rec

    def _debug_compiles(self):
        """Compile & HBM telemetry (engine/compile_registry.py +
        segment/device_cache.py): executable families ranked by cumulative
        compile cost — the AOT-persist priority list — plus device-memory
        high-water marks and eviction attribution. Served from the broker
        because this build co-locates broker and servers in one process;
        the server REST exposes the same payload per instance."""
        from ..engine import aot_cache
        from ..engine.compile_registry import COMPILE_REGISTRY
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE

        out = COMPILE_REGISTRY.snapshot()
        out["hbm"] = GLOBAL_DEVICE_CACHE.hbm_telemetry()
        out["aot"] = aot_cache.stats()
        return 200, out

    def _cache_clear(self):
        """DELETE /cache — drop every tier (operator hammer for debugging
        staleness or reclaiming memory; lineage invalidation is automatic)."""
        from ..cache.partial import GLOBAL_PARTIAL_CACHE
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE

        dropped = self.broker.result_cache.clear()
        GLOBAL_PARTIAL_CACHE.clear()
        device_dropped = GLOBAL_DEVICE_CACHE.drop_partials()
        return 200, {"resultEntriesDropped": dropped,
                     "devicePartialsDropped": device_dropped,
                     "status": "cleared"}

    def _query(self, body: dict, principal=None):
        sql = body.get("sql")
        if not sql:
            return 400, {"error": "missing 'sql'"}
        if principal is not None:
            # table-level READ authorization on every referenced table,
            # resolved by the real parsers — a regex grammar would miss
            # quoted identifiers (reference:
            # BasicAuthBrokerRequestHandler table checks)
            from .auth import READ

            tables = _referenced_tables(sql)
            if tables is None and "*" not in principal.tables:
                return 403, {"error": "cannot resolve tables for "
                                      "table-scoped principal"}
            for t in tables or ():
                if not principal.allows(t, READ):
                    return 403, {"error": f"READ on {t} not permitted"}
        if body.get("getCursor"):
            out = self.broker.execute_sql_cursor(
                sql, int(body.get("numRows", 1000)))
            if principal is not None and out.get("cursorId"):
                self._cursor_owners[out["cursorId"]] = principal.name
            return (200 if not out.get("exceptions") else 500), out
        resp = self.broker.execute_sql(sql)
        if getattr(resp, "query_rejected", False):
            # admission control shed the query — 429, not a server error
            return 429, resp.to_json()
        return (200 if not resp.exceptions else 500), resp.to_json()

    def _cursor_owned(self, cursor_id: str, principal) -> bool:
        if principal is None:
            return True  # no auth layer configured
        owner = self._cursor_owners.get(cursor_id)
        return owner is None or owner == principal.name

    def _cursor_fetch(self, cursor_id: str, offset: int, num_rows: int,
                      principal=None):
        if not self._cursor_owned(cursor_id, principal):
            return 403, {"error": "cursor belongs to another principal"}
        try:
            return 200, self.broker.fetch_cursor(cursor_id, offset, num_rows)
        except KeyError as e:
            return 404, {"error": str(e)}

    def _cursor_delete(self, cursor_id: str, principal=None):
        if not self._cursor_owned(cursor_id, principal):
            return 403, {"error": "cursor belongs to another principal"}
        self._cursor_owners.pop(cursor_id, None)
        return 200, {"deleted": self.broker.response_store.delete(cursor_id)}

    def _timeseries(self, body: dict, principal=None):
        if self.timeseries_engine is None:
            return 501, {"error": "timeseries engine not configured"}
        if principal is not None:
            from ..timeseries.engine import parse_m3ql
            from .auth import READ

            try:
                table = parse_m3ql(body.get("query", "")).fetch.table
            except Exception:
                table = None
            if table is None and "*" not in principal.tables:
                return 403, {"error": "cannot resolve table for "
                                      "table-scoped principal"}
            if table and not principal.allows(table, READ):
                return 403, {"error": f"READ on {table} not permitted"}
        block = self.timeseries_engine.execute(
            body["query"], int(body["start"]), int(body["end"]),
            int(body["step"]), body.get("language", "m3ql"))
        return 200, block.to_json()


class ControllerRestServer(_RestServer):
    """Table/schema/segment lifecycle endpoints (reference:
    PinotTableRestletResource, PinotSchemaRestletResource,
    PinotSegmentUploadDownloadRestletResource, rebalance endpoints)."""

    def __init__(self, controller: ClusterController,
                 host: str = "127.0.0.1", port: int = 0,
                 access_control=None):
        srv = self

        class Handler(_JsonHandler):
            routes_get = [
                (r"/health/liveness",
                 lambda h, m, q: (200, {"status": "OK"})),
                (r"/health/readiness", lambda h, m, q: srv._health()),
                # bare /health keeps the minimal LB-probe payload;
                # readiness above adds the seat (leader|standby)
                (r"/health", lambda h, m, q: (200, {"status": "OK"})),
                (r"/metrics", lambda h, m, q: srv._metrics()),
                (r"/tables", lambda h, m, q: srv._list_tables()),
                (r"/debug/cluster", lambda h, m, q: srv._debug_cluster()),
                (r"/tables/([^/]+)", lambda h, m, q: srv._get_table(m.group(1))),
                (r"/schemas/([^/]+)", lambda h, m, q: srv._get_schema(m.group(1))),
                (r"/segments/([^/]+)", lambda h, m, q: srv._list_segments(m.group(1))),
                (r"/instances", lambda h, m, q: (200, {
                    "instances": srv.controller.list_instances(),
                    "live": srv.controller.live_instances()})),
                (r"/cluster/summary", lambda h, m, q: srv._summary()),
                (r"/debug/store", lambda h, m, q: srv._debug_store()),
                (r"/tables/([^/]+)/rebalanceStatus",
                 lambda h, m, q: srv._rebalance_status(m.group(1))),
                (r"/debug/rebalance", lambda h, m, q: srv._debug_rebalance()),
                (r"/tables/([^/]+)/instancePartitions",
                 lambda h, m, q: srv._instance_partitions(m.group(1))),
                (r"/", lambda h, m, q: srv._home_page()),
            ]
            routes_post = [
                (r"/schemas", lambda h, m, q: srv._add_schema(h._body())),
                (r"/recommender", lambda h, m, q: srv._recommend(h._body()),
                 "READ"),
                (r"/tables", lambda h, m, q: srv._create_table(h._body())),
                (r"/segments/([^/]+)/([^/]+)",
                 lambda h, m, q: srv._add_segment(m.group(1), m.group(2), h._body())),
                (r"/tables/([^/]+)/rebalance",
                 lambda h, m, q: srv._rebalance(
                     m.group(1),
                     dry_run=q.get("dryRun", ["false"])[0] == "true")),
                (r"/tables/([^/]+)/rebalance/abort",
                 lambda h, m, q: srv._rebalance_abort(m.group(1))),
                (r"/tables/([^/]+)/relocate",
                 lambda h, m, q: (200, srv.controller.relocate_tiers(
                     table_name_with_type(m.group(1)),
                     dry_run=q.get("dryRun", ["false"])[0] == "true"))),
                (r"/tables/([^/]+)/instancePartitions",
                 lambda h, m, q: srv._assign_instances(m.group(1), h._body())),
            ]
            routes_delete = [
                (r"/tables/([^/]+)",
                 lambda h, m, q: srv._drop_table(m.group(1))),
                (r"/segments/([^/]+)/([^/]+)",
                 lambda h, m, q: srv._drop_segment(m.group(1), m.group(2))),
            ]

        Handler.access_control = access_control
        self.controller = controller
        super().__init__(Handler, host, port)

    def _metrics(self):
        from ..spi.metrics import CONTROLLER_METRICS, render_prometheus

        return 200, RawText(
            render_prometheus(CONTROLLER_METRICS, role="controller"))

    def _health(self):
        """Controller health names its seat: the leader serves writes, a
        standby is healthy but deliberately idle (leader-gated periodic
        tasks do not run there)."""
        is_leader = self.controller.is_leader() \
            if hasattr(self.controller, "is_leader") else True
        return 200, {"status": "OK",
                     "role": "leader" if is_leader else "standby"}

    def _debug_cluster(self):
        """Fleet health rollup materialized by the leader's
        ClusterHealthChecker periodic task (cluster/periodic.py); a
        standby serves the leader-written snapshot from the store."""
        from .periodic import HEALTH_REPORT_PATH

        snap = self.controller.store.get(HEALTH_REPORT_PATH)
        if snap is None:
            return 503, {"error": "no health snapshot yet "
                                  "(leader scrape has not run)"}
        return 200, snap

    def _list_tables(self):
        return 200, {"tables": self.controller.store.children("/CONFIGS/TABLE")}

    def _get_table(self, name: str):
        cfg = self.controller.table_config(table_name_with_type(name))
        if cfg is None:
            cfg = self.controller.table_config(table_name_with_type(name, "REALTIME"))
        return (200, cfg) if cfg else (404, {"error": f"table {name} not found"})

    def _get_schema(self, name: str):
        s = self.controller.store.get(f"/SCHEMAS/{name}")
        return (200, s) if s else (404, {"error": f"schema {name} not found"})

    def _add_schema(self, body: dict):
        self.controller.add_schema(body)
        return 200, {"status": f"schema {body.get('schemaName')} added"}

    def _create_table(self, body: dict):
        name = self.controller.create_table(body)
        return 200, {"status": f"table {name} created", "tableName": name}

    def _list_segments(self, table: str):
        t = table_name_with_type(table)
        return 200, {"segments": self.controller.store.children(f"/SEGMENTS/{t}")}

    def _add_segment(self, table: str, segment: str, body: dict):
        assigned = self.controller.add_segment(
            table_name_with_type(table), segment, body)
        return 200, {"status": "added", "assigned": assigned}

    def _drop_table(self, table: str):
        self.controller.drop_table(table_name_with_type(table))
        return 200, {"status": f"table {table} dropped"}

    def _drop_segment(self, table: str, segment: str):
        self.controller.drop_segment(table_name_with_type(table), segment)
        return 200, {"status": f"segment {segment} dropped"}

    def _debug_store(self):
        """Control-plane durability introspection: journal/snapshot/recovery
        state of the property store plus the current leader seat."""
        from .leader import LEADER_PATH

        store = self.controller.store
        out = dict(store.durability_stats())
        leader = store.get(LEADER_PATH)
        out["leaderInstance"] = (leader or {}).get("instance")
        out["thisInstance"] = getattr(self.controller, "instance_id", None)
        out["isLeader"] = self.controller.is_leader() \
            if hasattr(self.controller, "is_leader") else True
        return 200, out

    def _rebalance_status(self, table: str):
        st = self.controller.rebalance_status(table_name_with_type(table))
        return (200, st) if st else (404, {"error": "no rebalance recorded"})

    @property
    def rebalancer(self):
        """Lazily-built durable rebalance engine (cluster/rebalance.py);
        shared with the periodic actuator when one is registered."""
        if getattr(self, "_rebalancer", None) is None:
            from .rebalance import SegmentRebalancer

            self._rebalancer = SegmentRebalancer(self.controller)
        return self._rebalancer

    def _rebalance(self, table: str, dry_run: bool = False):
        """POST /tables/{t}/rebalance — journal a durable, make-before-break
        move plan and drive it to a terminal status inline (the journal at
        /REBALANCE/{t} means a crash mid-drive is resumed by any leader's
        RebalanceActuator rather than lost)."""
        from .rebalance import RebalanceInProgress

        nwt = table_name_with_type(table)
        try:
            if dry_run:
                return 200, self.rebalancer.plan(nwt, dry_run=True)
            return 200, self.rebalancer.run(nwt)
        except RebalanceInProgress as e:
            return 409, {"error": str(e)}
        except KeyError:
            return 404, {"error": f"table {table} not found"}
        except TimeoutError as e:
            return 200, {"status": "IN_PROGRESS", "detail": str(e),
                         "job": self.rebalancer.job(nwt)}
        except RuntimeError as e:
            return 409, {"error": str(e)}

    def _rebalance_abort(self, table: str):
        nwt = table_name_with_type(table)
        job = self.rebalancer.job(nwt)
        if not job:
            return 404, {"error": "no rebalance recorded"}
        return 200, self.rebalancer.abort(nwt)

    def _debug_rebalance(self):
        return 200, self.rebalancer.debug()

    def _instance_partitions(self, table: str):
        ip = self.controller.instance_partitions(table_name_with_type(table))
        return (200, ip) if ip else (404, {"error": "no instance partitions"})

    def _assign_instances(self, table: str, body: dict):
        ip = self.controller.configure_instance_partitions(
            table_name_with_type(table),
            int(body["numReplicaGroups"]),
            instances_per_group=body.get("instancesPerReplicaGroup"),
            num_partitions=body.get("numPartitions"))
        return 200, ip

    # -- cluster summary / minimal UI (reference: controller UI's cluster
    # manager pages, served as data here) ----------------------------------
    def _summary(self):
        store = self.controller.store
        tables = {}
        for nwt in store.children("/CONFIGS/TABLE"):
            segs = store.children(f"/SEGMENTS/{nwt}")
            view = store.get(f"/EXTERNALVIEW/{nwt}") or {}
            online = sum(1 for s in segs if view.get(s))
            tables[nwt] = {"segments": len(segs), "online": online,
                           "totalDocs": sum(
                               (store.get(f"/SEGMENTS/{nwt}/{s}") or {})
                               .get("numDocs", 0) for s in segs)}
        return 200, {
            "tables": tables,
            "instances": self.controller.list_instances(),
            "liveInstances": self.controller.live_instances(),
            "schemas": store.children("/SCHEMAS"),
        }

    def _home_page(self):
        _code, s = self._summary()
        rows = "".join(
            f"<tr><td>{t}</td><td>{v['segments']}</td><td>{v['online']}</td>"
            f"<td>{v['totalDocs']}</td></tr>" for t, v in s["tables"].items())
        live = set(s["liveInstances"])
        insts = "".join(
            f"<li>{i} {'&#9679; live' if i in live else '&#9675; down'}</li>"
            for i in s["instances"])
        html = (
            "<html><head><title>pinot-tpu cluster</title></head><body>"
            "<h1>Cluster</h1>"
            f"<h2>Tables ({len(s['tables'])})</h2>"
            "<table border=1><tr><th>table</th><th>segments</th>"
            f"<th>online</th><th>docs</th></tr>{rows}</table>"
            f"<h2>Instances</h2><ul>{insts}</ul>"
            "</body></html>")
        return 200, RawHtml(html)

    def _recommend(self, body: dict):
        """POST /recommender {schema, queries|queryStats, cardinalities,
        numRows, qps} (reference: PinotConfigRecommenderRestletResource)."""
        from ..spi.data_types import Schema
        from .recommender import recommend

        schema_json = body.get("schema")
        if schema_json is None:
            name = body.get("schemaName")
            schema_json = self.controller.store.get(f"/SCHEMAS/{name}")
            if schema_json is None:
                return 400, {"error": "missing 'schema' or known 'schemaName'"}
        rec = recommend(
            Schema.from_json(schema_json),
            queries=body.get("queries"),
            query_stats=body.get("queryStats"),
            cardinalities=body.get("cardinalities"),
            num_rows=int(body.get("numRows", 1_000_000)),
            qps=float(body.get("qps", 10.0)))
        return 200, rec.to_json()


class ServerRestServer(_RestServer):
    """Server-role admin/debug REST (reference: pinot-server/.../api/
    resources — TablesResource /tables + /tables/{t}/segments,
    /segments/{t}/{s}/metadata, DebugResource, HealthCheckResource
    /health/liveness + /health/readiness). Read-only introspection of one
    server's hosted state plus query-kill; cluster mutations stay on the
    controller REST."""

    def __init__(self, server, host: str = "127.0.0.1", port: int = 0,
                 access_control=None):
        srv = self

        class Handler(_JsonHandler):
            routes_get = [
                (r"/health/liveness", lambda h, m, q: (200, {"status": "OK"})),
                (r"/health(/readiness)?", lambda h, m, q: srv._readiness()),
                (r"/metrics", lambda h, m, q: srv._metrics()),
                (r"/instance", lambda h, m, q: srv._instance()),
                (r"/tables", lambda h, m, q: (200, {
                    "tables": sorted(srv.server.segments)})),
                (r"/tables/([^/]+)/segments",
                 lambda h, m, q: srv._table_segments(m.group(1))),
                (r"/tables/([^/]+)/size",
                 lambda h, m, q: srv._table_size(m.group(1))),
                (r"/segments/([^/]+)/([^/]+)/metadata",
                 lambda h, m, q: srv._segment_metadata(m.group(1), m.group(2))),
                (r"/debug/tables/([^/]+)",
                 lambda h, m, q: srv._debug_table(m.group(1))),
                (r"/debug/segments", lambda h, m, q: srv._debug_segments()),
                (r"/debug/queries", lambda h, m, q: srv._debug_queries()),
                (r"/debug/compiles", lambda h, m, q: srv._debug_compiles()),
                (r"/debug/status",
                 lambda h, m, q: (200, srv.server.health_status())),
                (r"/debug/storage",
                 lambda h, m, q: (200, srv.server.debug_storage())),
            ]
            routes_post = [
                (r"/queries/([^/]+)/kill",
                 lambda h, m, q: srv._kill_query(m.group(1)), "WRITE"),
            ]
            routes_delete = []

        Handler.access_control = access_control
        self.server = server
        super().__init__(Handler, host, port)

    def _metrics(self):
        from ..spi.metrics import SERVER_METRICS, render_prometheus

        return 200, RawText(render_prometheus(SERVER_METRICS, role="server"))

    def _readiness(self):
        """Readiness gates on Helix join + the FIRST converge pass having
        completed (reference: ServiceStatus ideal-state checkers) — a
        joined-but-unconverged server would serve missing-segment errors."""
        ok = bool(getattr(self.server, "_started", False)) \
            and bool(getattr(self.server, "_converged", True))
        return (200 if ok else 503), {"status": "OK" if ok else "STARTING"}

    def _instance(self):
        host, port = self.server.address
        return 200, {"instanceId": self.server.instance_id,
                     "host": host, "port": port,
                     "tags": self.server.tags,
                     "backend": self.server.backend}

    def _table_segments(self, table: str):
        segs = self.server.segments.get(table)
        if segs is None:
            return 404, {"error": f"table {table} not hosted"}
        return 200, {"segments": [
            {"name": name, "numDocs": seg.num_docs,
             "mutable": bool(getattr(seg, "is_mutable", False))}
            for name, seg in sorted(segs.items())]}

    def _table_size(self, table: str):
        segs = self.server.segments.get(table)
        if segs is None:
            return 404, {"error": f"table {table} not hosted"}
        per_seg = {}
        for name, seg in segs.items():
            loc = getattr(seg, "directory", None)
            nbytes = 0
            if loc:
                import os as _os

                for root, _dirs, files in _os.walk(str(loc)):
                    nbytes += sum(
                        _os.path.getsize(_os.path.join(root, f))
                        for f in files)
            per_seg[name] = {"diskSizeBytes": nbytes,
                             "numDocs": seg.num_docs}
        return 200, {"tableName": table, "segments": per_seg,
                     "totalDiskSizeBytes": sum(
                         v["diskSizeBytes"] for v in per_seg.values())}

    def _segment_metadata(self, table: str, segment: str):
        segs = self.server.segments.get(table) or {}
        seg = segs.get(segment)
        if seg is None:
            return 404, {"error": f"{table}/{segment} not hosted"}
        meta = {"segmentName": segment, "numDocs": seg.num_docs,
                "mutable": bool(getattr(seg, "is_mutable", False))}
        cols = {}
        for c in getattr(seg, "columns", lambda: [])() \
                if callable(getattr(seg, "columns", None)) \
                else getattr(seg, "columns", []):
            m = seg.column_metadata(c) if hasattr(seg, "column_metadata") \
                else None
            if m is not None:
                cols[c] = {"cardinality": getattr(m, "cardinality", None),
                           "dataType": str(getattr(m, "data_type", "")),
                           "singleValue": getattr(m, "single_value", True),
                           "minValue": _json_safe(getattr(m, "min_value", None)),
                           "maxValue": _json_safe(getattr(m, "max_value", None))}
        if cols:
            meta["columns"] = cols
        return 200, meta

    def _debug_table(self, table: str):
        """Hosted vs ideal comparison for one table (reference:
        DebugResource.getTableDebugInfo segment-error surface)."""
        hosted = set(self.server.segments.get(table) or {})
        ideal = self.server.store.get(f"/IDEALSTATES/{table}") or {}
        want = {s for s, inst_map in ideal.items()
                if self.server.instance_id in inst_map}
        return 200, {"tableName": table,
                     "hostedSegments": sorted(hosted),
                     "idealSegments": sorted(want),
                     "missing": sorted(want - hosted),
                     "unexpected": sorted(hosted - want)}

    def _debug_segments(self):
        """Served vs quarantined inventory across every hosted table —
        quarantine entries carry the verify-failure reason, damaged
        columns, and repair-attempt count (reference:
        DebugResource.getSegmentsDebugInfo error surface)."""
        return 200, {"tables": self.server.debug_segments()}

    def _debug_queries(self):
        from ..engine.scheduler import GLOBAL_ACCOUNTANT

        return 200, {"inflight": GLOBAL_ACCOUNTANT.inflight(),
                     "allocatedBytes": GLOBAL_ACCOUNTANT.total_allocated()}

    def _debug_compiles(self):
        """Per-instance compile & HBM telemetry — same payload shape as
        the broker's GET /debug/compiles (this build shares the process,
        so the registries are the same objects)."""
        from ..engine import aot_cache
        from ..engine.compile_registry import COMPILE_REGISTRY
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE

        out = COMPILE_REGISTRY.snapshot()
        out["hbm"] = GLOBAL_DEVICE_CACHE.hbm_telemetry()
        out["aot"] = aot_cache.stats()
        coalescer = getattr(getattr(self.server, "executor", None),
                            "coalescer", None)
        out["coalesce"] = coalescer.snapshot() if coalescer else {}
        return 200, out

    def _kill_query(self, query_id: str):
        from ..engine.scheduler import GLOBAL_ACCOUNTANT

        ok = GLOBAL_ACCOUNTANT.kill_query(query_id)
        return (200 if ok else 404), {
            "queryId": query_id, "killed": ok}


def _json_safe(v):
    if hasattr(v, "item"):  # numpy scalar → native number, not a string
        try:
            return v.item()
        except (TypeError, ValueError):
            pass
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)
