"""Continuous performance regression sentinel (drift detector half).

The ledger (engine/perf_ledger.py) accumulates per-plan rolling windows;
this periodic task — leader-gated, same double-gate idiom as
ClusterHealthChecker — turns them into named, hysteresis-protected
anomalies and SLO burn-rate alerts:

- ``latency-drift``        a plan's short-window p50 regressed past its
                           decayed reference by bench_gate's rules (ratio
                           threshold AND absolute jitter floor — the same
                           match-flip/threshold/floor discipline the
                           offline gate applies to committed rounds)
- ``compile-storm``        compiles per query in the short window blew past
                           the reference rate (an AOT/compile-cache miss
                           pattern: the family keeps recompiling)
- ``fallback-surge``       engine fallback events (mesh→solo,
                           device-join→host, fused→host) spiking vs their
                           reference window
- ``cache-collapse``       a plan that used to serve from the result cache
                           stopped hitting (epoch churn, key drift)
- ``crossing-regression``  device→host crossings per query rose — a fused
                           plan silently losing residency
- ``slo-burn``             a table's error budget (latency / error /
                           partial-rate objective) is burning hot in BOTH
                           the fast and slow windows (Google-SRE
                           multiwindow rule: one noisy minute cannot page,
                           a sustained burn cannot hide)

Every rule must breach ``PINOT_TPU_SENTINEL_BREACHES`` consecutive
evaluations to fire and pass ``PINOT_TPU_SENTINEL_CLEARS`` clean ones to
resolve. On a NEW alert the sentinel arms exemplar pinning: the next N
matching queries get head-sampling forced ON and their traces pinned in
the TraceStore tagged with the alert id — every alert links to an
openable flame of the regressed shape.

Each scrape also persists the ledger's reference windows through the WAL
store (restart-survivable "normal") and publishes its report at
``/PERF/SENTINEL`` (served by ``GET /debug/ledger``), with the latest
committed ``BENCH_r*.json`` round attached as offline baseline context.
"""

from __future__ import annotations

import os
import time
from pathlib import Path

from ..engine.perf_ledger import ALERTS, PERF_LEDGER, bucket_quantile
from ..spi.metrics import CONTROLLER_METRICS, ControllerGauge

SENTINEL_REPORT_PATH = "/PERF/SENTINEL"

# drift thresholds default to bench_gate's offline gate values: the
# sentinel is the always-on version of the same judgement
THRESHOLD_ENV = "PINOT_TPU_SENTINEL_THRESHOLD"
MIN_ABS_MS_ENV = "PINOT_TPU_SENTINEL_MIN_ABS_MS"
# fewest short-window queries before a plan's windows are judged at all
MIN_QUERIES_ENV = "PINOT_TPU_SENTINEL_MIN_QUERIES"
# hysteresis: consecutive breaching evaluations to fire / clean ones to clear
BREACHES_ENV = "PINOT_TPU_SENTINEL_BREACHES"
CLEARS_ENV = "PINOT_TPU_SENTINEL_CLEARS"
# exemplars pinned per new alert
EXEMPLARS_ENV = "PINOT_TPU_SENTINEL_EXEMPLARS"
SCRAPE_S_ENV = "PINOT_TPU_SENTINEL_SCRAPE_S"

# table-config keys that override the PINOT_TPU_SLO_* env objectives
_SLO_CFG_KEYS = {"sloLatencyMs": "latencyMs", "sloLatencyPct": "latencyPct",
                 "sloErrorRate": "errorRate", "sloPartialRate": "partialRate"}


def _latest_bench_round():
    """(name, payload) of the newest committed BENCH_r*.json, or None —
    offline baseline context attached to the sentinel report."""
    root = Path(__file__).resolve().parents[2]
    rounds = sorted(root.glob("BENCH_r[0-9][0-9].json"))
    if not rounds:
        return None
    from ..tools.bench_gate import load_round

    try:
        return rounds[-1].name, load_round(str(rounds[-1]))
    except (OSError, ValueError):
        return None


class PerfRegressionSentinel:
    """Leader-gated periodic drift detector over the perf ledger."""

    def __init__(self, store, controller=None,
                 threshold: float = None, min_abs_ms: float = None,
                 min_queries: int = None, breaches: int = None,
                 clears: int = None, exemplars: int = None,
                 ledger=None, alerts=None):
        self.store = store
        self.controller = controller
        self.ledger = PERF_LEDGER if ledger is None else ledger
        self.alerts = ALERTS if alerts is None else alerts
        self.threshold = float(os.environ.get(THRESHOLD_ENV, 0.25)) \
            if threshold is None else threshold
        self.min_abs_ms = float(os.environ.get(MIN_ABS_MS_ENV, 2.0)) \
            if min_abs_ms is None else min_abs_ms
        self.min_queries = int(os.environ.get(MIN_QUERIES_ENV, 5)) \
            if min_queries is None else min_queries
        self.breaches = int(os.environ.get(BREACHES_ENV, 2)) \
            if breaches is None else breaches
        self.clears = int(os.environ.get(CLEARS_ENV, 2)) \
            if clears is None else clears
        self.exemplars = int(os.environ.get(EXEMPLARS_ENV, 3)) \
            if exemplars is None else exemplars
        self._streak: dict[tuple, int] = {}
        self._ok: dict[tuple, int] = {}
        self._bench = None  # cached (name, payload) baseline context
        self._restored = False
        CONTROLLER_METRICS.set_gauge(
            ControllerGauge.PERF_ANOMALIES_ACTIVE,
            lambda: self.alerts.active_count)

    # -- periodic entry point ------------------------------------------------

    def __call__(self) -> dict:
        leader = getattr(self.controller, "leader", None)
        if leader is not None and not leader.is_leader:
            return {"skipped": "standby controller does not evaluate"}
        if not self._restored:
            # first leader scrape after boot: preload "normal" from the
            # WAL store so a restart doesn't start amnesiac
            self._restored = True
            self.ledger.restore(self.store)
        self._load_slo_overrides()
        report = self.evaluate()
        self.ledger.persist(self.store)
        self.store.set(SENTINEL_REPORT_PATH, report)
        return report

    def _load_slo_overrides(self) -> None:
        from .controller import raw_table_name

        for table in self.store.children("/CONFIGS/TABLE"):
            cfg = self.store.get(f"/CONFIGS/TABLE/{table}") or {}
            override = {dst: float(cfg[src])
                        for src, dst in _SLO_CFG_KEYS.items() if src in cfg}
            if override:
                # ledger tables are keyed by the raw parsed table name;
                # store config children carry the _OFFLINE/_REALTIME suffix
                self.ledger.set_slo_override(raw_table_name(table), override)

    # -- drift rules ---------------------------------------------------------

    def evaluate(self) -> dict:
        """One full evaluation pass: rotate aged windows, judge every rule,
        apply hysteresis, fire/resolve alerts, arm exemplars on NEW fires.
        Pure in-process — callable directly from tests and soaks."""
        self.ledger.maybe_rotate()
        breaching: dict[tuple, dict] = {}
        plans_judged = 0
        for key in self.ledger.keys():
            win = self.ledger.plan_windows(key)
            if win is None:
                continue
            cur, ref, ref_weight, table = win
            if ref_weight <= 0.0 or cur["queries"] < self.min_queries:
                continue
            plans_judged += 1
            self._judge_plan(key, table, cur, ref, ref_weight, breaching)
        self._judge_fallbacks(breaching)
        burn_report = self._judge_slo(breaching)
        anomalies = self._apply_hysteresis(breaching)
        return {
            "checkedAtMs": int(time.time() * 1000),
            "plansJudged": plans_judged,
            "anomalies": anomalies,
            "burnRates": burn_report,
            "alertsActive": self.alerts.active_count,
            "benchBaseline": self._bench_context(),
            "thresholds": {"threshold": self.threshold,
                           "minAbsMs": self.min_abs_ms,
                           "minQueries": self.min_queries,
                           "breachesToFire": self.breaches,
                           "clearsToResolve": self.clears},
        }

    def _judge_plan(self, key: str, table: str, cur: dict, ref: dict,
                    ref_weight: float, breaching: dict) -> None:
        qn = cur["queries"]
        ref_q = ref["queries"] / ref_weight  # per-window averages
        if ref_q <= 0:
            return
        # latency-drift: bench_gate's p50 rule (ratio threshold + absolute
        # jitter floor) applied short-window vs decayed reference
        cur_p50 = bucket_quantile(cur["latBuckets"], 0.5)
        ref_p50 = bucket_quantile(ref["latBuckets"], 0.5)
        if ref_p50 > 0 and cur_p50 > ref_p50 * (1.0 + self.threshold) \
                and cur_p50 - ref_p50 >= self.min_abs_ms:
            breaching[("latency-drift", key)] = {
                "table": table,
                "summary": f"p50 {ref_p50:.1f}ms -> {cur_p50:.1f}ms "
                           f"({cur_p50 / ref_p50:.2f}x, threshold "
                           f"{1.0 + self.threshold:.2f}x)",
                "details": {"refP50Ms": round(ref_p50, 3),
                            "shortP50Ms": round(cur_p50, 3),
                            "shortQueries": qn}}
        # compile-storm: compiles per query vs the reference rate — a
        # recompiling family (AOT refuse-and-recompile loop, cache churn)
        cur_rate = cur["compiles"] / qn
        ref_rate = (ref["compiles"] / ref_weight) / ref_q
        if cur["compiles"] >= 2 \
                and cur_rate > ref_rate * (1.0 + self.threshold) + 0.01:
            breaching[("compile-storm", key)] = {
                "table": table,
                "summary": f"compiles/query {ref_rate:.3f} -> "
                           f"{cur_rate:.3f} ({cur['compiles']} compiles "
                           f"over {qn} queries)",
                "details": {"refCompilesPerQuery": round(ref_rate, 4),
                            "shortCompilesPerQuery": round(cur_rate, 4)}}
        # cache-collapse: a plan that used to hit the result cache stopped
        cur_lookups = cur["cacheHits"] + cur["cacheMisses"]
        ref_lookups = ref["cacheHits"] + ref["cacheMisses"]
        if cur_lookups >= self.min_queries and ref_lookups > 0:
            cur_hit = cur["cacheHits"] / cur_lookups
            ref_hit = ref["cacheHits"] / ref_lookups
            if ref_hit >= 0.2 and cur_hit < ref_hit / 2.0:
                breaching[("cache-collapse", key)] = {
                    "table": table,
                    "summary": f"result-cache hit rate {ref_hit:.0%} -> "
                               f"{cur_hit:.0%} over {cur_lookups} lookups",
                    "details": {"refHitRate": round(ref_hit, 4),
                                "shortHitRate": round(cur_hit, 4)}}
        # crossing-regression: device→host crossings per query rose (plan
        # property — bench_gate fails ANY increase; live windows get half
        # a crossing of slack for mixed traffic under one fingerprint)
        cur_x = cur["hostCrossings"] / qn
        ref_x = (ref["hostCrossings"] / ref_weight) / ref_q
        if ref["hostCrossings"] > 0 and cur_x > ref_x + 0.5:
            breaching[("crossing-regression", key)] = {
                "table": table,
                "summary": f"host crossings/query {ref_x:.2f} -> "
                           f"{cur_x:.2f} (fused plan losing residency)",
                "details": {"refCrossingsPerQuery": round(ref_x, 3),
                            "shortCrossingsPerQuery": round(cur_x, 3)}}

    def _judge_fallbacks(self, breaching: dict) -> None:
        cur, ref, ref_weight, _tot = self.ledger.events_windows()
        for kind, n in cur.items():
            ref_rate = ref.get(kind, 0.0) / max(ref_weight, 1.0)
            if n >= 3 and n > ref_rate * (1.0 + self.threshold) + 1.0:
                breaching[("fallback-surge", kind)] = {
                    "table": "",
                    "summary": f"{n} {kind} fallbacks this window "
                               f"(reference {ref_rate:.2f}/window)",
                    "details": {"kind": kind, "shortCount": n,
                                "refPerWindow": round(ref_rate, 3)}}

    def _judge_slo(self, breaching: dict) -> dict:
        burn_report = {}
        for table in self.ledger.tables():
            rates = self.ledger.burn_rates(table)
            if not rates:
                continue
            fast, slow = rates.get("fast", {}), rates.get("slow", {})
            burn_report[table] = {"fast": fast, "slow": slow}
            CONTROLLER_METRICS.set_gauge(
                f"sloBurnRate.{table}",
                lambda t=table: max(
                    (self.ledger.burn_rates(t).get("fast") or {}).get(
                        "latencyBurn", 0.0),
                    (self.ledger.burn_rates(t).get("fast") or {}).get(
                        "errorBurn", 0.0),
                    (self.ledger.burn_rates(t).get("fast") or {}).get(
                        "partialBurn", 0.0)))
            if fast.get("queries", 0) < self.min_queries:
                continue
            for kind, field in (("latency", "latencyBurn"),
                                ("error", "errorBurn"),
                                ("partial", "partialBurn")):
                fb, sb = fast.get(field, 0.0), slow.get(field, 0.0)
                # multiwindow rule: BOTH windows must burn above 1x
                if fb > 1.0 and sb > 1.0:
                    breaching[("slo-burn", f"{table}:{kind}")] = {
                        "table": table,
                        "summary": f"{kind} budget burning {fb:.1f}x "
                                   f"(fast) / {sb:.1f}x (slow) on "
                                   f"{table}",
                        "details": {"objective": kind,
                                    "fastBurn": round(fb, 3),
                                    "slowBurn": round(sb, 3),
                                    "slo": rates.get("slo", {})}}
        return burn_report

    # -- hysteresis + alert lifecycle ----------------------------------------

    def _apply_hysteresis(self, breaching: dict) -> list:
        anomalies = []
        for (typ, key), info in breaching.items():
            tk = (typ, key)
            self._streak[tk] = self._streak.get(tk, 0) + 1
            self._ok.pop(tk, None)
            anomalies.append({"type": typ, "key": key,
                              "table": info["table"],
                              "streak": self._streak[tk],
                              "summary": info["summary"]})
            if self._streak[tk] < self.breaches:
                continue  # hysteresis: one noisy window never fires
            aid, new = self.alerts.fire(typ, key, info["table"],
                                        info["summary"], info["details"])
            if new:
                # close the metrics→traces loop: force head-sampling for
                # the next N matching queries, pinned under this alert id
                if typ in ("slo-burn", "fallback-surge"):
                    self.ledger.arm_exemplars(aid, table=info["table"],
                                              count=self.exemplars)
                else:
                    self.ledger.arm_exemplars(aid, plan_key=key,
                                              count=self.exemplars)
        # clean evaluations resolve, also with hysteresis; an active alert
        # whose scope vanished (plan evicted, table idle) counts clean
        for rec in self.alerts.active():
            tk = (rec["type"], rec["key"])
            if tk in breaching:
                continue
            self._streak.pop(tk, None)
            self._ok[tk] = self._ok.get(tk, 0) + 1
            if self._ok[tk] >= self.clears:
                aid = self.alerts.resolve(rec["type"], rec["key"])
                if aid:
                    self.ledger.disarm_exemplars(aid)
                self._ok.pop(tk, None)
        # forget streaks for rules that stopped breaching before firing
        for tk in [t for t in self._streak
                   if t not in breaching
                   and not any(a["type"] == t[0] and a["key"] == t[1]
                               for a in self.alerts.active())]:
            del self._streak[tk]
        return anomalies

    def _bench_context(self):
        if self._bench is None:
            self._bench = _latest_bench_round() or False
        if not self._bench:
            return None
        name, payload = self._bench
        return {"round": name,
                "platform": payload.get("platform"),
                "runner": payload.get("runner"),
                "configP50s": {cfg: d.get("tpu_p50_s")
                               for cfg, d in
                               (payload.get("detail") or {}).items()}}
