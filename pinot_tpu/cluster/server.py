"""Server role: converge to ideal state, host segments, serve queries.

Reference analogue: pinot-server — BaseServerStarter.start:578 boots the
instance data manager + query executor + Netty server and joins Helix; the
state model SegmentOnlineOfflineStateModelFactory.java:44 handles
OFFLINE→ONLINE (load segment), ONLINE→OFFLINE (release), →DROPPED
transitions (:73-140). Here the transitions are driven by a watch on the
ideal state; after each transition the server updates the external view,
exactly Helix's contract.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from ..engine.query_executor import QueryExecutor
from ..segment.loader import load_segment
from ..spi import faults
from ..spi.data_types import Schema
from .controller import ONLINE, raw_table_name
from .store import PropertyStore
from ..engine.scheduler import QueryScheduler
from .transport import RpcServer

log = logging.getLogger(__name__)


class ServerInstance:
    def __init__(self, store: PropertyStore, instance_id: str,
                 backend: str = "auto", tags: Optional[list[str]] = None,
                 max_concurrent_queries: int = 8):
        self.store = store
        self.instance_id = instance_id
        self.tags = tags or ["DefaultTenant"]
        self.backend = backend
        self.executor = QueryExecutor(backend=backend)
        # admission control in front of execution (reference:
        # QueryScheduler.submit, fcfs default policy)
        self.scheduler = QueryScheduler(max_concurrent=max_concurrent_queries)
        # tableNameWithType → {segment_name: ImmutableSegment}
        self.segments: dict[str, dict[str, object]] = {}
        self._lock = threading.RLock()
        self._rpc = RpcServer(self._handle)
        self._started = False

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.store.set(f"/INSTANCECONFIGS/{self.instance_id}",
                       {"host": self._rpc.host, "port": self._rpc.port,
                        "tags": self.tags})
        self.store.set(f"/LIVEINSTANCES/{self.instance_id}",
                       {"host": self._rpc.host, "port": self._rpc.port},
                       ephemeral_owner=self.instance_id)
        self.store.watch("/IDEALSTATES/", self._on_ideal_state)
        self._started = True
        # replay current ideal states (Helix replays pending transitions on join)
        for table in self.store.children("/IDEALSTATES"):
            self._converge(table, self.store.get(f"/IDEALSTATES/{table}"))

    def stop(self) -> None:
        """Simulates process death: ephemeral live-instance entry expires.
        Instance config stays (reference: ZK session expiry vs config)."""
        self._started = False
        self._rpc.close()
        # unregister the ideal-state watcher: a dead server left in the
        # store's watch list is pinned alive with every loaded segment's
        # memmap fd — unbounded fd/memory growth under server churn
        try:
            self.store.unwatch(self._on_ideal_state)
        except AttributeError:
            pass  # store impls without unwatch (older remote protocol)
        self.store.expire_session(self.instance_id)

    @property
    def address(self) -> tuple[str, int]:
        return (self._rpc.host, self._rpc.port)

    # -- state transitions --------------------------------------------------
    def _on_ideal_state(self, path: str, value) -> None:
        if not self._started:
            return
        table = path.rsplit("/", 1)[-1]
        self._converge(table, value)

    def _converge(self, table: str, ideal: Optional[dict]) -> None:
        """Diff ideal vs hosted → load/drop (the OFFLINE→ONLINE / →DROPPED
        transitions)."""
        ideal = ideal or {}
        want = {seg for seg, m in ideal.items()
                if m.get(self.instance_id) == ONLINE}
        with self._lock:
            have = set(self.segments.get(table, {}))
            to_load = want - have
            to_drop = have - want
            indexing = None
            if to_load:
                cfg_json = self.store.get(f"/CONFIGS/TABLE/{table}")
                if cfg_json and "tableName" in cfg_json:
                    from ..spi.table_config import TableConfig

                    indexing = TableConfig.from_json(cfg_json).indexing
            for seg in to_load:
                meta = self.store.get(f"/SEGMENTS/{table}/{seg}")
                if meta is None:
                    continue
                try:
                    if faults.ACTIVE:
                        faults.FAULTS.fire("segment.load", table=table,
                                           segment=seg)
                    segment = load_segment(self._fetch(meta["location"]))
                    if indexing is not None:
                        # config-requested indexes the segment was written
                        # without get built at load (SegmentPreProcessor)
                        segment.backfill_indexes(indexing)
                except Exception:
                    # a failed load must not abort convergence of the other
                    # segments — and since the external-view update below
                    # advertises only want & loaded, the broker routes this
                    # segment's replicas elsewhere (or reports it partial)
                    log.exception("%s: failed to load segment %s/%s",
                                  self.instance_id, table, seg)
                    continue
                self.segments.setdefault(table, {})[seg] = segment
            if to_drop:
                # dropped/replaced segments invalidate their cached partial
                # results (host + device tiers) and release device planes —
                # the server-side half of lineage-driven invalidation
                from ..cache.partial import GLOBAL_PARTIAL_CACHE
                from ..segment.device_cache import GLOBAL_DEVICE_CACHE
            for seg in to_drop:
                segment = self.segments.get(table, {}).pop(seg, None)
                GLOBAL_PARTIAL_CACHE.invalidate_segment(seg)
                GLOBAL_DEVICE_CACHE.drop_partials(segment_name=seg)
                if segment is not None:
                    GLOBAL_DEVICE_CACHE.drop(segment)
            self._register_table(table)
            loaded = set(self.segments.get(table, {}))
        # advertise only what actually loaded — a skipped/failed load must
        # not appear ONLINE or the broker would silently lose its rows
        self._update_external_view(table, want & loaded)

    def _fetch(self, location: str) -> str:
        """Deep-store fetch: tarred segments download + untar to a local
        work dir (reference: SegmentFetcherFactory on OFFLINE→ONLINE);
        plain directories load in place."""
        if location.endswith((".tar.gz", ".tgz")):
            import tempfile

            from ..ingestion.batch import untar_segment

            if not hasattr(self, "_untar_dir"):
                self._untar_dir = tempfile.mkdtemp(prefix=f"{self.instance_id}_seg_")
            return untar_segment(location, self._untar_dir)
        return location

    def _register_table(self, table: str) -> None:
        raw = raw_table_name(table)
        schema_json = self.store.get(f"/SCHEMAS/{raw}")
        if schema_json is None or table not in self.segments:
            return
        schema = Schema.from_json(schema_json)
        segments = list(self.segments[table].values())
        cfg = self.store.get(f"/CONFIGS/TABLE/{table}") or {}
        if cfg.get("warmOnLoad") and self.backend != "host":
            # pre-upload column planes to HBM off the convergence thread
            # (reference: segment preload on load — first query skips H2D)
            import threading as _threading

            from ..segment.device_cache import GLOBAL_DEVICE_CACHE

            def _warm(segs=list(segments)):
                for seg in segs:
                    try:
                        GLOBAL_DEVICE_CACHE.warm(seg)
                    except Exception:
                        return  # no accelerator / transient: queries warm lazily

            _threading.Thread(target=_warm, daemon=True,
                              name=f"warm-{table}").start()
        if cfg.get("isDimTable") and schema.primary_key_columns:
            # dimension table: every server holds the full copy and serves
            # LOOKUP joins from it (reference DimensionTableDataManager)
            self.executor.add_dimension_table(schema, segments, name=table)
            # LOOKUP callers name the RAW table
            from ..engine.dim_tables import alias_dimension_table

            alias_dimension_table(raw, table)
            return
        self.executor.add_table(schema, segments, name=table)

    def _update_external_view(self, table: str, online: set) -> None:
        def upd(view):
            view = view or {}
            for seg in list(view):
                view[seg].pop(self.instance_id, None)
                if not view[seg]:
                    del view[seg]
            for seg in online:
                view.setdefault(seg, {})[self.instance_id] = ONLINE
            return view

        self.store.update(f"/EXTERNALVIEW/{table}", upd)

    # -- query plane --------------------------------------------------------
    def _handle(self, request):
        kind = request.get("type")
        if kind == "query":
            return self._handle_query(request)
        if kind == "query_stream":
            return self._handle_query_stream(request)
        if kind == "explain":
            return self._handle_explain(request)
        if kind == "scan_arrow":
            return self._handle_scan_arrow(request)
        if kind == "ping":
            return "pong"
        if kind == "cancel":
            # broker abandon/timeout: flag the tracker so the segment loop's
            # check_cancel stops device work (reference: the /query/{id}
            # DELETE path into the accountant interrupt). A prefix cancel
            # kills every shard of the query (`<query_id>:<n>` ids) and
            # tombstones the prefix so a shard that lost the race to this
            # cancel still dies on arrival.
            reason = request.get("reason", "cancelled by broker")
            qid = request.get("queryId", "")
            if request.get("prefix"):
                return {"cancelled": self.scheduler.accountant.kill_prefix(
                    qid, reason=reason) > 0}
            return {"cancelled": self.scheduler.accountant.kill_query(
                qid, reason=reason)}
        if isinstance(kind, str) and kind.startswith("mse_"):
            return self.mse_worker.handle(request)
        raise ValueError(f"unknown request type {kind}")

    @property
    def mse_worker(self):
        """Multi-stage worker endpoint (mse/distributed.py) — lazily built
        so the MSE runtime only loads when a stage is dispatched here.
        Double-checked under the instance lock: stage dispatch and mailbox
        deliveries arrive CONCURRENTLY (pipelined dispatcher), and an
        unlocked first touch can build two services — the losing request's
        blocks land in an orphaned MailboxStore and the query hangs."""
        worker = getattr(self, "_mse_worker", None)
        if worker is None:
            with self._lock:
                worker = getattr(self, "_mse_worker", None)
                if worker is None:
                    from ..mse.distributed import MseWorkerService

                    worker = MseWorkerService(self)
                    self._mse_worker = worker
        return worker

    def _handle_query(self, request):
        """Execute a QueryContext over an explicit segment list (the broker
        names segments per server, reference InstanceRequest.searchSegments)
        under the scheduler's admission control."""
        table = request["table"]
        names = request["segments"]
        query = request["query"]
        if faults.ACTIVE:
            faults.FAULTS.fire("server.query", table=table,
                               instance=self.instance_id)
        # deadline propagation: the broker stamps its remaining budget on
        # the request; it bounds the scheduler queue wait AND clamps the
        # per-segment loop's timeoutMs (the request is unpickled fresh per
        # RPC, so mutating query_options here is private to this call)
        deadline_ms = request.get("deadlineMs")
        query_id = request.get("queryId")
        timeout_s = 60.0
        if deadline_ms is not None:
            timeout_s = max(0.05, min(60.0, float(deadline_ms) / 1000.0))
            cur = query.query_options.get("timeoutMs")
            query.query_options["timeoutMs"] = (
                float(deadline_ms) if cur is None
                else min(float(cur), float(deadline_ms)))
        with self._lock:
            hosted = self.segments.get(table, {})
            segs = [hosted[n] for n in names if n in hosted]
            missing = [n for n in names if n not in hosted]

        def run(tracker):
            return self.executor.execute_segments(query, segs, tracker=tracker)

        # trace option: the server owns a trace for its shard of the query
        # (scheduler.submit runs `run` on this thread, so the thread-local
        # trace covers execute_segments and its family dispatches); the span
        # list rides back next to the datatable for the broker to merge
        from ..spi.trace import TRACING

        trace = None
        if query.query_options.get("trace") in (True, "true", 1) \
                and TRACING.active_trace() is None:
            trace = TRACING.start_trace(f"server:{self.instance_id}")
        try:
            combined, stats = self.scheduler.submit(
                run, group=table, timeout_s=timeout_s, query_id=query_id)
        finally:
            if trace is not None:
                TRACING.end_trace()
        stats["missing_segments"] = missing
        # intermediates travel as the versioned binary DataTable, not as
        # pickled Python objects (reference: DataTableImplV4 on the wire)
        from .datatable import encode

        out = {"datatable": encode(combined, stats)}
        if trace is not None:
            out["trace"] = trace.to_json()
        return out

    def _handle_scan_arrow(self, request):
        """Direct Arrow IPC segment read for external engines — straight
        from segment storage, no SQL/DataTable in the data path
        (reference: the Spark connector's gRPC server reads;
        connectors/arrow_reader.py holds the client half)."""
        from ..connectors.arrow_reader import segment_ipc_bytes

        table = request["table"]
        name = request["segment"]
        with self._lock:
            seg = self.segments.get(table, {}).get(name)
        if seg is None:
            raise ValueError(f"segment {name} not hosted for {table}")
        ipc = segment_ipc_bytes(seg, request.get("columns"))
        return {"ipc": ipc, "numRows": seg.num_docs}

    def _handle_explain(self, request):
        """Render the operator-tree plan for this server's hosted segments
        without executing (reference: EXPLAIN runs the plan maker only)."""
        from types import SimpleNamespace

        from ..engine.explain import explain_plan

        table = request["table"]
        names = request["segments"]
        query = request["query"]
        with self._lock:
            hosted = self.segments.get(table, {})
            segs = [hosted[n] for n in names if n in hosted]
        rt = explain_plan(query, SimpleNamespace(segments=segs),
                          self.executor.pruner,
                          backend=self.executor.backend,
                          use_star_tree=self.executor.use_star_tree)
        return {"columns": rt.schema.column_names,
                "types": rt.schema.column_types, "rows": rt.rows}

    def _handle_query_stream(self, request):
        """Server-streaming query: one DataTable chunk per segment as each
        finishes (reference: GrpcQueryServer.submit streaming per-segment
        blocks for streamable operators, GrpcQueryServer.java:65)."""
        from .datatable import encode

        table = request["table"]
        names = request["segments"]
        query = request["query"]
        with self._lock:
            hosted = self.segments.get(table, {})
            segs = [(n, hosted[n]) for n in names if n in hosted]
            missing = [n for n in names if n not in hosted]

        def stream():
            if missing:
                raise RuntimeError(f"missing routed segments: {missing}")
            for name, seg in segs:
                combined, stats = self.executor.execute_segments(query, [seg])
                stats["segment"] = name
                yield encode(combined, stats)

        return stream()
