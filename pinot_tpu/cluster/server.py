"""Server role: converge to ideal state, host segments, serve queries.

Reference analogue: pinot-server — BaseServerStarter.start:578 boots the
instance data manager + query executor + Netty server and joins Helix; the
state model SegmentOnlineOfflineStateModelFactory.java:44 handles
OFFLINE→ONLINE (load segment), ONLINE→OFFLINE (release), →DROPPED
transitions (:73-140). Here the transitions are driven by a watch on the
ideal state; after each transition the server updates the external view,
exactly Helix's contract.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Optional

from ..engine.query_executor import QueryExecutor
from ..segment.loader import SegmentIntegrityError, load_segment
from ..spi import faults
from ..spi.data_types import Schema
from ..spi.metrics import SERVER_METRICS, ServerMeter, ServerTimer
from ..storage.tier import SegmentTierManager
from .controller import ERROR, ONLINE, raw_table_name
from .store import PropertyStore
from ..engine.scheduler import QueryScheduler
from .transport import RpcServer

log = logging.getLogger(__name__)


def _quantile(sorted_ms: list, q: float) -> float:
    """Nearest-rank quantile over an already-sorted latency sample."""
    if not sorted_ms:
        return 0.0
    idx = min(len(sorted_ms) - 1, int(q * len(sorted_ms)))
    return round(float(sorted_ms[idx]), 3)


def _safe_mesh_devices() -> int:
    """meshDevices gauge supplier: local chips the segment mesh may span
    (1 when the device backend is unavailable at scrape time)."""
    try:
        from ..parallel.mesh import mesh_device_count

        return mesh_device_count()
    except Exception:
        return 1


class ServerInstance:
    def __init__(self, store: PropertyStore, instance_id: str,
                 backend: str = "auto", tags: Optional[list[str]] = None,
                 max_concurrent_queries: int = 8,
                 local_storage_mb: Optional[float] = None):
        self.store = store
        self.instance_id = instance_id
        self.tags = tags or ["DefaultTenant"]
        self.backend = backend
        self.executor = QueryExecutor(backend=backend)
        # tiered storage: the byte-budgeted local disk tier beneath the HBM
        # plane cache. Every locally materialized segment directory —
        # converge load, cold lazy load, repair/rebalance re-fetch — goes
        # through tier.acquire(), so ONE budget accounts for all of them.
        # ``local_storage_mb`` overrides PINOT_TPU_LOCAL_STORAGE_MB.
        tier_kwargs = {}
        if local_storage_mb is not None:
            tier_kwargs["budget_mb"] = local_storage_mb
        self._tier = SegmentTierManager(
            instance_id=instance_id, evict_cb=self._evict_segment,
            heat_fn=self._broker_table_costs, **tier_kwargs)
        # cold (metadata-only) segments: advertised ONLINE but not local —
        # tableNameWithType → {segment_name: /SEGMENTS meta dict}
        self._cold: dict[str, dict[str, dict]] = {}
        # catalog meta of RESIDENT segments, kept so eviction can demote
        # them back to cold without a store read
        self._seg_meta: dict[tuple, dict] = {}
        # in-flight cold warms: (table, seg) → completion Event, so
        # concurrent queries coalesce on one fetch instead of racing
        self._warming: dict[tuple, threading.Event] = {}
        # admission control in front of execution (reference:
        # QueryScheduler.submit, fcfs default policy)
        self.scheduler = QueryScheduler(max_concurrent=max_concurrent_queries)
        # tableNameWithType → {segment_name: ImmutableSegment}
        self.segments: dict[str, dict[str, object]] = {}
        # integrity quarantine: tableNameWithType → {segment_name → entry}
        # (a replica that failed load-verify; advertised ERROR, never
        # routed, owned by the repair path until it re-verifies)
        self.quarantined: dict[str, dict[str, dict]] = {}
        # transient (non-integrity) load failures: (table, seg) → attempts;
        # bounded so one flaky deep-store read doesn't loop a converge hot,
        # reset by a repair nudge, a successful load, or a drop
        self._load_failures: dict[tuple, int] = {}
        self.max_load_retries = int(
            os.environ.get("PINOT_TPU_LOAD_RETRIES", "5"))
        self._lock = threading.RLock()
        self._rpc = RpcServer(self._handle)
        # compile/HBM telemetry: supplier gauges polled only at /metrics
        # scrape time (spi/metrics.py evaluates suppliers in snapshot()),
        # so the dispatch hot path never pays for them
        from ..engine.compile_registry import COMPILE_REGISTRY
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE
        from ..spi.metrics import ServerGauge

        SERVER_METRICS.set_gauge(
            ServerGauge.COMPILE_FAMILIES,
            lambda: COMPILE_REGISTRY.totals()["families"])
        SERVER_METRICS.set_gauge(
            ServerGauge.COMPILE_MS_TOTAL,
            lambda: COMPILE_REGISTRY.totals()["compileMs"])
        SERVER_METRICS.set_gauge(
            ServerGauge.HBM_BYTES_USED,
            lambda: GLOBAL_DEVICE_CACHE.hbm_telemetry()["bytesUsed"])
        SERVER_METRICS.set_gauge(
            ServerGauge.HBM_BYTES_HIGH_WATER,
            lambda: GLOBAL_DEVICE_CACHE.hbm_telemetry()["highWater"]["total"])
        SERVER_METRICS.set_gauge(
            ServerGauge.HBM_EVICTIONS,
            lambda: GLOBAL_DEVICE_CACHE.hbm_telemetry()["evictions"])
        # mesh execution telemetry: how many local chips the segment-axis
        # mesh spans, plus per-device HBM residency (one dynamic gauge per
        # device id — scrape-time shard walks, never on the query path)
        if backend != "host":
            SERVER_METRICS.set_gauge(ServerGauge.MESH_DEVICES,
                                     _safe_mesh_devices)
            try:
                import jax

                for d in jax.devices():
                    SERVER_METRICS.set_gauge(
                        f"hbmBytesUsedDevice.{d.id}",
                        lambda did=int(d.id):
                        GLOBAL_DEVICE_CACHE.hbm_per_device().get(did, 0))
            except Exception:
                pass
        self._started = False
        # readiness (GET /health/readiness) gates on the FIRST converge
        # pass completing, not on mere registration: a server that joined
        # but has not loaded its ideal-state segments would answer queries
        # with missing-segment errors
        self._converged = False
        # per-INSTANCE wall-ms of recent query RPCs — the straggler signal
        # for the controller's ClusterHealthChecker (the metrics-registry
        # timers are process-wide singletons, indistinguishable between
        # co-hosted instances)
        self._query_ms: deque = deque(maxlen=256)

    # -- lifecycle ----------------------------------------------------------
    def start(self) -> None:
        self.store.set(f"/INSTANCECONFIGS/{self.instance_id}",
                       {"host": self._rpc.host, "port": self._rpc.port,
                        "tags": self.tags})
        self.store.set(f"/LIVEINSTANCES/{self.instance_id}",
                       {"host": self._rpc.host, "port": self._rpc.port},
                       ephemeral_owner=self.instance_id)
        self.store.watch("/IDEALSTATES/", self._on_ideal_state)
        self.store.watch("/REPAIRS/", self._on_repair_request)
        self.store.watch("/PREFETCH/", self._on_prefetch)
        self._started = True
        # replay current ideal states (Helix replays pending transitions on join)
        for table in self.store.children("/IDEALSTATES"):
            self._converge(table, self.store.get(f"/IDEALSTATES/{table}"))
        self._converged = True

    def stop(self) -> None:
        """Simulates process death: ephemeral live-instance entry expires.
        Instance config stays (reference: ZK session expiry vs config)."""
        self._started = False
        self._converged = False
        self._rpc.close()
        # unregister the ideal-state watcher: a dead server left in the
        # store's watch list is pinned alive with every loaded segment's
        # memmap fd — unbounded fd/memory growth under server churn
        try:
            self.store.unwatch(self._on_ideal_state)
            self.store.unwatch(self._on_repair_request)
            self.store.unwatch(self._on_prefetch)
        except AttributeError:
            pass  # store impls without unwatch (older remote protocol)
        self.store.expire_session(self.instance_id)
        # release every tier-local copy (also cleans the work dirs the old
        # per-instance untar/repair tempdirs used to leak)
        self._tier.close()

    @property
    def address(self) -> tuple[str, int]:
        return (self._rpc.host, self._rpc.port)

    # -- state transitions --------------------------------------------------
    def _on_ideal_state(self, path: str, value) -> None:
        if not self._started:
            return
        table = path.rsplit("/", 1)[-1]
        self._converge(table, value)

    def _converge(self, table: str, ideal: Optional[dict]) -> None:
        """Diff ideal vs hosted → load/drop (the OFFLINE→ONLINE / →DROPPED
        transitions)."""
        ideal = ideal or {}
        want = {seg for seg, m in ideal.items()
                if m.get(self.instance_id) == ONLINE}
        with self._lock:
            have = set(self.segments.get(table, {}))
            to_load = want - have
            to_drop = have - want
            indexing = None
            if to_load:
                cfg_json = self.store.get(f"/CONFIGS/TABLE/{table}")
                if cfg_json and "tableName" in cfg_json:
                    from ..spi.table_config import TableConfig

                    indexing = TableConfig.from_json(cfg_json).indexing
            repair_kicks = []
            for seg in to_load:
                meta = self.store.get(f"/SEGMENTS/{table}/{seg}")
                if meta is None:
                    continue
                if seg in self.quarantined.get(table, {}):
                    # the local copy failed verification — reloading it
                    # would just fail again; the repair path owns it until
                    # a fresh deep-store fetch verifies
                    continue
                if self._load_failures.get((table, seg), 0) \
                        >= self.max_load_retries:
                    continue  # transient retries exhausted; needs a nudge
                if self._tier.should_lazy_load():
                    # the local tier is at budget: register the segment
                    # COLD — metadata only, no fetch. It still advertises
                    # ONLINE below; the first query that routes here (or a
                    # prefetch nudge) warms it lazily
                    self._cold.setdefault(table, {})[seg] = meta
                    continue
                try:
                    segment = self._load_segment_verified(
                        table, seg, meta, indexing)
                except SegmentIntegrityError as e:
                    # integrity failure: quarantine (ERROR in the external
                    # view, excluded from routing) and hand off to repair
                    self._quarantine(table, seg, e)
                    repair_kicks.append(seg)
                    continue
                except Exception:
                    # a failed load must not abort convergence of the other
                    # segments — and since the external-view update below
                    # advertises only want & loaded, the broker routes this
                    # segment's replicas elsewhere (or reports it partial).
                    # Transient (non-integrity) failures retry on the NEXT
                    # converge, bounded by max_load_retries.
                    n = self._load_failures.get((table, seg), 0) + 1
                    self._load_failures[(table, seg)] = n
                    log.exception("%s: failed to load segment %s/%s "
                                  "(attempt %d/%d)", self.instance_id, table,
                                  seg, n, self.max_load_retries)
                    continue
                self.segments.setdefault(table, {})[seg] = segment
                self._seg_meta[(table, seg)] = meta
                self._cold.get(table, {}).pop(seg, None)
                self._load_failures.pop((table, seg), None)
            cold_tbl = self._cold.get(table, {})
            cold_drop = set(cold_tbl) - want
            if to_drop or cold_drop:
                # dropped/replaced segments invalidate their cached partial
                # results (host + device tiers) and release device planes —
                # the server-side half of lineage-driven invalidation
                from ..cache.partial import GLOBAL_PARTIAL_CACHE
                from ..segment.device_cache import GLOBAL_DEVICE_CACHE
            for seg in cold_drop:
                # a departed cold segment has no local bytes or live object,
                # but name-keyed HBM leftovers from its resident days and
                # journaled partials must still go
                cold_tbl.pop(seg, None)
                GLOBAL_PARTIAL_CACHE.invalidate_segment(seg)
                GLOBAL_DEVICE_CACHE.drop_partials(segment_name=seg)
                GLOBAL_DEVICE_CACHE.drop_named(seg)
            for seg in to_drop:
                segment = self.segments.get(table, {}).pop(seg, None)
                self._seg_meta.pop((table, seg), None)
                self._tier.forget(table, seg)
                GLOBAL_PARTIAL_CACHE.invalidate_segment(seg)
                GLOBAL_DEVICE_CACHE.drop_partials(segment_name=seg)
                if segment is not None:
                    GLOBAL_DEVICE_CACHE.drop(segment)
                else:
                    # the live object is gone (lost mid-move, repair window,
                    # prior incarnation of this instance) — id()-keyed views
                    # and stacked [S, N] batch-family planes can only be
                    # found by NAME now, and left behind they pin HBM for a
                    # segment this server no longer serves
                    GLOBAL_DEVICE_CACHE.drop_named(seg)
            # segments dropped from the ideal state release their quarantine
            # entry and transient-failure counters — nothing left to repair
            for seg in set(self.quarantined.get(table, ())) - want:
                self.quarantined[table].pop(seg, None)
            for key in [k for k in self._load_failures
                        if k[0] == table and k[1] not in want]:
                self._load_failures.pop(key, None)
            self._register_table(table)
            loaded = set(self.segments.get(table, {}))
            cold = set(self._cold.get(table, ()))
        # advertise what actually loaded PLUS the cold (metadata-only)
        # registrations — a cold replica is still routable (the first query
        # warms it); a skipped/FAILED load must not appear ONLINE or the
        # broker would silently lose its rows
        self._update_external_view(table, (want & loaded) | (want & cold))
        for seg in repair_kicks:
            self._kick_repair(table, seg)

    def _fetch(self, location: str, fresh: bool = False,
               table: str = "", seg: str = "") -> str:
        """Deep-store fetch THROUGH the storage tier: tarred segments
        download + untar into the SegmentTierManager's byte-budgeted local
        cache (reference: SegmentFetcherFactory on OFFLINE→ONLINE), so
        converge loads, cold lazy loads and repair/rebalance re-fetches all
        draw from one budget; plain directories load in place. ``fresh``
        fetches a new copy so a repair never reuses a possibly-damaged
        local one. The returned path carries one reader ref (``hold``) so
        a concurrent acquire's eviction pass cannot reclaim the directory
        before the loader has read it; the caller drops it via
        ``tier.release()`` once the segment is loaded."""
        if not seg:
            seg = os.path.basename(str(location))
        return self._tier.acquire(table or "_unassigned", seg, location,
                                  fresh=fresh, hold=True)

    def _load_segment_verified(self, table: str, seg: str, meta: dict,
                               indexing, fresh: bool = False,
                               cold: bool = False):
        """Fetch + load + verify one segment. The ``segment.load`` fault
        point fires here; an injected ``corrupt`` fault damages a local COPY
        of the fetched directory (the deep store stays pristine, so repair
        can heal) and the verifying loader is expected to catch it. Cold
        lazy loads additionally pass through the ``storage.fetch`` point
        with the same corrupt→quarantine→repair-fresh contract as
        ``rebalance.move``."""
        corruption = None
        if faults.ACTIVE:
            try:
                faults.FAULTS.fire("segment.load", table=table, segment=seg)
            except faults.InjectedCorruption as c:
                corruption = c
            if corruption is None and cold:
                try:
                    faults.FAULTS.fire("storage.fetch", table=table,
                                       segment=seg,
                                       instance=self.instance_id)
                except faults.InjectedCorruption as c:
                    corruption = c
            if corruption is None and self._is_move_destination(table, seg):
                # chaos seam for mid-rebalance failure: this load is the
                # DESTINATION fetch of an in-flight segment move (the
                # /REBALANCE journal names this instance as the target)
                try:
                    faults.FAULTS.fire("rebalance.move", table=table,
                                       segment=seg,
                                       instance=self.instance_id)
                except faults.InjectedCorruption as c:
                    corruption = c
        local = self._fetch(meta["location"], fresh=fresh,
                            table=table, seg=seg)
        try:
            if corruption is not None:
                local = self._corrupt_local_copy(local, corruption)
            segment = load_segment(local, expected_crc=meta.get("crc"))
            if indexing is not None:
                # config-requested indexes the segment was written
                # without get built at load (SegmentPreProcessor)
                segment.backfill_indexes(indexing)
        finally:
            self._tier.release(table or "_unassigned", seg)
        return segment

    def _is_move_destination(self, table: str, seg: str) -> bool:
        """True when an active rebalance move targets (table, seg) AT this
        instance — consulted only under faults.ACTIVE, so the extra store
        read never taxes a normal load."""
        try:
            job = self.store.get(f"/REBALANCE/{table}")
        except Exception:
            return False
        if not job or job.get("status") not in ("IN_PROGRESS", "ABORTING"):
            return False
        for move in (job.get("movePlan") or []):
            if move.get("segment") == seg \
                    and self.instance_id in (move.get("adds") or {}) \
                    and move.get("state") in ("PENDING", "ADDING"):
                return True
        return False

    def _corrupt_local_copy(self, local: str, c) -> str:
        """Copy the fetched segment dir and damage the copy's data file —
        models on-disk/local-FS corruption without touching the source."""
        import shutil
        import tempfile
        from pathlib import Path

        from ..segment.format import DATA_FILE

        src = Path(local)
        dst = Path(tempfile.mkdtemp(
            prefix=f"{self.instance_id}_corrupt_")) / src.name
        shutil.copytree(src, dst)
        data = dst / DATA_FILE
        data.write_bytes(faults.corrupt_bytes(
            data.read_bytes(), c.mode, c.seed, c.index))
        return str(dst)

    # -- integrity quarantine + repair --------------------------------------
    def _quarantine(self, table: str, seg: str, err) -> None:
        """Record an integrity failure: the replica is advertised ERROR
        (excluded from broker routing) with the reason kept for
        /debug/segments, and the repair path takes ownership."""
        entry = {
            "reason": str(err),
            "columns": list(getattr(err, "columns", []) or []),
            "sinceMs": int(time.time() * 1000),
            "repairAttempts": 0,
            "unrepairable": False,
        }
        with self._lock:
            self.quarantined.setdefault(table, {})[seg] = entry
        SERVER_METRICS.add_meter(ServerMeter.SEGMENTS_QUARANTINED)
        log.error("%s: quarantined segment %s/%s: %s",
                  self.instance_id, table, seg, err)

    def _kick_repair(self, table: str, seg: str) -> None:
        """Schedule a background repair unless auto-repair is disabled
        (tests disable it to drive repair deterministically)."""
        if os.environ.get("PINOT_TPU_AUTO_REPAIR", "true").lower() \
                in ("false", "0", "off", "no"):
            return
        threading.Thread(target=self.repair_segment, args=(table, seg),
                         daemon=True, name=f"repair-{seg}").start()

    def repair_segment(self, table: str, seg: str) -> bool:
        """Self-repair a quarantined segment: re-fetch a FRESH copy from
        deep store, re-verify, and rejoin the external view. Bounded
        retries with exponential backoff (PINOT_TPU_REPAIR_RETRIES /
        PINOT_TPU_REPAIR_BACKOFF_MS); exhaustion flags the replica
        unrepairable so the controller's SegmentIntegrityChecker can
        surface it instead of re-nudging forever."""
        retries = max(1, int(os.environ.get("PINOT_TPU_REPAIR_RETRIES", "3")))
        backoff_s = float(
            os.environ.get("PINOT_TPU_REPAIR_BACKOFF_MS", "50")) / 1000.0
        for attempt in range(retries):
            if attempt:
                time.sleep(min(backoff_s * (2 ** (attempt - 1)), 2.0))
            meta = self.store.get(f"/SEGMENTS/{table}/{seg}")
            ideal = self.store.get(f"/IDEALSTATES/{table}") or {}
            assigned = (ideal.get(seg) or {}).get(self.instance_id) == ONLINE
            if meta is None or not assigned:
                # dropped or moved away while quarantined — nothing to heal
                with self._lock:
                    self.quarantined.get(table, {}).pop(seg, None)
                return False
            indexing = None
            cfg_json = self.store.get(f"/CONFIGS/TABLE/{table}")
            if cfg_json and "tableName" in cfg_json:
                from ..spi.table_config import TableConfig

                indexing = TableConfig.from_json(cfg_json).indexing
            with self._lock:
                ent = self.quarantined.get(table, {}).get(seg)
                if ent is not None:
                    ent["repairAttempts"] += 1
            try:
                segment = self._load_segment_verified(
                    table, seg, meta, indexing, fresh=True)
            except Exception as e:
                log.warning("%s: repair attempt %d/%d for %s/%s failed: %s",
                            self.instance_id, attempt + 1, retries, table,
                            seg, e)
                continue
            with self._lock:
                self.segments.setdefault(table, {})[seg] = segment
                self._seg_meta[(table, seg)] = meta
                self._cold.get(table, {}).pop(seg, None)
                self.quarantined.get(table, {}).pop(seg, None)
                self._load_failures.pop((table, seg), None)
                self._register_table(table)
                want = {s for s, m in ideal.items()
                        if m.get(self.instance_id) == ONLINE}
                online = (want & set(self.segments.get(table, {}))) \
                    | (want & set(self._cold.get(table, ())))
            SERVER_METRICS.add_meter(ServerMeter.SEGMENT_REPAIRS)
            self._update_external_view(table, online)
            log.info("%s: repaired segment %s/%s from deep store "
                     "(attempt %d)", self.instance_id, table, seg,
                     attempt + 1)
            return True
        with self._lock:
            ent = self.quarantined.get(table, {}).get(seg)
            if ent is not None:
                ent["unrepairable"] = True
        log.error("%s: segment %s/%s unrepairable after %d attempts",
                  self.instance_id, table, seg, retries)
        return False

    def _on_repair_request(self, path: str, value) -> None:
        """Controller nudge via /REPAIRS/{table}/{seg} (the
        SegmentIntegrityChecker noticed degraded replication): retry a
        quarantined replica's repair — synchronously, and even when
        auto-repair is off, because an explicit nudge IS the operator
        asking — or re-converge a transient failure whose bounded retries
        were exhausted."""
        if not self._started or value is None:
            return
        parts = path.strip("/").split("/")
        if len(parts) != 3:
            return
        _, table, seg = parts
        with self._lock:
            ent = self.quarantined.get(table, {}).get(seg)
            if ent is not None:
                ent["unrepairable"] = False
            self._load_failures.pop((table, seg), None)
        if ent is not None:
            self.repair_segment(table, seg)
        else:
            self._converge(table, self.store.get(f"/IDEALSTATES/{table}"))

    # -- tiered storage: evict / warm / prefetch -----------------------------
    def _evict_segment(self, table: str, seg: str):
        """Tier evict callback: demote a resident segment to cold
        (metadata-only) state under budget pressure. The deep-store bytes
        are unchanged, so this must NOT bump /CACHEEPOCH and does not touch
        the external view — the replica stays ONLINE and re-fetchable.
        HBM stacks/partials for the departed copy drop by name (the PR-14
        departure hygiene path). Returns the live ImmutableSegment so the
        tier can defer destroy() until in-flight readers drain."""
        from ..cache.partial import GLOBAL_PARTIAL_CACHE
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE

        with self._lock:
            segment = self.segments.get(table, {}).pop(seg, None)
            meta = self._seg_meta.pop((table, seg), None)
            if meta is not None:
                self._cold.setdefault(table, {})[seg] = meta
            if segment is not None:
                self._register_table(table)
        GLOBAL_PARTIAL_CACHE.invalidate_segment(seg)
        GLOBAL_DEVICE_CACHE.drop_partials(segment_name=seg)
        if segment is not None:
            GLOBAL_DEVICE_CACHE.drop(segment)
        GLOBAL_DEVICE_CACHE.drop_named(seg)
        SERVER_METRICS.add_meter(ServerMeter.SEGMENT_EVICTIONS)
        log.info("%s: evicted segment %s/%s to cold (metadata-only)",
                 self.instance_id, table, seg)
        return segment

    def _broker_table_costs(self) -> dict:
        """Fleet-wide decayed per-table query cost from the broker
        /BROKERSTATE beacons (PR-10 WorkloadTracker) — the tier's eviction
        heat weighting. Consulted only when the tier must evict, never on
        the query path."""
        costs: dict[str, float] = {}
        try:
            ids = self.store.children("/BROKERSTATE")
        except Exception:
            return costs
        for bid in ids:
            state = self.store.get(f"/BROKERSTATE/{bid}") or {}
            for t, c in (state.get("tableCostsMs") or {}).items():
                try:
                    costs[t] = max(costs.get(t, 0.0), float(c))
                except (TypeError, ValueError):
                    continue
        # beacons carry broker-facing table names; tier entries are keyed
        # by the type-suffixed internal name — project costs onto both
        for nwt in self._tables_named(list(costs)):
            raw = nwt.rsplit("_", 1)[0]
            if raw in costs:
                costs[nwt] = max(costs.get(nwt, 0.0), costs[raw])
        return costs

    def _tables_named(self, names) -> list:
        """Hosted (resident or cold) internal table names matching any of
        the given broker-facing names — either exactly or modulo the
        ``_OFFLINE``/``_REALTIME`` type suffix."""
        wanted = set(names)
        with self._lock:
            hosted = set(self.segments) | set(self._cold)
        return sorted(t for t in hosted
                      if t in wanted or t.rsplit("_", 1)[0] in wanted)

    def _kick_warm(self, table: str, seg: str) -> threading.Event:
        """Start (or join) a background warm of one cold segment. Returns
        the completion event; concurrent callers coalesce on one fetch."""
        key = (table, seg)
        with self._lock:
            if seg in self.segments.get(table, {}):
                done = threading.Event()
                done.set()
                return done
            ev = self._warming.get(key)
            if ev is not None:
                return ev
            ev = self._warming[key] = threading.Event()
        threading.Thread(target=self._warm_leader, args=(table, seg, ev),
                         daemon=True, name=f"warm-{seg}").start()
        return ev

    def _warm_leader(self, table: str, seg: str, ev: threading.Event) -> None:
        """Fetch + verify + load one cold segment (the single in-flight
        warm for its (table, seg) key). An integrity failure quarantines
        and kicks repair — exactly the rebalance.move contract — so a
        corrupt deep-store fetch heals with a fresh copy instead of being
        served or retried in place."""
        t0 = time.perf_counter()
        try:
            with self._lock:
                meta = self._cold.get(table, {}).get(seg)
            if meta is None:
                return
            indexing = None
            cfg_json = self.store.get(f"/CONFIGS/TABLE/{table}")
            if cfg_json and "tableName" in cfg_json:
                from ..spi.table_config import TableConfig

                indexing = TableConfig.from_json(cfg_json).indexing
            try:
                segment = self._load_segment_verified(
                    table, seg, meta, indexing, cold=True)
            except SegmentIntegrityError as e:
                with self._lock:
                    self._cold.get(table, {}).pop(seg, None)
                self._quarantine(table, seg, e)
                self._kick_repair(table, seg)
                return
            except Exception:
                with self._lock:
                    n = self._load_failures.get((table, seg), 0) + 1
                    self._load_failures[(table, seg)] = n
                log.warning("%s: cold load of %s/%s failed (attempt %d)",
                            self.instance_id, table, seg, n, exc_info=True)
                return
            with self._lock:
                self._cold.get(table, {}).pop(seg, None)
                self.segments.setdefault(table, {})[seg] = segment
                self._seg_meta[(table, seg)] = meta
                self._load_failures.pop((table, seg), None)
                self._register_table(table)
            SERVER_METRICS.add_meter(ServerMeter.SEGMENT_COLD_LOADS)
            SERVER_METRICS.update_timer(
                ServerTimer.COLD_LOAD_MS, (time.perf_counter() - t0) * 1000.0)
        finally:
            with self._lock:
                self._warming.pop((table, seg), None)
            ev.set()

    def _warm_cold_segments(self, table: str, cold_names: list,
                            deadline_ms) -> list:
        """Deadline-aware lazy warm of cold routed segments: kick all the
        warms, then wait for each inside the remaining broker budget minus
        a floor. Returns the names still cold when the budget ran out —
        they keep warming in the background (next query finds them
        resident) while THIS response degrades instead of blocking."""
        floor_s = float(
            os.environ.get("PINOT_TPU_COLD_SYNC_FLOOR_MS", "25")) / 1000.0
        deadline = None
        if deadline_ms is not None:
            deadline = time.monotonic() + max(0.0, float(deadline_ms) / 1000.0)
        events = [(seg, self._kick_warm(table, seg)) for seg in cold_names]
        still = []
        for seg, ev in events:
            timeout = None
            if deadline is not None:
                timeout = max(0.0, deadline - time.monotonic() - floor_s)
            ev.wait(timeout)
            with self._lock:
                if seg not in self.segments.get(table, {}):
                    still.append(seg)
        return still

    def _on_prefetch(self, path: str, value) -> None:
        """/PREFETCH/{table} nudge from the leader's StoragePrefetcher:
        mark the table hot (goes last in eviction order for the hot TTL)
        and warm its cold segments in the background while tier headroom
        remains, so the hot table is resident before traffic lands."""
        if not self._started or value is None:
            return
        parts = path.strip("/").split("/")
        if len(parts) != 2:
            return
        # the nudge names the broker-facing table; hosted state is keyed
        # by the type-suffixed internal name
        table = parts[1]
        self._tier.note_hot(table)
        for nwt in self._tables_named([table]):
            self._tier.note_hot(nwt)
            with self._lock:
                cold = sorted(self._cold.get(nwt, {}))
            if cold:
                threading.Thread(target=self._prefetch_warm,
                                 args=(nwt, cold),
                                 daemon=True,
                                 name=f"prefetch-{nwt}").start()
        # the same nudge pre-warms the table's AOT-persisted executables:
        # the prefetcher predicts traffic is about to land here, so deserialize
        # its top family programs off the serving path (engine/aot_cache.py)
        from ..engine.aot_cache import enabled as _aot_enabled, prewarm_table
        if _aot_enabled():
            threading.Thread(target=prewarm_table, args=(table,),
                             daemon=True,
                             name=f"aot-prewarm-{table}").start()

    def _prefetch_warm(self, table: str, names: list) -> None:
        for seg in names:
            if not self._started or not self._tier.headroom():
                return  # prefetch warms fill headroom; they never evict
            self._kick_warm(table, seg).wait(30.0)
            with self._lock:
                ok = seg in self.segments.get(table, {})
            if ok:
                SERVER_METRICS.add_meter(ServerMeter.PREFETCH_HITS)

    def debug_storage(self) -> dict:
        """Storage-tier inventory for GET /debug/storage: local-tier
        budget/usage, resident vs cold (metadata-only) segments per table,
        and the in-flight warm queue."""
        with self._lock:
            tables = sorted(set(self.segments) | set(self._cold))
            per_table = {
                t: {"resident": sorted(self.segments.get(t, {})),
                    "cold": sorted(self._cold.get(t, {}))}
                for t in tables}
            warming = sorted(f"{t}/{s}" for t, s in self._warming)
        return {
            "localTier": self._tier.stats(),
            "residentSegments": sum(len(v["resident"])
                                    for v in per_table.values()),
            "coldSegments": sum(len(v["cold"]) for v in per_table.values()),
            "warming": warming,
            "tables": per_table,
        }

    def health_status(self) -> dict:
        """Per-instance health beacon: answered over RPC (`status`) to the
        controller's ClusterHealthChecker and over GET /debug/status. Reads
        only instance-local state plus the process metric singletons — no
        device syncs, no query-path locks beyond the instance lock."""
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE

        lat = sorted(self._query_ms)
        with self._lock:
            quarantined = {t: sorted(q) for t, q in self.quarantined.items()
                           if q}
            num_segments = sum(len(s) for s in self.segments.values())
            num_docs = sum(int(getattr(seg, "num_docs", 0))
                           for table in self.segments.values()
                           for seg in table.values())
        return {
            "instanceId": self.instance_id,
            "started": self._started,
            "converged": self._converged,
            "queryLatencyMs": {
                "count": len(lat),
                "p50": _quantile(lat, 0.50),
                "p95": _quantile(lat, 0.95),
                "p99": _quantile(lat, 0.99),
            },
            "hbm": GLOBAL_DEVICE_CACHE.hbm_stats(),
            "segmentCache": {
                "hits": SERVER_METRICS.meter_count(
                    ServerMeter.SEGMENT_CACHE_HITS),
                "misses": SERVER_METRICS.meter_count(
                    ServerMeter.SEGMENT_CACHE_MISSES),
            },
            "hbmOomEvents": SERVER_METRICS.meter_count(
                ServerMeter.HBM_OOM_EVENTS),
            "quarantined": quarantined,
            "numSegments": num_segments,
            "numDocs": num_docs,
        }

    def debug_segments(self) -> dict:
        """Hosted-vs-quarantined segment inventory for GET /debug/segments."""
        with self._lock:
            out = {}
            for table in sorted(set(self.segments) | set(self.quarantined)):
                q = self.quarantined.get(table, {})
                out[table] = {
                    "served": sorted(self.segments.get(table, {})),
                    "quarantined": {s: dict(e) for s, e in sorted(q.items())},
                }
            return out

    def _register_table(self, table: str) -> None:
        raw = raw_table_name(table)
        schema_json = self.store.get(f"/SCHEMAS/{raw}")
        if schema_json is None or table not in self.segments:
            return
        schema = Schema.from_json(schema_json)
        segments = list(self.segments[table].values())
        cfg = self.store.get(f"/CONFIGS/TABLE/{table}") or {}
        if cfg.get("warmOnLoad") and self.backend != "host":
            # pre-upload column planes to HBM off the convergence thread
            # (reference: segment preload on load — first query skips H2D)
            import threading as _threading

            from ..segment.device_cache import GLOBAL_DEVICE_CACHE

            def _warm(segs=list(segments)):
                for seg in segs:
                    try:
                        GLOBAL_DEVICE_CACHE.warm(seg)
                    except Exception:
                        return  # no accelerator / transient: queries warm lazily

            _threading.Thread(target=_warm, daemon=True,
                              name=f"warm-{table}").start()
        if cfg.get("isDimTable") and schema.primary_key_columns:
            # dimension table: every server holds the full copy and serves
            # LOOKUP joins from it (reference DimensionTableDataManager)
            self.executor.add_dimension_table(schema, segments, name=table)
            # LOOKUP callers name the RAW table
            from ..engine.dim_tables import alias_dimension_table

            alias_dimension_table(raw, table)
            return
        self.executor.add_table(schema, segments, name=table)

    def _update_external_view(self, table: str, online: set) -> None:
        with self._lock:
            error = set(self.quarantined.get(table, ())) - set(online)

        def upd(view):
            view = view or {}
            for seg in list(view):
                view[seg].pop(self.instance_id, None)
                if not view[seg]:
                    del view[seg]
            for seg in online:
                view.setdefault(seg, {})[self.instance_id] = ONLINE
            # quarantined replicas are advertised ERROR (reference: Helix
            # ERROR state) — visible to the controller's integrity checker,
            # invisible to broker routing (which selects ONLINE only)
            for seg in error:
                view.setdefault(seg, {})[self.instance_id] = ERROR
            return view

        # a glitching control plane (injected store.write fault, CAS
        # contention burst) must not abort convergence: retry briefly, then
        # leave the old advertisement — the next converge republishes
        from .store import StoreError

        for attempt in range(4):
            try:
                self.store.update(f"/EXTERNALVIEW/{table}", upd)
                return
            except (StoreError, faults.InjectedFault):
                if attempt == 3:
                    log.warning("%s: external-view update for %s kept "
                                "failing; serving stale view until next "
                                "converge", self.instance_id, table,
                                exc_info=True)
                else:
                    time.sleep(0.01 * (attempt + 1))

    # -- query plane --------------------------------------------------------
    def _handle(self, request):
        kind = request.get("type")
        if kind == "query":
            t0 = time.perf_counter()
            try:
                return self._handle_query(request)
            finally:
                # timed here (not in _handle_query) so scheduler waits and
                # injected server.query delays both land in the ring — the
                # health checker must see the latency the broker sees
                self._query_ms.append((time.perf_counter() - t0) * 1000.0)
        if kind == "status":
            return self.health_status()
        if kind == "query_stream":
            return self._handle_query_stream(request)
        if kind == "explain":
            return self._handle_explain(request)
        if kind == "scan_arrow":
            return self._handle_scan_arrow(request)
        if kind == "ping":
            return "pong"
        if kind == "cancel":
            # broker abandon/timeout: flag the tracker so the segment loop's
            # check_cancel stops device work (reference: the /query/{id}
            # DELETE path into the accountant interrupt). A prefix cancel
            # kills every shard of the query (`<query_id>:<n>` ids) and
            # tombstones the prefix so a shard that lost the race to this
            # cancel still dies on arrival.
            reason = request.get("reason", "cancelled by broker")
            qid = request.get("queryId", "")
            if request.get("prefix"):
                return {"cancelled": self.scheduler.accountant.kill_prefix(
                    qid, reason=reason) > 0}
            return {"cancelled": self.scheduler.accountant.kill_query(
                qid, reason=reason)}
        if isinstance(kind, str) and kind.startswith("mse_"):
            return self.mse_worker.handle(request)
        raise ValueError(f"unknown request type {kind}")

    @property
    def mse_worker(self):
        """Multi-stage worker endpoint (mse/distributed.py) — lazily built
        so the MSE runtime only loads when a stage is dispatched here.
        Double-checked under the instance lock: stage dispatch and mailbox
        deliveries arrive CONCURRENTLY (pipelined dispatcher), and an
        unlocked first touch can build two services — the losing request's
        blocks land in an orphaned MailboxStore and the query hangs."""
        worker = getattr(self, "_mse_worker", None)
        if worker is None:
            with self._lock:
                worker = getattr(self, "_mse_worker", None)
                if worker is None:
                    from ..mse.distributed import MseWorkerService

                    worker = MseWorkerService(self)
                    self._mse_worker = worker
        return worker

    def _handle_query(self, request):
        """Execute a QueryContext over an explicit segment list (the broker
        names segments per server, reference InstanceRequest.searchSegments)
        under the scheduler's admission control."""
        table = request["table"]
        names = request["segments"]
        query = request["query"]
        if faults.ACTIVE:
            faults.FAULTS.fire("server.query", table=table,
                               instance=self.instance_id)
        # deadline propagation: the broker stamps its remaining budget on
        # the request; it bounds the scheduler queue wait AND clamps the
        # per-segment loop's timeoutMs (the request is unpickled fresh per
        # RPC, so mutating query_options here is private to this call)
        deadline_ms = request.get("deadlineMs")
        query_id = request.get("queryId")
        t_enter = time.monotonic()
        # cold (metadata-only) routed segments warm BEFORE admission,
        # bounded by the remaining broker budget; un-warmable ones ride the
        # missing-segments machinery (replica retry → degrade) instead of
        # blocking the response
        with self._lock:
            hosted = self.segments.get(table, {})
            cold_routed = [n for n in names if n not in hosted
                           and n in self._cold.get(table, {})]
        still_cold = self._warm_cold_segments(table, cold_routed,
                                              deadline_ms) \
            if cold_routed else []
        timeout_s = 60.0
        if deadline_ms is not None:
            left_ms = max(50.0, float(deadline_ms)
                          - (time.monotonic() - t_enter) * 1000.0)
            timeout_s = max(0.05, min(60.0, left_ms / 1000.0))
            cur = query.query_options.get("timeoutMs")
            query.query_options["timeoutMs"] = (
                left_ms if cur is None else min(float(cur), left_ms))
        with self._lock:
            hosted = self.segments.get(table, {})
            segs = [hosted[n] for n in names if n in hosted]
            missing = [n for n in names if n not in hosted]
            # refcount-pin the tier-local copies for the scan: an eviction
            # racing this query defers its directory removal (and the
            # segment destroy) until the pin releases — no ENOENT mid-scan
            pins = self._tier.pin(table, [n for n in names if n in hosted])

        def run(tracker):
            return self.executor.execute_segments(query, segs, tracker=tracker)

        # trace option: the server owns a trace for its shard of the query
        # (scheduler.submit runs `run` on this thread, so the thread-local
        # trace covers execute_segments and its family dispatches); the span
        # list rides back next to the datatable for the broker to merge
        from ..spi.trace import TRACING, sample_decision, trace_sample_rate

        trace = None
        if TRACING.active_trace() is None:
            if query.query_options.get("trace") in (True, "true", 1):
                # the analyze marker keeps cache tiers live under this trace
                # (EXPLAIN ANALYZE must observe real cache behaviour)
                trace = TRACING.start_trace(
                    f"server:{self.instance_id}",
                    analyze=query.query_options.get("analyze") in
                    (True, "true", 1))
            elif query_id:
                # flight-recorder head sampling: hash the broker queryId
                # PREFIX (each scatter RPC carries ``<query_id>:<n>``) so
                # every shard reaches the broker's own sample decision
                # without an option riding the wire; analyze=True keeps the
                # cache tiers live — a sampled query must behave exactly
                # like its unsampled twin
                root_qid = str(query_id).split(":", 1)[0]
                if sample_decision(root_qid, trace_sample_rate()):
                    trace = TRACING.start_trace(
                        f"server:{self.instance_id}", analyze=True)
        try:
            combined, stats = self.scheduler.submit(
                run, group=table, timeout_s=timeout_s, query_id=query_id)
        finally:
            if trace is not None:
                TRACING.end_trace()
            self._tier.unpin(pins)
        stats["missing_segments"] = missing
        if still_cold:
            # names the broker both counts (coldSegmentsWarming) and may
            # retry against this same instance once the warm completes
            stats["cold_segments"] = [n for n in still_cold if n in missing]
        # intermediates travel as the versioned binary DataTable, not as
        # pickled Python objects (reference: DataTableImplV4 on the wire)
        from .datatable import encode

        blob = encode(combined, stats)
        if faults.ACTIVE:
            # the "datatable.encode" corrupt fault damages the encoded
            # payload — the broker's checksum must catch it downstream
            blob = faults.corrupt_at("datatable.encode", blob, table=table,
                                     instance=self.instance_id)
        out = {"datatable": blob}
        if trace is not None:
            out["trace"] = trace.to_json()
        return out

    def _handle_scan_arrow(self, request):
        """Direct Arrow IPC segment read for external engines — straight
        from segment storage, no SQL/DataTable in the data path
        (reference: the Spark connector's gRPC server reads;
        connectors/arrow_reader.py holds the client half)."""
        from ..connectors.arrow_reader import segment_ipc_bytes

        table = request["table"]
        name = request["segment"]
        with self._lock:
            seg = self.segments.get(table, {}).get(name)
        if seg is None:
            raise ValueError(f"segment {name} not hosted for {table}")
        with self._tier.reading(table, [name]):
            ipc = segment_ipc_bytes(seg, request.get("columns"))
        return {"ipc": ipc, "numRows": seg.num_docs}

    def _handle_explain(self, request):
        """Render the operator-tree plan for this server's hosted segments
        without executing (reference: EXPLAIN runs the plan maker only)."""
        from types import SimpleNamespace

        from ..engine.explain import explain_plan

        table = request["table"]
        names = request["segments"]
        query = request["query"]
        with self._lock:
            hosted = self.segments.get(table, {})
            segs = [hosted[n] for n in names if n in hosted]
        rt = explain_plan(query, SimpleNamespace(segments=segs),
                          self.executor.pruner,
                          backend=self.executor.backend,
                          use_star_tree=self.executor.use_star_tree)
        return {"columns": rt.schema.column_names,
                "types": rt.schema.column_types, "rows": rt.rows}

    def _handle_query_stream(self, request):
        """Server-streaming query: one DataTable chunk per segment as each
        finishes (reference: GrpcQueryServer.submit streaming per-segment
        blocks for streamable operators, GrpcQueryServer.java:65)."""
        from .datatable import encode

        table = request["table"]
        names = request["segments"]
        query = request["query"]
        with self._lock:
            hosted = self.segments.get(table, {})
            segs = [(n, hosted[n]) for n in names if n in hosted]
            missing = [n for n in names if n not in hosted]

        def stream():
            if missing:
                raise RuntimeError(f"missing routed segments: {missing}")
            with self._tier.reading(table, [n for n, _ in segs]):
                for name, seg in segs:
                    combined, stats = self.executor.execute_segments(
                        query, [seg])
                    stats["segment"] = name
                    yield encode(combined, stats)

        return stream()
