"""Property store: hierarchical versioned JSON store with watches.

Reference analogue: ZooKeeper as used by Helix — the property store under
`/PROPERTYSTORE`, ideal states under `/IDEALSTATES`, external views, live
instances (SURVEY.md §2.10 control plane). Single-process implementation
with the same semantics the cluster code needs: compare-and-set versioning,
ephemeral entries tied to a session, and subtree watches delivered
synchronously (tests) or via a notifier thread.

Durability (optional ``data_dir``): ZooKeeper survives process death by
journaling every transaction before acking it; the in-memory default here
vaporizes ideal states, segment DONE records, and lineage epochs on
restart. With a ``data_dir`` the store becomes crash-consistent the same
way: every persistent mutation is appended to ``store.journal`` as a
length+crc32-framed JSON record BEFORE it is applied in memory
(write-ahead ordering), the journal is compacted into an atomically
replaced ``store.snapshot`` past a size threshold, and construction
recovers snapshot+journal, truncating a torn tail at the first bad frame.
CAS versions ride inside the records, so compare-and-set picks up exactly
where it left off across a restart. Ephemeral entries are session-scoped
by definition and are never journaled — a restarted store comes up with
no live instances and no leader, exactly like a fresh ZK session space.

Fsync policy (``PINOT_TPU_STORE_FSYNC`` or the ``fsync`` ctor arg):
``always`` fsyncs after every append (ZK ``forceSync=yes``), ``batch``
flushes per append but fsyncs only on snapshot/close, ``off`` never
fsyncs. Frame format matches PR-8's wire idiom: ``<u32 len><u32 crc32>``
followed by the JSON payload, crc over the payload bytes.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ..spi import faults
from ..spi.metrics import CONTROLLER_METRICS, ControllerGauge, ControllerMeter

# Module-level instrumentation counters (perf-guard pins: an in-memory
# store must never append/fsync; a durable store must not write on reads).
JOURNAL_APPENDS = 0
FSYNC_CALLS = 0

# frame header: payload length, crc32(payload) — little-endian u32 pair
_FRAME = struct.Struct("<II")

_JOURNAL_FILE = "store.journal"
_SNAPSHOT_FILE = "store.snapshot"

_FSYNC_POLICIES = ("always", "batch", "off")
_DEFAULT_SNAPSHOT_BYTES = 1 << 20


class StoreError(Exception):
    pass


class BadVersionError(StoreError):
    """Compare-and-set failed (reference: ZK BadVersionException)."""


@dataclass
class _Entry:
    value: Any
    version: int = 0
    ephemeral_owner: Optional[str] = None


def _frame(payload: bytes) -> bytes:
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


class PropertyStore:
    """Path → JSON-value store. Paths are '/'-separated strings."""

    def __init__(self, data_dir: Optional[str] = None,
                 fsync: Optional[str] = None,
                 snapshot_threshold_bytes: Optional[int] = None):
        self._lock = threading.RLock()
        self._data: dict[str, _Entry] = {}
        self._watches: list[tuple[str, Callable[[str, Optional[Any]], None]]] = []
        # -- durability state --------------------------------------------
        self._data_dir = str(data_dir) if data_dir is not None else None
        self._journal = None
        self._journal_bytes = 0
        self.recoveries = 0
        self.truncations = 0
        self.snapshots = 0
        if self._data_dir is None:
            return
        self._fsync_policy = (fsync or
                              os.environ.get("PINOT_TPU_STORE_FSYNC", "batch"))
        if self._fsync_policy not in _FSYNC_POLICIES:
            raise StoreError(f"bad fsync policy {self._fsync_policy!r} "
                             f"(one of {_FSYNC_POLICIES})")
        if snapshot_threshold_bytes is None:
            snapshot_threshold_bytes = int(os.environ.get(
                "PINOT_TPU_STORE_SNAPSHOT_BYTES", _DEFAULT_SNAPSHOT_BYTES))
        self._snapshot_threshold = snapshot_threshold_bytes
        os.makedirs(self._data_dir, exist_ok=True)
        self._journal_path = os.path.join(self._data_dir, _JOURNAL_FILE)
        self._snapshot_path = os.path.join(self._data_dir, _SNAPSHOT_FILE)
        self._recover()
        self._journal = open(self._journal_path, "ab")
        self._journal_bytes = self._journal.tell()
        CONTROLLER_METRICS.set_gauge(ControllerGauge.STORE_JOURNAL_BYTES,
                                     lambda: float(self._journal_bytes))

    @property
    def durable(self) -> bool:
        return self._journal is not None

    # -- basic ops ---------------------------------------------------------
    def set(self, path: str, value: Any, expected_version: int = -1,
            ephemeral_owner: Optional[str] = None) -> int:
        """Set value; expected_version ≥ 0 makes it a compare-and-set.
        Returns the new version."""
        if faults.ACTIVE:
            faults.FAULTS.fire("store.write", path=path)
        json.dumps(value)  # enforce JSON-serializable (ZK stores bytes)
        with self._lock:
            cur = self._data.get(path)
            if expected_version >= 0:
                curv = cur.version if cur is not None else -1
                if curv != expected_version:
                    raise BadVersionError(
                        f"{path}: expected v{expected_version}, have v{curv}")
            newv = (cur.version + 1) if cur is not None else 0
            if self._journal is not None:
                if ephemeral_owner is None:
                    self._append({"op": "set", "path": path, "value": value,
                                  "version": newv})
                elif cur is not None and cur.ephemeral_owner is None:
                    # persistent entry shadowed by an ephemeral one: the
                    # journal must forget the old persistent value or a
                    # restart would resurrect it past the session death
                    self._append({"op": "delete", "path": path})
            self._data[path] = _Entry(value, newv, ephemeral_owner)
            self._maybe_compact()
        self._notify(path, value)
        return newv

    def create_if_absent(self, path: str, value: Any,
                         ephemeral_owner: Optional[str] = None) -> bool:
        """Atomic exclusive create (ZK create with EPHEMERAL flag): True if
        this call created the entry, False if it already existed."""
        if faults.ACTIVE:
            faults.FAULTS.fire("store.write", path=path)
        json.dumps(value)
        with self._lock:
            if path in self._data:
                return False
            if self._journal is not None and ephemeral_owner is None:
                self._append({"op": "set", "path": path, "value": value,
                              "version": 0})
            self._data[path] = _Entry(value, 0, ephemeral_owner)
            self._maybe_compact()
        self._notify(path, value)
        return True

    def get(self, path: str) -> Optional[Any]:
        with self._lock:
            e = self._data.get(path)
            return None if e is None else e.value

    def get_with_version(self, path: str) -> tuple[Optional[Any], int]:
        with self._lock:
            e = self._data.get(path)
            return (None, -1) if e is None else (e.value, e.version)

    def delete(self, path: str) -> bool:
        with self._lock:
            e = self._data.pop(path, None)
            existed = e is not None
            if existed and self._journal is not None and e.ephemeral_owner is None:
                self._append({"op": "delete", "path": path})
                self._maybe_compact()
        if existed:
            self._notify(path, None)
        return existed

    def delete_if(self, path: str,
                  predicate: Callable[[Any], bool]) -> bool:
        """Atomic conditional delete: remove ``path`` only if it exists and
        ``predicate(value)`` holds, all under one lock (ZK's versioned
        delete). Closes the get→check→delete race in graceful leader
        resignation, where a concurrent expiry + standby claim between the
        get and the delete would delete the NEW leader's entry."""
        if faults.ACTIVE:
            faults.FAULTS.fire("store.write", path=path)
        with self._lock:
            e = self._data.get(path)
            if e is None or not predicate(e.value):
                return False
            del self._data[path]
            if self._journal is not None and e.ephemeral_owner is None:
                self._append({"op": "delete", "path": path})
                self._maybe_compact()
        self._notify(path, None)
        return True

    def children(self, prefix: str) -> list[str]:
        """Direct child names under prefix (ZK getChildren)."""
        prefix = prefix.rstrip("/") + "/"
        with self._lock:
            names = set()
            for p in self._data:
                if p.startswith(prefix):
                    names.add(p[len(prefix):].split("/", 1)[0])
            return sorted(names)

    def list_paths(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(p for p in self._data if p.startswith(prefix))

    # -- ephemerals / sessions ---------------------------------------------
    def expire_session(self, owner: str) -> None:
        """Drop all ephemeral entries owned by a session (instance death).
        Nothing to journal: ephemerals are never persisted."""
        with self._lock:
            dead = [p for p, e in self._data.items() if e.ephemeral_owner == owner]
            for p in dead:
                del self._data[p]
        for p in dead:
            self._notify(p, None)

    # -- watches -----------------------------------------------------------
    def watch(self, prefix: str, callback: Callable[[str, Optional[Any]], None]) -> None:
        """callback(path, new_value_or_None) on every change under prefix.
        Persistent (unlike raw ZK one-shot watches; Helix re-registers —
        this is the post-re-registration behavior)."""
        with self._lock:
            self._watches.append((prefix, callback))

    def unwatch(self, callback: Callable) -> None:
        """Remove every watch registered with this callback. A stopped
        component MUST unregister, or the store pins it (and everything it
        references — loaded segments, sockets) for the store's lifetime:
        a real fd/memory leak under server churn (reference analogue: ZK
        watcher removal on Helix disconnect)."""
        with self._lock:
            # equality, not identity: bound methods are re-created per
            # access, so `is` would never match
            self._watches = [(p, cb) for p, cb in self._watches
                             if cb != callback]

    def _notify(self, path: str, value: Optional[Any]) -> None:
        with self._lock:
            targets = [cb for prefix, cb in self._watches if path.startswith(prefix)]
        for cb in targets:
            cb(path, value)

    # -- transactional helpers ---------------------------------------------
    def update(self, path: str, fn: Callable[[Optional[Any]], Any],
               max_retries: int = 20) -> Any:
        """Read-modify-write with CAS retry (Helix's ZkBaseDataAccessor
        update pattern)."""
        for _ in range(max_retries):
            cur, version = self.get_with_version(path)
            new = fn(json.loads(json.dumps(cur)) if cur is not None else None)
            try:
                self.set(path, new, expected_version=version)
                return new
            except BadVersionError:
                continue
        raise StoreError(f"update contention on {path}")

    # -- durability ---------------------------------------------------------
    def _append(self, record: dict) -> None:
        """Write-ahead append: called under self._lock BEFORE the in-memory
        mutation, so a crash between append and apply leaves a journal that
        is ahead of (never behind) the acked state — replay is idempotent.

        ``store.journal`` fault semantics: an ``error`` spec fires AFTER
        the frame hits the file (crash-after-append-before-notify — the
        caller sees a failure but recovery replays the record); a
        ``corrupt`` spec damages the frame bytes on disk while memory
        proceeds normally (torn write / bitflip — recovery truncates
        there)."""
        global JOURNAL_APPENDS
        payload = json.dumps(record, separators=(",", ":")).encode()
        frame = _frame(payload)
        crash: Optional[BaseException] = None
        if faults.ACTIVE:
            try:
                faults.FAULTS.fire("store.journal", path=record.get("path"))
            except faults.InjectedCorruption as c:
                frame = faults.corrupt_bytes(frame, c.mode, c.seed, c.index)
            except faults.InjectedFault as e:
                crash = e
        self._journal.write(frame)
        self._journal.flush()
        JOURNAL_APPENDS += 1
        self._journal_bytes += len(frame)
        if self._fsync_policy == "always":
            self._do_fsync(self._journal)
        if crash is not None:
            raise crash

    def _maybe_compact(self) -> None:
        """Called under self._lock AFTER the in-memory apply — compacting
        inside _append would snapshot a _data that doesn't yet hold the
        record that crossed the threshold, silently dropping it."""
        if (self._journal is not None
                and self._journal_bytes >= self._snapshot_threshold):
            self._compact()

    @staticmethod
    def _do_fsync(f) -> None:
        global FSYNC_CALLS
        os.fsync(f.fileno())
        FSYNC_CALLS += 1

    def _compact(self) -> None:
        """Snapshot + journal reset (atomic tmp+replace, the
        ``_save_checkpoints`` idiom). Called under self._lock."""
        entries = {p: {"value": e.value, "version": e.version}
                   for p, e in self._data.items()
                   if e.ephemeral_owner is None}
        payload = json.dumps({"entries": entries},
                             separators=(",", ":")).encode()
        tmp = self._snapshot_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(_frame(payload))
            f.flush()
            if self._fsync_policy != "off":
                self._do_fsync(f)
        os.replace(tmp, self._snapshot_path)
        self._journal.close()
        self._journal = open(self._journal_path, "wb")
        self._journal_bytes = 0
        self.snapshots += 1
        CONTROLLER_METRICS.add_meter(ControllerMeter.STORE_SNAPSHOTS)

    def _recover(self) -> None:
        """Load snapshot (strict: snapshot writes are atomic, so a bad one
        is real corruption) then replay the journal, truncating at the
        first bad frame (torn tail from a crash or an injected bitflip)."""
        had_state = False
        if os.path.exists(self._snapshot_path):
            had_state = True
            with open(self._snapshot_path, "rb") as f:
                blob = f.read()
            payload = self._parse_frame(blob, 0)
            if payload is None:
                raise StoreError(
                    f"corrupt snapshot {self._snapshot_path} — snapshot "
                    "writes are atomic; refusing to guess at state")
            for p, rec in json.loads(payload)["entries"].items():
                self._data[p] = _Entry(rec["value"], rec["version"])
        if os.path.exists(self._journal_path):
            with open(self._journal_path, "rb") as f:
                blob = f.read()
            had_state = had_state or bool(blob)
            off = 0
            while off < len(blob):
                payload = self._parse_frame(blob, off)
                if payload is None:
                    # torn tail: keep everything before the bad frame,
                    # drop it and whatever follows
                    with open(self._journal_path, "r+b") as f:
                        f.truncate(off)
                    self.truncations += 1
                    CONTROLLER_METRICS.add_meter(
                        ControllerMeter.STORE_JOURNAL_TRUNCATIONS)
                    break
                rec = json.loads(payload)
                if rec["op"] == "set":
                    self._data[rec["path"]] = _Entry(rec["value"],
                                                     rec["version"])
                elif rec["op"] == "delete":
                    self._data.pop(rec["path"], None)
                off += _FRAME.size + len(payload)
        if had_state:
            self.recoveries += 1
            CONTROLLER_METRICS.add_meter(ControllerMeter.STORE_RECOVERIES)

    @staticmethod
    def _parse_frame(blob: bytes, off: int) -> Optional[bytes]:
        """Payload at ``off`` if header, length, crc, and JSON all check
        out; None for any damage (caller truncates there)."""
        if off + _FRAME.size > len(blob):
            return None
        length, crc = _FRAME.unpack_from(blob, off)
        start = off + _FRAME.size
        payload = blob[start:start + length]
        if len(payload) != length or zlib.crc32(payload) != crc:
            return None
        try:
            json.loads(payload)
        except ValueError:
            return None
        return payload

    def durability_stats(self) -> dict:
        """`GET /debug/store` payload: journal/snapshot/recovery state."""
        with self._lock:
            return {
                "durable": self.durable,
                "dataDir": self._data_dir,
                "fsyncPolicy": getattr(self, "_fsync_policy", None),
                "journalBytes": self._journal_bytes,
                "snapshotCount": self.snapshots,
                "recoveryCount": self.recoveries,
                "journalTruncations": self.truncations,
                "numEntries": len(self._data),
            }

    def close(self) -> None:
        """Flush and release the journal handle (tests reopening the same
        data_dir; harmless on an in-memory store)."""
        with self._lock:
            if self._journal is not None:
                self._journal.flush()
                if self._fsync_policy != "off":
                    self._do_fsync(self._journal)
                self._journal.close()
                self._journal = None
