"""Property store: hierarchical versioned JSON store with watches.

Reference analogue: ZooKeeper as used by Helix — the property store under
`/PROPERTYSTORE`, ideal states under `/IDEALSTATES`, external views, live
instances (SURVEY.md §2.10 control plane). Single-process implementation
with the same semantics the cluster code needs: compare-and-set versioning,
ephemeral entries tied to a session, and subtree watches delivered
synchronously (tests) or via a notifier thread.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ..spi import faults


class StoreError(Exception):
    pass


class BadVersionError(StoreError):
    """Compare-and-set failed (reference: ZK BadVersionException)."""


@dataclass
class _Entry:
    value: Any
    version: int = 0
    ephemeral_owner: Optional[str] = None


class PropertyStore:
    """Path → JSON-value store. Paths are '/'-separated strings."""

    def __init__(self):
        self._lock = threading.RLock()
        self._data: dict[str, _Entry] = {}
        self._watches: list[tuple[str, Callable[[str, Optional[Any]], None]]] = []

    # -- basic ops ---------------------------------------------------------
    def set(self, path: str, value: Any, expected_version: int = -1,
            ephemeral_owner: Optional[str] = None) -> int:
        """Set value; expected_version ≥ 0 makes it a compare-and-set.
        Returns the new version."""
        if faults.ACTIVE:
            faults.FAULTS.fire("store.write", path=path)
        json.dumps(value)  # enforce JSON-serializable (ZK stores bytes)
        with self._lock:
            cur = self._data.get(path)
            if expected_version >= 0:
                curv = cur.version if cur is not None else -1
                if curv != expected_version:
                    raise BadVersionError(
                        f"{path}: expected v{expected_version}, have v{curv}")
            newv = (cur.version + 1) if cur is not None else 0
            self._data[path] = _Entry(value, newv, ephemeral_owner)
        self._notify(path, value)
        return newv

    def create_if_absent(self, path: str, value: Any,
                         ephemeral_owner: Optional[str] = None) -> bool:
        """Atomic exclusive create (ZK create with EPHEMERAL flag): True if
        this call created the entry, False if it already existed."""
        if faults.ACTIVE:
            faults.FAULTS.fire("store.write", path=path)
        json.dumps(value)
        with self._lock:
            if path in self._data:
                return False
            self._data[path] = _Entry(value, 0, ephemeral_owner)
        self._notify(path, value)
        return True

    def get(self, path: str) -> Optional[Any]:
        with self._lock:
            e = self._data.get(path)
            return None if e is None else e.value

    def get_with_version(self, path: str) -> tuple[Optional[Any], int]:
        with self._lock:
            e = self._data.get(path)
            return (None, -1) if e is None else (e.value, e.version)

    def delete(self, path: str) -> bool:
        with self._lock:
            existed = self._data.pop(path, None) is not None
        if existed:
            self._notify(path, None)
        return existed

    def children(self, prefix: str) -> list[str]:
        """Direct child names under prefix (ZK getChildren)."""
        prefix = prefix.rstrip("/") + "/"
        with self._lock:
            names = set()
            for p in self._data:
                if p.startswith(prefix):
                    names.add(p[len(prefix):].split("/", 1)[0])
            return sorted(names)

    def list_paths(self, prefix: str) -> list[str]:
        with self._lock:
            return sorted(p for p in self._data if p.startswith(prefix))

    # -- ephemerals / sessions ---------------------------------------------
    def expire_session(self, owner: str) -> None:
        """Drop all ephemeral entries owned by a session (instance death)."""
        with self._lock:
            dead = [p for p, e in self._data.items() if e.ephemeral_owner == owner]
            for p in dead:
                del self._data[p]
        for p in dead:
            self._notify(p, None)

    # -- watches -----------------------------------------------------------
    def watch(self, prefix: str, callback: Callable[[str, Optional[Any]], None]) -> None:
        """callback(path, new_value_or_None) on every change under prefix.
        Persistent (unlike raw ZK one-shot watches; Helix re-registers —
        this is the post-re-registration behavior)."""
        with self._lock:
            self._watches.append((prefix, callback))

    def unwatch(self, callback: Callable) -> None:
        """Remove every watch registered with this callback. A stopped
        component MUST unregister, or the store pins it (and everything it
        references — loaded segments, sockets) for the store's lifetime:
        a real fd/memory leak under server churn (reference analogue: ZK
        watcher removal on Helix disconnect)."""
        with self._lock:
            # equality, not identity: bound methods are re-created per
            # access, so `is` would never match
            self._watches = [(p, cb) for p, cb in self._watches
                             if cb != callback]

    def _notify(self, path: str, value: Optional[Any]) -> None:
        with self._lock:
            targets = [cb for prefix, cb in self._watches if path.startswith(prefix)]
        for cb in targets:
            cb(path, value)

    # -- transactional helpers ---------------------------------------------
    def update(self, path: str, fn: Callable[[Optional[Any]], Any],
               max_retries: int = 20) -> Any:
        """Read-modify-write with CAS retry (Helix's ZkBaseDataAccessor
        update pattern)."""
        for _ in range(max_retries):
            cur, version = self.get_with_version(path)
            new = fn(json.loads(json.dumps(cur)) if cur is not None else None)
            try:
                self.set(path, new, expected_version=version)
                return new
            except BadVersionError:
                continue
        raise StoreError(f"update contention on {path}")
