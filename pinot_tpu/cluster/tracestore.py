"""Broker-side retained-trace ring (the flight recorder's storage half).

Reference analogue: there is no Pinot equivalent — the closest are the
broker's query log ring and the OpenTelemetry collector's tail-sampling
buffer. Every trace the broker finishes with (head-sampled, explicit
``SET trace``, or EXPLAIN ANALYZE) is offered here; slow, partial, and
failed queries are retained PINNED (tail-based capture: the queries worth
debugging are exactly the ones a probabilistic drop would lose), while
fast healthy samples are best-effort and evict first under the byte
budget.

Entries are keyed by the broker's queryId and served at
``GET /debug/traces`` (summaries) and ``GET /debug/traces/{queryId}``
(full span list, or Chrome Trace Event JSON via ``?format=chrome`` —
spi/traceexport.py). The store is process-local and bounded two ways:
``PINOT_TPU_TRACE_STORE_BYTES`` (default 16 MiB of span JSON) and
``PINOT_TPU_TRACE_STORE_MAX`` entries — eviction drops the oldest
unpinned trace first, then the oldest pinned one, and counts what it
dropped so /metrics can surface retention pressure.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Optional

DEFAULT_BUDGET_BYTES = int(os.environ.get(
    "PINOT_TPU_TRACE_STORE_BYTES", 16 << 20))
DEFAULT_MAX_TRACES = int(os.environ.get(
    "PINOT_TPU_TRACE_STORE_MAX", 256))


class TraceStore:
    """Byte-budgeted, pin-aware ring of retained traces."""

    def __init__(self, budget_bytes: Optional[int] = None,
                 max_traces: Optional[int] = None):
        self.budget_bytes = DEFAULT_BUDGET_BYTES if budget_bytes is None \
            else int(budget_bytes)
        self.max_traces = DEFAULT_MAX_TRACES if max_traces is None \
            else int(max_traces)
        # queryId → entry dict; insertion order is arrival order (the
        # eviction scan walks oldest-first within each pin class)
        self._traces: "OrderedDict[str, dict]" = OrderedDict()
        self._bytes = 0
        self.evictions = 0  # lifetime drops (budget/count pressure)
        self._lock = threading.Lock()

    def offer(self, query_id: str, spans: list, *, reason: str = "sampled",
              pinned: bool = False, table: str = "", time_ms: float = 0.0,
              exceptions: int = 0, partial: bool = False,
              alert_id: str = "") -> str:
        """Retain one finished trace. ``pinned`` marks tail-captured
        traces (slow/partial/failed) that outlive budget pressure from
        healthy samples. ``alert_id`` tags sentinel-pinned exemplars
        (engine/perf_ledger.py) so the alert record and the trace link
        both ways. Returns the retained trace id (the queryId).
        A re-offer under the same id replaces the old entry (hedged
        EXPLAIN reruns of one id keep the latest)."""
        # sizing by serialized span JSON: that is exactly what the debug
        # endpoint ships, and it is only computed on RETAINED traces —
        # untraced queries never reach this method
        try:
            nbytes = len(json.dumps(spans))
        except (TypeError, ValueError):
            spans = [{"operator": "unserializable-trace"}]
            nbytes = 64
        entry = {
            "queryId": query_id,
            "reason": reason,
            "pinned": bool(pinned),
            "table": table,
            "timeMs": round(float(time_ms), 3),
            "exceptions": int(exceptions),
            "partialResult": bool(partial),
            "numSpans": len(spans),
            "bytes": nbytes,
            "timestamp": round(time.time(), 3),
            "spans": spans,
        }
        if alert_id:
            entry["alertIds"] = [alert_id]
        with self._lock:
            old = self._traces.pop(query_id, None)
            if old is not None:
                self._bytes -= old["bytes"]
            self._traces[query_id] = entry
            self._bytes += nbytes
            self._evict_locked()
        return query_id

    def _evict_locked(self) -> None:
        def over() -> bool:
            return self._bytes > self.budget_bytes \
                or len(self._traces) > self.max_traces
        if not over():
            return
        # unpinned (healthy samples) go first, oldest-first; pinned
        # (slow/partial/failed) only when samples alone can't fit the
        # budget — but the just-offered newest entry always survives
        for pin_class in (False, True):
            for qid in list(self._traces):
                if not over():
                    return
                ent = self._traces[qid]
                if ent["pinned"] is not pin_class:
                    continue
                if qid == next(reversed(self._traces)):
                    continue  # never evict the entry being offered
                self._bytes -= ent["bytes"]
                del self._traces[qid]
                self.evictions += 1

    def get(self, query_id: str) -> Optional[dict]:
        with self._lock:
            ent = self._traces.get(query_id)
            return dict(ent) if ent is not None else None

    def summaries(self) -> list:
        """Newest-first listing without the span payloads."""
        with self._lock:
            out = [{k: v for k, v in ent.items() if k != "spans"}
                   for ent in self._traces.values()]
        out.reverse()
        return out

    def stats(self) -> dict:
        with self._lock:
            pinned = sum(1 for e in self._traces.values() if e["pinned"])
            exemplars = sum(1 for e in self._traces.values()
                            if e.get("alertIds"))
            return {"traces": len(self._traces),
                    "pinnedTraces": pinned,
                    "alertExemplars": exemplars,
                    "bytes": self._bytes,
                    "budgetBytes": self.budget_bytes,
                    "maxTraces": self.max_traces,
                    "evictions": self.evictions}

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()
            self._bytes = 0
