"""Data-plane transport: broker↔server query RPC over TCP.

Reference analogue: the Netty data plane (pinot-core/.../transport/ —
QueryRouter.submitQuery:90 → ServerChannels → server QueryServer +
InstanceRequestHandler.channelRead0:122 deserializing Thrift
InstanceRequest). Here: length-prefixed pickled frames over TCP sockets
with a thread-per-connection server — the host-side scatter/gather plane.
Device-side data never crosses this wire; servers ship per-table combined
intermediates (the DataTable analogue), brokers merge and reduce.

Pickle is acceptable where Thrift serves in the reference because both ends
are this same trusted process group (in-proc cluster / localhost tests);
the framing keeps the transport swappable for a real codec later.
"""

from __future__ import annotations

import os
import pickle
import socket
import ssl
import struct
import threading
from typing import Callable, Optional

from ..spi import faults

_HDR = struct.Struct(">I")
_MAX_FRAME = 1 << 30


def make_server_ssl_context(certfile: str, keyfile: str) -> ssl.SSLContext:
    """TLS for the data plane (reference: Netty channel TLS,
    pinot-core/.../transport/ChannelHandlerFactory with TlsConfig)."""
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    ctx.load_cert_chain(certfile, keyfile)
    return ctx


def make_client_ssl_context(cafile: Optional[str] = None,
                            verify: bool = True) -> ssl.SSLContext:
    ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
    if cafile:
        ctx.load_verify_locations(cafile)
    else:
        # PROTOCOL_TLS_CLIENT starts with zero trust anchors (unlike
        # create_default_context) — CA-signed server certs need system CAs
        ctx.load_default_certs()
    if not verify:  # self-signed dev certs (reference tls "skip server" mode)
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx


class TransportError(Exception):
    """Connection-level failure — the peer is unreachable or hung up."""


class RemoteError(Exception):
    """The peer was reached and its handler raised — a per-request error,
    NOT a server-health signal (the broker must not mark the instance
    unhealthy or fail over; reference: QueryException in the DataTable vs a
    Netty channel error)."""


def _send_frame(sock: socket.socket, obj) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("connection closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket):
    (n,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
    if n > _MAX_FRAME:
        raise TransportError(f"frame too large: {n}")
    return pickle.loads(_recv_exact(sock, n))


def _corrupt_payload(payload, c):
    """Apply an injected wire corruption to a decoded response: garble its
    byte fields (integrity checksums downstream must catch it — e.g. the
    DataTable trailer at the broker). Responses with no byte fields degrade
    to a garbled-frame TransportError so the fault is never a silent no-op."""
    if isinstance(payload, (bytes, bytearray)):
        return faults.corrupt_bytes(bytes(payload), c.mode, c.seed, c.index)
    if isinstance(payload, dict):
        hit = False
        out = dict(payload)
        for k, v in payload.items():
            if isinstance(v, (bytes, bytearray)):
                out[k] = faults.corrupt_bytes(bytes(v), c.mode, c.seed,
                                              c.index)
                hit = True
        if hit:
            return out
    raise TransportError(f"garbled response frame: {c}")


class RpcServer:
    """Thread-per-connection request/response server.
    handler(request_obj) → response_obj. Bind to port 0 for an ephemeral
    port; .port reports the bound port."""

    def __init__(self, handler: Callable, host: str = "127.0.0.1", port: int = 0,
                 ssl_context: Optional[ssl.SSLContext] = None,
                 max_inflight_bytes: Optional[int] = None,
                 handshake_timeout_s: Optional[float] = None):
        self.handler = handler
        self._ssl = ssl_context
        # TLS-handshake ceiling: constructor arg wins, then the
        # PINOT_TPU_RPC_HANDSHAKE_S env knob, then the historical 10s
        if handshake_timeout_s is None:
            handshake_timeout_s = float(
                os.environ.get("PINOT_TPU_RPC_HANDSHAKE_S", 10.0))
        self._handshake_s = handshake_timeout_s
        # request-memory guard (reference: DirectOOMHandler — shed load
        # instead of dying when request buffers exceed the direct-memory
        # budget): frames beyond the budget are drained and refused
        self._budget = max_inflight_bytes
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.host = host
        self.port = self._sock.getsockname()[1]
        self._closed = threading.Event()
        self._conns: set = set()  # live per-connection sockets
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"rpc-accept-{self.port}", daemon=True)
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._closed.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn: socket.socket) -> Optional[socket.socket]:
        """TLS handshake off the accept loop (a stalled ClientHello must
        not block other connections) and under a timeout."""
        if self._ssl is None:
            return conn
        conn.settimeout(self._handshake_s)
        try:
            conn = self._ssl.wrap_socket(conn, server_side=True)
            conn.settimeout(None)
            return conn
        except (ssl.SSLError, OSError):
            try:
                conn.close()
            except OSError:
                pass
            return None

    def _reserve(self, n: int) -> bool:
        if self._budget is None:
            return True
        with self._inflight_lock:
            if self._inflight + n > self._budget:
                return False
            self._inflight += n
            return True

    def _release(self, n: int) -> None:
        if self._budget is not None:
            with self._inflight_lock:
                self._inflight -= n

    def _serve_conn(self, conn: socket.socket) -> None:
        handshaken = self._handshake(conn)
        if handshaken is None:
            return
        conn = handshaken
        with self._conns_lock:
            if self._closed.is_set():
                conn.close()
                return
            self._conns.add(conn)
        try:
            self._serve_conn_loop(conn)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _serve_conn_loop(self, conn: socket.socket) -> None:
        import types

        with conn:
            while not self._closed.is_set():
                try:
                    (n,) = _HDR.unpack(_recv_exact(conn, _HDR.size))
                    if n > _MAX_FRAME:
                        return
                    if not self._reserve(n):
                        # drain through a fixed scratch to keep the stream
                        # in sync WITHOUT buffering the frame (the guard
                        # must not itself allocate what it refuses)
                        left = n
                        while left:
                            chunk = conn.recv(min(left, 1 << 16))
                            if not chunk:
                                return
                            left -= len(chunk)
                        try:
                            _send_frame(conn, (
                                "error", "ServerOutOfMemory: request "
                                "buffers exceed the transport memory budget"))
                        except OSError:
                            return
                        continue
                    try:
                        request = pickle.loads(_recv_exact(conn, n))
                    finally:
                        self._release(n)
                except (TransportError, OSError, EOFError):
                    return
                try:
                    result = self.handler(request)
                    if isinstance(result, types.GeneratorType):
                        # streaming response: one ("chunk", x) frame per
                        # yielded item, then ("ok", None) — the gRPC
                        # server-streaming analogue over the framed plane
                        try:
                            for chunk in result:
                                _send_frame(conn, ("chunk", chunk))
                            response = ("ok", None)
                        except Exception as e:  # mid-stream failure
                            response = ("error", f"{type(e).__name__}: {e}")
                    else:
                        response = ("ok", result)
                except Exception as e:  # surface handler errors to the caller
                    response = ("error", f"{type(e).__name__}: {e}")
                try:
                    _send_frame(conn, response)
                except OSError:
                    return

    def close(self) -> None:
        self._closed.set()
        try:
            # close() alone does NOT wake a thread blocked in accept() on
            # Linux — shutdown() does (EINVAL), so the accept thread can
            # actually exit and release its reference to the handler
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        # established connections must die too: a per-connection thread
        # blocked in recv() on a still-open socket pins self.handler (and
        # through the bound method, the whole owning server instance)
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # join the accept thread: while alive it pins self.handler
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=2.0)


class RpcClient:
    """Pooled connections per target with reconnect-on-failure.

    Up to ``pool_size`` sockets (``PINOT_TPU_RPC_POOL``, default 8) may
    carry in-flight calls to one target concurrently. A single pooled
    socket would serialize concurrent queries from different broker
    threads on the wire — the server would only ever see one query at a
    time, so cross-query coalescing could never form a group."""

    def __init__(self, host: str, port: int, timeout: float = 30.0,
                 ssl_context: Optional[ssl.SSLContext] = None,
                 connect_timeout: Optional[float] = None,
                 pool_size: Optional[int] = None):
        self.host = host
        self.port = port
        self.timeout = timeout
        # connect timeout decoupled from the request (recv) timeout:
        # constructor arg, then PINOT_TPU_RPC_CONNECT_S, then ``timeout``
        if connect_timeout is None:
            env = os.environ.get("PINOT_TPU_RPC_CONNECT_S")
            connect_timeout = float(env) if env else timeout
        self.connect_timeout = connect_timeout
        if pool_size is None:
            pool_size = int(os.environ.get("PINOT_TPU_RPC_POOL", 8))
        self.pool_size = max(1, pool_size)
        self._ssl = ssl_context
        self._free: list = []  # idle sockets, checkout/checkin under _lock
        self._lock = threading.Lock()
        # caps concurrent in-flight calls at pool_size; excess callers
        # queue here instead of growing the socket count without bound
        self._sem = threading.BoundedSemaphore(self.pool_size)
        # close() bumps the generation: sockets checked out under an
        # older generation are closed on checkin instead of re-pooled
        self._gen = 0

    def _connect(self) -> socket.socket:
        s = socket.create_connection((self.host, self.port),
                                     timeout=self.connect_timeout)
        # create_connection's timeout persists on the socket (it would be
        # the recv timeout too) — restore the request timeout explicitly
        s.settimeout(self.timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._ssl is not None:
            s = self._ssl.wrap_socket(s, server_hostname=self.host)
        return s

    def _fire_fault(self, point: str):
        """Injection seam: an InjectedDrop kills the pooled socket (the
        peer 'hung up'); an InjectedCorruption is RETURNED — the RPC itself
        proceeds and the caller garbles the response payload, so integrity
        checksums (not connection errors) must catch it; any other injected
        fault surfaces as TransportError — the connection-level failure
        shape, so callers exercise their real failover/retry paths."""
        try:
            faults.FAULTS.fire(point, host=self.host, port=self.port)
        except faults.InjectedCorruption as c:
            return c
        except faults.InjectedDrop as e:
            self.close()
            raise TransportError(
                f"rpc to {self.host}:{self.port} failed: {e}") from None
        except faults.InjectedFault as e:
            raise TransportError(
                f"rpc to {self.host}:{self.port} failed: {e}") from None
        return None

    def call(self, request, retry: bool = True,
             timeout: Optional[float] = None):
        """``retry`` re-sends once on a connection failure (the pooled
        connection may have gone stale between calls). Callers whose
        requests are NOT idempotent — e.g. an mse_stage dispatch, where a
        re-run would consume mailboxes twice — pass retry=False; mailbox
        block deliveries stay retryable because the receiver dedups on
        (sender, seq). ``timeout`` bounds THIS call only (deadline
        propagation: the broker passes its remaining budget) by temporarily
        tightening the socket timeout.

        An armed ``corrupt`` fault on transport.call lets the RPC complete
        and then garbles the response's byte fields — models in-flight wire
        corruption below the app layer; the DataTable checksum at the
        broker must catch it."""
        corruption = None
        if faults.ACTIVE:
            corruption = self._fire_fault("transport.call")
        attempts = (0, 1) if retry else (1,)
        self._sem.acquire()
        try:
            sock = gen = None
            for attempt in attempts:
                try:
                    if sock is None:
                        if attempt == 0:
                            sock, gen = self._checkout()
                        else:
                            # the pooled socket just failed — every idle
                            # peer from the same era is suspect (server
                            # restart), so retry on a FRESH connection
                            with self._lock:
                                gen = self._gen
                            sock = self._connect()
                    if timeout is not None:
                        sock.settimeout(timeout)
                    try:
                        _send_frame(sock, request)
                        status, payload = _recv_frame(sock)
                    finally:
                        if timeout is not None:
                            try:
                                sock.settimeout(self.timeout)
                            except OSError:
                                pass
                    self._checkin(sock, gen)
                    break
                except (TransportError, OSError, EOFError):
                    if sock is not None:
                        try:
                            sock.close()
                        except OSError:
                            pass
                        sock = None
                    self._flush_free()
                    if attempt == 1:
                        raise TransportError(
                            f"rpc to {self.host}:{self.port} failed")
        finally:
            self._sem.release()
        if status == "error":
            raise RemoteError(payload)
        if corruption is not None:
            payload = _corrupt_payload(payload, corruption)
        return payload

    def call_stream(self, request):
        """Generator over a streaming response: yields each chunk; raises
        RemoteError on a server-side failure (also mid-stream). Uses a
        DEDICATED connection (not the pooled one) so an abandoned or
        long-lived stream never blocks concurrent unary calls — the
        per-stream-channel behavior of the gRPC analogue."""
        if faults.ACTIVE:
            c = self._fire_fault("transport.stream")
            if c is not None:
                # streams have no payload-level checksum yet — degrade a
                # corrupt fault to the connection-failure shape
                raise TransportError(
                    f"stream from {self.host}:{self.port} garbled: {c}")
        try:
            sock = self._connect()
        except OSError:
            raise TransportError(
                f"rpc to {self.host}:{self.port} failed") from None
        try:
            _send_frame(sock, request)
            while True:
                try:
                    status, payload = _recv_frame(sock)
                except (TransportError, OSError, EOFError):
                    raise TransportError(
                        f"stream from {self.host}:{self.port} broke") from None
                if status == "chunk":
                    yield payload
                elif status == "ok":
                    return
                else:
                    raise RemoteError(payload)
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _checkout(self):
        """Pop an idle socket (or dial a fresh one) plus the generation
        it belongs to. May raise OSError from connect."""
        with self._lock:
            if self._free:
                return self._free.pop(), self._gen
            gen = self._gen
        return self._connect(), gen

    def _checkin(self, sock: socket.socket, gen) -> None:
        with self._lock:
            if gen == self._gen and len(self._free) < self.pool_size:
                self._free.append(sock)
                return
        try:
            sock.close()
        except OSError:
            pass

    def _flush_free(self) -> None:
        with self._lock:
            free, self._free = self._free, []
        for s in free:
            try:
                s.close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            self._gen += 1
            free, self._free = self._free, []
        for s in free:
            try:
                s.close()
            except OSError:
                pass
