"""Per-query cost accounting and decaying workload rollups.

Reference analogue: the broker's query-cost attribution in
QueryLogger/BrokerQueryEventListener plus the controller recommender's
queryStats input — here folded into one place: every completed query is
reduced to a ``QueryCostReport`` (device/host/transfer/shuffle cost,
cache behaviour, healing effort) attributed to its table and client id,
and accumulated into exponentially-decaying per-table rollups served by
the broker's ``GET /debug/workload``.

Two consumers read the rollups instead of raw query counts:

- the admission controller (cluster/quota.py): a saturated broker can
  shed *expensive* queries first — ``expected_cost_ms`` supplies the
  decayed mean cost for the query's table as the admission cost hint;
- the config recommender (cluster/recommender.py): ``recommender_input``
  emits the exact ``{queries: [{sql, freq}], qps}`` body shape that
  ``POST /recommender`` accepts, built from observed traffic rather than
  a hand-written sample.

Cost extraction never arms tracing: untraced queries contribute the
response-level counters (wall ms, docs, dispatches, cache hits,
retries/hedges, MSE shuffle bytes); phase-level device/combine times and
HBM/cache byte attribution ride along only when the query ran traced
(EXPLAIN ANALYZE or ``SET trace = true``). The fold is plain dict
arithmetic on the broker's return path — zero device syncs, zero span
allocations (pinned by tests/test_tracing_perf_guard.py).
"""

from __future__ import annotations

import math
import os
import re
import threading
import time
from collections import deque
from typing import Optional

# decayed-sum half life for the per-table rollups: ~5 minutes means a
# burst stops dominating the admission cost hint within a few half-lives
DEFAULT_HALF_LIFE_S = float(os.environ.get(
    "PINOT_TPU_WORKLOAD_HALF_LIFE_S", 300.0))

# distinct SQL patterns sampled per table for the recommender feed
MAX_PATTERNS_PER_TABLE = 64

# accumulated (decaying) numeric fields of a rollup; every one is also a
# QueryCostReport key
_SUM_FIELDS = (
    "queries", "failures", "rejected", "tracedQueries",
    "timeMs", "deviceMs", "compileMs", "hostCombineMs",
    "transferBytes", "hbmBytesTouched", "shuffledBytes", "cacheHitBytes",
    "docsScanned", "deviceDispatches", "compiles",
    "segmentCacheHits", "segmentCacheMisses",
    "resultCacheHits", "scatterRetries", "hedgedRequests",
)

_CLIENT_ID_RE = re.compile(r"(?i)\bset\s+clientid\s*=\s*'?([\w.@-]+)'?")


def client_id_of(sql: str) -> str:
    """Client attribution from the query's own ``SET clientId = x`` option
    (the parsers treat unknown SET options as passthrough query options;
    this extracts it without re-parsing on the hot path)."""
    m = _CLIENT_ID_RE.search(sql)
    return m.group(1) if m else ""


def build_cost_report(resp, table: str = "", client_id: str = "",
                      sql: str = "") -> dict:
    """Fold one completed query's response (and its trace, when present)
    into a flat cost report. Every numeric key is decayed-summable."""
    trace_info = getattr(resp, "trace_info", None)
    device_ms = compile_ms = combine_ms = 0.0
    transfer = shuffled = hbm_touched = cache_hit_bytes = 0
    if trace_info:
        from ..spi.trace import phase_breakdown

        phases = phase_breakdown(trace_info)
        device_ms = phases["deviceExecMs"]
        compile_ms = phases["compileMs"]
        combine_ms = phases["hostCombineMs"]
        transfer = phases["transferBytes"]
        shuffled = phases.get("shuffledBytes", 0)
        for span in trace_info:
            attrs = span.get("attributes") or {}
            hbm_touched = max(hbm_touched,
                              int(attrs.get("hbmBytesUsed", 0) or 0))
            cache_hit_bytes += int(attrs.get("cacheHitBytes", 0) or 0)
    mss = getattr(resp, "mse_stage_stats", None)
    if mss:
        # MSE stage stats carry shuffle volume even untraced
        shuffled = max(shuffled, sum(
            int((s or {}).get("shuffled_bytes", 0) or 0)
            for s in mss.values()))
    return {
        "table": table,
        "clientId": client_id,
        "queries": 1,
        "failures": 1 if getattr(resp, "exceptions", None) else 0,
        "rejected": 1 if getattr(resp, "query_rejected", False) else 0,
        "tracedQueries": 1 if trace_info else 0,
        "timeMs": round(float(getattr(resp, "time_used_ms", 0.0) or 0.0), 3),
        "deviceMs": device_ms,
        "compileMs": compile_ms,
        "hostCombineMs": combine_ms,
        "transferBytes": transfer,
        "hbmBytesTouched": hbm_touched,
        "shuffledBytes": shuffled,
        "cacheHitBytes": cache_hit_bytes,
        "docsScanned": int(getattr(resp, "num_docs_scanned", 0) or 0),
        "deviceDispatches": int(
            getattr(resp, "num_device_dispatches", 0) or 0),
        "compiles": int(getattr(resp, "num_compiles", 0) or 0),
        "segmentCacheHits": int(
            getattr(resp, "num_segments_cache_hit", 0) or 0),
        "segmentCacheMisses": int(
            getattr(resp, "num_segments_cache_miss", 0) or 0),
        "resultCacheHits":
            1 if getattr(resp, "cache_outcome", None) == "hit" else 0,
        "scatterRetries": int(getattr(resp, "num_scatter_retries", 0) or 0),
        "hedgedRequests": int(getattr(resp, "num_hedged_requests", 0) or 0),
        "sql": sql,
    }


class _Rollup:
    """Exponentially-decaying sums: every fold first decays the stored
    values by 2^(-dt/half_life), so 'recent' traffic dominates and an idle
    table's cost signal fades to zero instead of pinning forever."""

    __slots__ = ("sums", "patterns", "last_ts", "half_life_s")

    def __init__(self, half_life_s: float, now: float):
        self.sums = {k: 0.0 for k in _SUM_FIELDS}
        # canonical sql → decayed frequency weight (recommender feed)
        self.patterns: dict[str, float] = {}
        self.last_ts = now
        self.half_life_s = half_life_s

    def _decay(self, now: float) -> None:
        dt = now - self.last_ts
        if dt <= 0:
            return
        f = math.pow(2.0, -dt / self.half_life_s)
        for k in self.sums:
            self.sums[k] *= f
        for k in list(self.patterns):
            w = self.patterns[k] * f
            if w < 1e-3:
                del self.patterns[k]
            else:
                self.patterns[k] = w
        self.last_ts = now

    def fold(self, report: dict, now: float) -> None:
        self._decay(now)
        for k in _SUM_FIELDS:
            self.sums[k] += float(report.get(k, 0) or 0)
        sql = report.get("sql") or ""
        if sql:
            if sql not in self.patterns \
                    and len(self.patterns) >= MAX_PATTERNS_PER_TABLE:
                # evict the faintest pattern; the sample stays bounded
                del self.patterns[min(self.patterns,
                                      key=self.patterns.get)]
            self.patterns[sql] = self.patterns.get(sql, 0.0) + 1.0

    def snapshot(self, now: float) -> dict:
        self._decay(now)
        out = {k: (round(v, 3) if isinstance(v, float) else v)
               for k, v in self.sums.items()}
        q = self.sums["queries"]
        out["meanTimeMs"] = round(self.sums["timeMs"] / q, 3) if q else 0.0
        out["cacheHitRate"] = round(
            self.sums["segmentCacheHits"]
            / (self.sums["segmentCacheHits"]
               + self.sums["segmentCacheMisses"]), 4) \
            if (self.sums["segmentCacheHits"]
                + self.sums["segmentCacheMisses"]) else None
        # decayed count / half-life ≈ recent arrival rate
        out["decayedQps"] = round(q * math.log(2) / self.half_life_s, 4)
        return out


class WorkloadTracker:
    """Broker-side cost accountant: per-table and per-client decaying
    rollups plus a bounded ring of the most recent raw cost reports."""

    def __init__(self, half_life_s: Optional[float] = None,
                 recent_reports: int = 64):
        self.half_life_s = DEFAULT_HALF_LIFE_S if half_life_s is None \
            else float(half_life_s)
        self._lock = threading.Lock()
        self._tables: dict[str, _Rollup] = {}
        self._clients: dict[str, _Rollup] = {}
        self._recent: deque = deque(maxlen=recent_reports)

    def note_response(self, sql: str, resp, table: str = "") -> dict:
        """Fold one completed query; returns its cost report."""
        report = build_cost_report(resp, table=table,
                                   client_id=client_id_of(sql), sql=sql)
        now = time.monotonic()
        with self._lock:
            key = table or "(none)"
            roll = self._tables.get(key)
            if roll is None:
                roll = self._tables[key] = _Rollup(self.half_life_s, now)
            roll.fold(report, now)
            cid = report["clientId"]
            if cid:
                croll = self._clients.get(cid)
                if croll is None:
                    croll = self._clients[cid] = _Rollup(
                        self.half_life_s, now)
                croll.fold(dict(report, sql=""), now)
            self._recent.append(
                dict(report, sql=report["sql"][:200],
                     timestamp=round(time.time(), 3)))
        return report

    def expected_cost_ms(self, table: str) -> float:
        """Decayed mean wall-time of the table's recent queries — the
        admission controller's heavy-query cost hint."""
        with self._lock:
            roll = self._tables.get(table or "(none)")
            if roll is None:
                return 0.0
            roll._decay(time.monotonic())
            q = roll.sums["queries"]
            return roll.sums["timeMs"] / q if q else 0.0

    def table_costs(self) -> dict[str, float]:
        """Every tracked table's decayed mean wall-time (ms). Published in
        the broker's /BROKERSTATE beacon so the controller's rebalancer can
        weight hot tables when ordering segment moves."""
        with self._lock:
            now = time.monotonic()
            out = {}
            for table, roll in self._tables.items():
                if table == "(none)":
                    continue
                roll._decay(now)
                q = roll.sums["queries"]
                if q:
                    out[table] = round(roll.sums["timeMs"] / q, 3)
            return out

    def recommender_input(self, table: str) -> Optional[dict]:
        """Observed traffic in the exact body shape ``POST /recommender``
        accepts: {queries: [{sql, freq}], qps}."""
        with self._lock:
            roll = self._tables.get(table)
            if roll is None:
                return None
            now = time.monotonic()
            roll._decay(now)
            total = sum(roll.patterns.values()) or 1.0
            return {
                "queries": [{"sql": s, "freq": round(w / total, 4)}
                            for s, w in sorted(roll.patterns.items(),
                                               key=lambda kv: -kv[1])],
                "qps": round(roll.sums["queries"] * math.log(2)
                             / self.half_life_s, 4),
            }

    def snapshot(self) -> dict:
        """The GET /debug/workload payload."""
        now = time.monotonic()
        with self._lock:
            return {
                "halfLifeS": self.half_life_s,
                "tables": {t: r.snapshot(now)
                           for t, r in self._tables.items()},
                "clients": {c: r.snapshot(now)
                            for c, r in self._clients.items()},
                "recentQueries": list(self._recent),
                "recommenderInput": {
                    t: inp for t in list(self._tables)
                    if (inp := self._recommender_input_locked(t, now))},
            }

    def _recommender_input_locked(self, table: str, now: float):
        roll = self._tables.get(table)
        if roll is None or not roll.patterns:
            return None
        total = sum(roll.patterns.values()) or 1.0
        return {"queries": [{"sql": s, "freq": round(w / total, 4)}
                            for s, w in sorted(roll.patterns.items(),
                                               key=lambda kv: -kv[1])],
                "qps": round(roll.sums["queries"] * math.log(2)
                             / self.half_life_s, 4)}
