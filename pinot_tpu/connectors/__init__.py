"""External-system connectors (reference: pinot-connectors/).

The reference ships Spark/Flink connectors — bulk read (parallel scans of
the query engine) and bulk write (build + push segments from a dataframe).
In the Python ecosystem the equivalent surfaces are pandas/pyarrow:
connectors/dataframe.py provides both directions.
"""

from .arrow_reader import (  # noqa: F401
    ScanSplit,
    plan_scan,
    read_split,
    read_table,
)
from .dataframe import (  # noqa: F401
    infer_schema,
    read_sql,
    read_sql_pandas,
    write_dataframe,
)
from .sink import StreamingSegmentWriter  # noqa: F401
