"""Arrow IPC bulk-read path for external engines.

Reference analogue: pinot-connectors/pinot-spark-3-connector — Spark's
DataSource v2 plans one InputPartition per Pinot segment and each
partition reader pulls that segment's rows from the hosting server over
gRPC, bypassing SQL fan-out. Here the same contract is Arrow-native:

  plan_scan(broker, table)        → splits (segment + hosting servers)
  read_split(split, ...)          → one pyarrow.RecordBatch, fetched
                                    DIRECTLY from a hosting server over
                                    the framed-TCP RPC plane ("scan_arrow"
                                    request, Arrow IPC stream bytes back)
  read_table(broker, table, ...)  → partition-parallel whole-table read

Servers serialize straight from segment storage (dictionary decode /
raw planes / MV lists) — no SQL, no DataTable, no broker in the data
path. Failover: each split carries every replica's address and the reader
tries them in order.
"""

from __future__ import annotations

import io
from dataclasses import dataclass
from typing import Optional

import numpy as np


# ---------------------------------------------------------------------------
# Server side: segment → Arrow IPC bytes
# ---------------------------------------------------------------------------


def segment_record_batch(segment, columns: Optional[list[str]] = None):
    """Materialize segment columns as one pyarrow.RecordBatch. SV columns
    decode through the dictionary (or raw plane); MV columns become Arrow
    list arrays; null bitmaps become Arrow validity."""
    import pyarrow as pa

    cols = columns or segment.columns()
    arrays, names = [], []
    for c in cols:
        if not segment.has_column(c):
            raise ValueError(f"unknown column {c}")
        m = segment.column_metadata(c)
        nulls = segment.get_null_bitmap(c)
        if m.single_value:
            vals = segment.get_values(c)
            if vals.dtype == object:
                arr = pa.array(vals.tolist(),
                               mask=nulls if nulls is not None else None)
            else:
                arr = pa.array(vals, mask=nulls if nulls is not None else None)
        else:
            rows = segment.get_mv_values(c)
            arr = pa.array([list(map(_py, r)) for r in rows])
        arrays.append(arr)
        names.append(c)
    return pa.RecordBatch.from_arrays(arrays, names=names)


def _py(v):
    return v.item() if isinstance(v, np.generic) else v


def segment_ipc_bytes(segment, columns: Optional[list[str]] = None) -> bytes:
    import pyarrow as pa

    batch = segment_record_batch(segment, columns)
    buf = io.BytesIO()
    with pa.ipc.new_stream(buf, batch.schema) as w:
        w.write_batch(batch)
    return buf.getvalue()


# ---------------------------------------------------------------------------
# Client side: plan + parallel read
# ---------------------------------------------------------------------------


@dataclass
class ScanSplit:
    """One unit of partition-parallel work: a segment plus every replica
    address able to serve it (reference: PinotInputPartition)."""

    table: str
    segment: str
    addresses: list[tuple[str, int]]  # (host, port) per hosting server


def plan_scan(broker, table: str) -> list[ScanSplit]:
    """Splits for a partition-parallel read (reference:
    planInputPartitions). ``table`` is the name with type suffix
    (e.g. "t_OFFLINE")."""
    routing = broker.routing_table(table)
    store = broker.store
    addr_cache: dict[str, tuple[str, int]] = {}

    def addr(inst: str):
        if inst not in addr_cache:
            cfg = store.get(f"/LIVEINSTANCES/{inst}") or \
                store.get(f"/INSTANCECONFIGS/{inst}")
            if cfg is None:
                return None
            addr_cache[inst] = (cfg["host"], cfg["port"])
        return addr_cache[inst]

    splits = []
    for seg in sorted(routing):
        addresses = [a for a in (addr(i) for i in routing[seg])
                     if a is not None]
        if not addresses:
            raise RuntimeError(f"segment {seg} has no online replica")
        splits.append(ScanSplit(table, seg, addresses))
    return splits


def read_split(split: ScanSplit, columns: Optional[list[str]] = None):
    """Fetch one split as a pyarrow.RecordBatch, failing over across the
    split's replicas."""
    import pyarrow as pa

    from ..cluster.transport import RemoteError, RpcClient, TransportError

    last: Exception | None = None
    for host, port in split.addresses:
        try:
            client = RpcClient(host, port)
            try:
                out = client.call({"type": "scan_arrow", "table": split.table,
                                   "segment": split.segment,
                                   "columns": columns})
            finally:
                client.close()
            with pa.ipc.open_stream(out["ipc"]) as r:
                return r.read_all().combine_chunks().to_batches()[0]
        except TransportError as e:  # connection-level: try next replica
            last = e
        except RemoteError as e:
            # stale routing ("not hosted") fails over; anything else (e.g.
            # unknown column) is the caller's bug — fail fast
            if "not hosted" not in str(e):
                raise
            last = e
    raise RuntimeError(
        f"segment {split.segment} unreadable on all replicas: {last}")


def read_table(broker, table: str, columns: Optional[list[str]] = None,
               num_readers: int = 4):
    """Partition-parallel whole-table read → pyarrow.Table (reference: the
    Spark connector's parallel partition readers)."""
    import concurrent.futures as cf

    import pyarrow as pa

    splits = plan_scan(broker, table)
    if not splits:
        raise RuntimeError(f"no routable segments for {table}")
    with cf.ThreadPoolExecutor(max_workers=num_readers) as pool:
        batches = list(pool.map(
            lambda s: read_split(s, columns), splits))
    return pa.Table.from_batches(batches)