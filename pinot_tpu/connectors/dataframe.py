"""pandas / pyarrow connector: bulk read + bulk write.

Reference analogue: pinot-connectors/pinot-spark-3-connector — the read
side runs queries against the cluster and hands back a dataframe; the
write side is the batch segment writer (Spark's PinotDataWriter building
segments from partitions and pushing them to the controller). pandas and
pyarrow are the dataframe currency of the Python data stack, so the
connector speaks both.

    import pinot_tpu.connectors as pc
    tbl = pc.read_sql("SELECT * FROM stats LIMIT 100000", broker_url=url)
    df  = pc.read_sql_pandas("SELECT ...", connection=conn)
    pc.write_dataframe(df, table_name="stats", controller=ctl,
                       out_dir="/deep/store", rows_per_segment=1_000_000)
"""

from __future__ import annotations

from pathlib import Path
from typing import Optional

import numpy as np

from ..spi.data_types import Schema

_ARROW_TYPES = {
    "INT": "int32", "LONG": "int64", "FLOAT": "float32", "DOUBLE": "float64",
    "BOOLEAN": "bool_", "TIMESTAMP": "int64", "STRING": "string",
    "JSON": "string", "BYTES": "binary",
}


# -- read side -----------------------------------------------------------------


def read_sql(sql: str, broker_url: Optional[str] = None, connection=None,
             auth=None, token: Optional[str] = None):
    """Run a query and return a ``pyarrow.Table`` (the Spark connector's
    read path: query → dataframe)."""
    import pyarrow as pa

    rs = _result_set(sql, broker_url, connection, auth, token)
    if rs.rows:
        cols = dict(zip(rs.column_names, map(list, zip(*rs.rows))))
    else:
        cols = {name: [] for name in rs.column_names}
    arrays, names = [], []
    for name, ctype in zip(rs.column_names, rs.column_types):
        pa_type = getattr(pa, _ARROW_TYPES.get(ctype, "string"), pa.string)()
        try:
            arrays.append(pa.array(cols[name], type=pa_type))
        except (pa.ArrowInvalid, pa.ArrowTypeError):
            arrays.append(pa.array(cols[name]))  # let arrow infer
        names.append(name)
    return pa.table(dict(zip(names, arrays)))


def read_sql_pandas(sql: str, broker_url: Optional[str] = None,
                    connection=None, auth=None, token: Optional[str] = None):
    return read_sql(sql, broker_url, connection, auth, token).to_pandas()


def scan_table(broker, table: str, columns: list[str],
               num_readers: int = 4, where: Optional[str] = None):
    """Segment-parallel scan: yields one ``pyarrow.RecordBatch`` per
    segment, fetched concurrently from the hosting servers.

    Reference analogue: the Spark connector's partitioned read plan —
    one Spark InputPartition per Pinot segment, each reading via the
    server's streaming endpoint (pinot-spark-3-connector
    PinotScan/PinotInputPartition). Here the embedded ``Broker`` supplies
    the routing table and per-segment selections run through the normal
    scatter plane, ``num_readers`` at a time; downstream engines consume
    the batches independently (the dataframe stack's executor pool plays
    the role of Spark's)."""
    import concurrent.futures as cf

    import pyarrow as pa

    routing = broker.routing_table(table)
    cols = ", ".join(columns)
    cond = f" WHERE {where}" if where else ""
    raw = table.rsplit("_", 1)[0]

    def fetch(seg):
        resp = broker.execute_sql(
            f"SELECT {cols} FROM {raw}{cond} LIMIT 1000000000",
            segments={table: [seg]})
        if resp.exceptions:
            raise RuntimeError(f"segment {seg}: {resp.exceptions}")
        rt = resp.result_table
        data = {name: [r[i] for r in rt.rows]
                for i, name in enumerate(rt.schema.column_names)}
        return pa.RecordBatch.from_pydict(data)

    with cf.ThreadPoolExecutor(max_workers=num_readers) as pool:
        futs = {pool.submit(fetch, seg): seg for seg in sorted(routing)}
        for fut in cf.as_completed(futs):
            yield futs[fut], fut.result()


def _result_set(sql, broker_url, connection, auth, token):
    if connection is None:
        if broker_url is None:
            raise ValueError("pass broker_url or connection")
        from ..client import connect

        # client connections are stateless (one HTTP request per execute,
        # nothing held open) so the throwaway connection costs one object;
        # pass `connection=` to reuse credentials across many reads
        connection = connect(broker_url, auth=auth, token=token)
    return connection.execute(sql)


# -- write side ----------------------------------------------------------------


def infer_schema(df, table_name: str,
                 time_column: Optional[str] = None) -> Schema:
    """pandas/pyarrow dtypes → Schema (the Spark writer's schema mapping).
    Integer/float columns become metrics, strings/booleans dimensions, the
    named time column a date-time field."""
    if hasattr(df, "to_pandas"):  # pyarrow.Table
        df = df.to_pandas()
    dims, metrics, date_times = [], [], []
    for name in df.columns:
        kind = df[name].dtype.kind
        if name == time_column or kind == "M":
            # datetime64 columns are date-times regardless of naming; values
            # convert to epoch MILLIS at write time
            date_times.append((name, "TIMESTAMP" if kind in "iuM" else "LONG"))
        elif kind in "iu":
            metrics.append((name, "LONG" if df[name].dtype.itemsize > 4
                            else "INT"))
        elif kind == "f":
            metrics.append((name, "DOUBLE" if df[name].dtype.itemsize > 4
                            else "FLOAT"))
        elif kind == "b":
            dims.append((name, "BOOLEAN"))
        else:
            dims.append((name, "STRING"))
    return Schema.build(table_name, dimensions=dims, metrics=metrics,
                        date_times=date_times)


def write_dataframe(df, table_name: str, out_dir: str | Path,
                    schema: Optional[Schema] = None,
                    table_config=None, controller=None,
                    time_column: Optional[str] = None,
                    rows_per_segment: int = 1_000_000,
                    segment_prefix: Optional[str] = None) -> list[str]:
    """Build segment directories from a dataframe and (optionally) register
    them with a controller (reference: the Spark connector's
    PinotDataWriter → segment build → controller push). Returns the built
    segment paths."""
    from ..segment.builder import SegmentBuilder

    if hasattr(df, "to_pandas"):
        df = df.to_pandas()
    if schema is None:
        schema = infer_schema(df, table_name, time_column)
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    prefix = segment_prefix or f"{table_name}"
    paths: list[str] = []
    n = len(df)
    num_segments = max(1, (n + rows_per_segment - 1) // rows_per_segment)
    for i in range(num_segments):
        part = df.iloc[i * rows_per_segment:(i + 1) * rows_per_segment]
        cols = {}
        for name in df.columns:
            v = part[name].to_numpy()
            if v.dtype.kind == "M":
                # datetime64[*] → epoch millis (TIMESTAMP's documented unit)
                v = v.astype("datetime64[ms]").astype(np.int64)
            cols[name] = v.astype(object) if v.dtype.kind == "O" else v
        seg_name = f"{prefix}_{i}"
        dest = out_dir / seg_name
        SegmentBuilder(schema, table_config=table_config,
                       segment_name=seg_name).build(cols, dest)
        paths.append(str(dest))
        if controller is not None:
            from ..segment.format import partition_push_metadata

            meta = {"location": str(dest), "numDocs": len(part)}
            meta.update(partition_push_metadata(dest))
            if time_column is not None and len(part):
                tv = cols[time_column]  # already normalized to epoch millis
                meta["startTimeMs"] = int(np.min(tv))
                meta["endTimeMs"] = int(np.max(tv))
            controller.add_segment(f"{table_name}_OFFLINE", seg_name, meta)
    return paths
