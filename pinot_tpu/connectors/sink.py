"""Streaming segment-writer sink (the Flink connector analogue).

Reference: pinot-connectors/pinot-flink-connector — FlinkSegmentWriter
buffers rows per parallel sink instance, cuts a segment every
``segmentFlushMaxNumRecords`` rows (or on checkpoint/close), names it with
the sink's partition id + a monotonically increasing sequence, and pushes
it via the segment uploader. The TPU-native rebuild keeps that contract —
row-at-a-time ``collect()``, threshold/explicit ``flush()``, push-on-close
— over this repo's transform pipeline + two-pass SegmentBuilder, so any
record-stream framework (a Flink DataStream sink, a Beam DoFn, a plain
loop over a queue) can land rows as query-ready segments.
"""

from __future__ import annotations

from typing import Mapping, Optional

from ..ingestion.batch import upload_segment_from_rows
from ..ingestion.transform import build_transform_pipeline
from ..spi.data_types import Schema
from ..spi.table_config import TableConfig


class StreamingSegmentWriter:
    """Buffer rows → segment directories → (optional) controller push.

    One writer per parallel sink instance; ``partition_id`` disambiguates
    segment names across instances exactly like the Flink writer's
    indexOfSubtask (reference: FlinkSegmentWriter.init(...,
    indexOfSubtask)). Not thread-safe — one owner per instance, matching
    the reference's per-subtask writer.
    """

    def __init__(self, schema: Schema, out_dir_uri: str,
                 table_config: Optional[TableConfig] = None,
                 controller=None, table_name_with_type: Optional[str] = None,
                 partition_id: int = 0,
                 flush_max_rows: int = 500_000,
                 time_column: Optional[str] = None,
                 start_seq: Optional[int] = None):
        self.schema = schema
        self.table_config = table_config or TableConfig(
            table_name=schema.schema_name)
        self.out_dir_uri = out_dir_uri.rstrip("/")
        self.controller = controller
        self.table = table_name_with_type or f"{schema.schema_name}_OFFLINE"
        self.partition_id = partition_id
        self.flush_max_rows = flush_max_rows
        self.time_column = time_column
        self._pipeline = build_transform_pipeline(self.schema,
                                                  self.table_config)
        self._rows: list[dict] = []
        # a restarted pipeline must not reuse segment names (add_segment
        # overwrites metadata — the first run's rows would silently
        # vanish). The Flink writer recovers its sequence from checkpoint
        # state; here it re-seeds past the table's registered segments for
        # this partition, or from an explicit start_seq.
        if start_seq is not None:
            self._seq = start_seq
        else:
            self._seq = 0
            if controller is not None:
                prefix = f"{schema.schema_name}_{partition_id}_"
                for seg in controller.store.children(
                        f"/SEGMENTS/{self.table}"):
                    if seg.startswith(prefix):
                        try:
                            self._seq = max(self._seq,
                                            int(seg[len(prefix):]) + 1)
                        except ValueError:
                            pass
        self._closed = False
        self.segments: list[str] = []  # pushed/built segment URIs
        self.rows_filtered = 0

    def collect(self, row: Mapping) -> None:
        """Add one record; cuts a segment when the buffer hits the
        threshold (reference: FlinkSegmentWriter.collect)."""
        if self._closed:
            raise RuntimeError("writer is closed")
        out = self._pipeline.transform(dict(row))
        if out is None:
            self.rows_filtered += 1
            return
        self._rows.append(out)
        if len(self._rows) >= self.flush_max_rows:
            self.flush()

    def flush(self) -> Optional[str]:
        """Build + push the buffered rows as one segment; returns its URI
        (None if the buffer was empty). Reference: flush() on
        checkpoint/threshold."""
        if not self._rows:
            return None
        name = (f"{self.schema.schema_name}_{self.partition_id}"
                f"_{self._seq}")
        self._seq += 1
        out_uri, partitions = upload_segment_from_rows(
            self.schema, self.table_config, name, self._rows,
            self.out_dir_uri)
        if self.controller is not None:
            meta = {"location": out_uri, "numDocs": len(self._rows)}
            if partitions:
                meta["partitions"] = partitions
            if self.time_column:
                tv = [r[self.time_column] for r in self._rows
                      if r.get(self.time_column) is not None]
                if tv:
                    meta["startTimeMs"] = int(min(tv))
                    meta["endTimeMs"] = int(max(tv))
            self.controller.add_segment(self.table, name, meta)
        self.segments.append(out_uri)
        self._rows = []
        return out_uri

    def close(self) -> list[str]:
        """Flush the tail and seal the writer; returns every segment URI
        this instance produced."""
        if not self._closed:
            self.flush()
            self._closed = True
        return self.segments

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc[0] is None:
            self.close()
        return False
