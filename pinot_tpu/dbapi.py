"""PEP 249 (DB-API 2.0) interface over the broker REST surface.

Reference analogue: pinot-clients/pinot-jdbc-client — the standard-driver
face of the query engine (JDBC for the JVM world, DB-API for Python). A
``Connection``/``Cursor`` pair over client.py's HTTP connection, with the
standard exception hierarchy, ``description`` metadata, fetch* methods,
and qmark-style parameter binding with SQL-literal escaping.

    import pinot_tpu.dbapi as dbapi
    conn = dbapi.connect("http://localhost:8099")
    cur = conn.cursor()
    cur.execute("SELECT team, SUM(runs) FROM stats WHERE year > ? "
                "GROUP BY team", (2000,))
    print(cur.description)
    rows = cur.fetchall()
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Sequence

from .client import Connection as _HttpConnection
from .client import PinotClientError

apilevel = "2.0"
threadsafety = 2  # threads may share the module and connections
paramstyle = "qmark"


# -- exception hierarchy (PEP 249) -------------------------------------------


class Warning(Exception):  # noqa: A001 — name mandated by PEP 249
    pass


class Error(Exception):
    pass


class InterfaceError(Error):
    pass


class DatabaseError(Error):
    pass


class DataError(DatabaseError):
    pass


class OperationalError(DatabaseError):
    pass


class IntegrityError(DatabaseError):
    pass


class InternalError(DatabaseError):
    pass


class ProgrammingError(DatabaseError):
    pass


class NotSupportedError(DatabaseError):
    pass


# -- parameter binding --------------------------------------------------------


def _quote(value: Any) -> str:
    if value is None:
        return "NULL"
    if isinstance(value, bool):
        return "true" if value else "false"
    if isinstance(value, (int, float)):
        return repr(value)
    if isinstance(value, (list, tuple)):
        return "(" + ", ".join(_quote(v) for v in value) + ")"
    return "'" + str(value).replace("'", "''") + "'"


def _bind(sql: str, params: Optional[Sequence]) -> str:
    if params is None:
        return sql
    out = []
    it = iter(params)
    in_str = False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if ch == "'":
            in_str = not in_str
            out.append(ch)
        elif ch == "?" and not in_str:
            try:
                out.append(_quote(next(it)))
            except StopIteration:
                raise ProgrammingError("not enough parameters for query")
        else:
            out.append(ch)
        i += 1
    leftovers = sum(1 for _ in it)
    if leftovers:
        raise ProgrammingError(f"{leftovers} unused parameters")
    return "".join(out)


# -- type codes ---------------------------------------------------------------

STRING = "STRING"
NUMBER = "NUMBER"
DATETIME = "DATETIME"
BINARY = "BINARY"
ROWID = "ROWID"

_TYPE_MAP = {
    "INT": NUMBER, "LONG": NUMBER, "FLOAT": NUMBER, "DOUBLE": NUMBER,
    "BIG_DECIMAL": NUMBER, "BOOLEAN": NUMBER, "TIMESTAMP": DATETIME,
    "STRING": STRING, "JSON": STRING, "BYTES": BINARY,
}


class Cursor:
    arraysize = 1

    def __init__(self, connection: "Connection"):
        self._conn = connection
        self._rows: list[list] = []
        self._pos = 0
        self.description: Optional[list[tuple]] = None
        self.rowcount = -1

    # -- execution ---------------------------------------------------------
    def execute(self, operation: str, parameters: Optional[Sequence] = None):
        self._check_open()
        sql = _bind(operation, parameters)
        try:
            rs = self._conn._http.execute(sql)
        except PinotClientError as e:
            raise OperationalError(str(e)) from None
        self._rows = list(rs)
        self._pos = 0
        self.rowcount = len(self._rows)
        self.description = [
            (name, _TYPE_MAP.get(ctype, STRING), None, None, None, None, None)
            for name, ctype in zip(rs.column_names, rs.column_types)]
        return self

    def executemany(self, operation: str, seq_of_parameters):
        for params in seq_of_parameters:
            self.execute(operation, params)
        return self

    # -- fetching ----------------------------------------------------------
    def fetchone(self) -> Optional[list]:
        self._check_open()
        if self._pos >= len(self._rows):
            return None
        row = self._rows[self._pos]
        self._pos += 1
        return row

    def fetchmany(self, size: Optional[int] = None) -> list[list]:
        self._check_open()
        n = size if size is not None else self.arraysize
        out = self._rows[self._pos:self._pos + n]
        self._pos += len(out)
        return out

    def fetchall(self) -> list[list]:
        self._check_open()
        out = self._rows[self._pos:]
        self._pos = len(self._rows)
        return out

    def __iter__(self) -> Iterator[list]:
        while True:
            row = self.fetchone()
            if row is None:
                return
            yield row

    # -- no-ops mandated by the spec ---------------------------------------
    def setinputsizes(self, sizes):
        pass

    def setoutputsize(self, size, column=None):
        pass

    def close(self) -> None:
        self._rows = []
        self._conn = None

    def _check_open(self) -> None:
        if self._conn is None or self._conn._closed:
            raise InterfaceError("cursor is closed")


class Connection:
    def __init__(self, broker_url: str, timeout_s: float = 60.0,
                 auth=None, token: Optional[str] = None):
        self._http = _HttpConnection(broker_url, timeout_s, auth=auth,
                                     token=token)
        self._closed = False

    def cursor(self) -> Cursor:
        if self._closed:
            raise InterfaceError("connection is closed")
        return Cursor(self)

    def close(self) -> None:
        self._closed = True

    def commit(self) -> None:
        pass  # queries are read-only; commit is a spec-mandated no-op

    def rollback(self) -> None:
        raise NotSupportedError("transactions are not supported")

    def __enter__(self) -> "Connection":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def connect(broker_url: str, timeout_s: float = 60.0, auth=None,
            token: Optional[str] = None) -> Connection:
    return Connection(broker_url, timeout_s, auth=auth, token=token)
