"""Aggregation functions: device lowering + host semantics.

Reference: pinot-core/.../query/aggregation/function/ (93 impls behind
AggregationFunction.aggregate/aggregateGroupBySV — .../AggregationFunction.java:74-82).
The TPU design splits each SQL aggregation into:
  1. *primitive device reductions* (AggOp: count/sum/min/max/sumsq/
     distinct_bitmap) fused into the segment kernel (ops/kernels.py),
  2. a host-side *intermediate state* per group (analogue of the reference's
     intermediate results shipped in DataTables),
  3. shared `AggSemantics` (merge across segments/servers + finalize at
     broker reduce + result type) used identically by the device path and
     the host (numpy) fallback engine, so the two paths can never drift.

Result types follow the reference: COUNT→LONG, SUM/MIN/MAX/AVG→DOUBLE,
DISTINCTCOUNT→INT.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Callable, Optional

import numpy as np

from ..query.expressions import ExpressionContext
from . import ir


class UnsupportedQueryError(Exception):
    """Raised when a query shape can't lower to the device kernel; callers
    fall back to the host (numpy) engine."""


@dataclass
class AggSemantics:
    """Cross-segment merge + broker finalize for one aggregation function."""

    merge: Callable  # (a, b) -> state
    finalize: Callable  # (state) -> python scalar
    result_type: str
    empty_value: object  # result when zero rows matched (aggregation query)


@dataclass
class LoweredAgg:
    """Device lowering of one SQL aggregation: how to read kernel outputs.

    extract(outs, g) builds the per-group intermediate state from the kernel
    output tuple (outs[0] is always the per-group row count).
    """

    name: str
    semantics: AggSemantics
    extract: Callable  # (outs, g) -> state


def _var_finalize(name: str):
    def fin(state):
        n, s, sq = state
        if n == 0 or (name.endswith("samp") and n < 2):
            return math.nan
        var = sq / n - (s / n) ** 2
        if name.endswith("samp"):
            var = var * n / (n - 1)
        var = max(var, 0.0)
        return math.sqrt(var) if name.startswith("stddev") else var

    return fin


def _merge3(a, b):
    return (a[0] + b[0], a[1] + b[1], a[2] + b[2])


def get_semantics(name: str) -> AggSemantics:
    if name == "count":
        return AggSemantics(lambda a, b: a + b, lambda s: s, "LONG", 0)
    if name in ("sum", "summv"):
        return AggSemantics(lambda a, b: a + b, lambda s: s, "DOUBLE", 0.0)
    if name in ("min", "minmv"):
        return AggSemantics(min, lambda s: s, "DOUBLE", math.inf)
    if name in ("max", "maxmv"):
        return AggSemantics(max, lambda s: s, "DOUBLE", -math.inf)
    if name == "minmaxrange":
        return AggSemantics(lambda a, b: (min(a[0], b[0]), max(a[1], b[1])),
                            lambda s: s[1] - s[0], "DOUBLE", -math.inf)
    if name in ("avg", "avgmv"):
        return AggSemantics(lambda a, b: (a[0] + b[0], a[1] + b[1]),
                            lambda s: (s[0] / s[1]) if s[1] else math.nan,
                            "DOUBLE", math.nan)
    if name in ("distinctcount", "distinctcountbitmap", "segmentpartitioneddistinctcount",
                "distinctcountmv"):
        return AggSemantics(lambda a, b: a | b, len, "INT", 0)
    if name == "distinctsum":
        return AggSemantics(lambda a, b: a | b, lambda s: float(sum(s)), "DOUBLE", 0.0)
    if name == "distinctavg":
        return AggSemantics(lambda a, b: a | b,
                            lambda s: sum(s) / len(s) if s else math.nan, "DOUBLE", math.nan)
    if name in ("stddevpop", "stddevsamp", "varpop", "varsamp"):
        return AggSemantics(_merge3, _var_finalize(name), "DOUBLE", math.nan)
    if name == "booland":
        # empty state is the AND identity (True) on both engines
        return AggSemantics(lambda a, b: a and b, bool, "BOOLEAN", True)
    if name in ("boolor", "boolagg"):
        return AggSemantics(lambda a, b: a or b, bool, "BOOLEAN", False)
    raise UnsupportedQueryError(f"aggregation {name} not implemented")


# ---------------------------------------------------------------------------
# Device lowering
# ---------------------------------------------------------------------------


class AggPlanContext:
    """Planner callback surface used by lowerings to register device ops."""

    def __init__(self):
        self.ops: list[ir.AggOp] = []

    def add_op(self, op: ir.AggOp) -> int:
        """Register a primitive op, dedup'd; returns its kernel output index
        (output 0 is the group count)."""
        if op in self.ops:
            return 1 + self.ops.index(op)
        self.ops.append(op)
        return len(self.ops)

    # provided by SegmentPlanner (engine/plan.py):
    def value_expr(self, e: ExpressionContext) -> ir.ValueExpr:  # pragma: no cover
        raise NotImplementedError

    def dict_info(self, e: ExpressionContext, sv_only: bool = False):  # pragma: no cover
        raise NotImplementedError


def lower_aggregation(ctx: AggPlanContext, expr: ExpressionContext) -> LoweredAgg:
    fn = expr.function
    name, args = fn.name, fn.arguments
    label = str(expr)
    sem = get_semantics(name)

    if name == "count":
        return LoweredAgg(label, sem, lambda outs, g: int(outs[0][g]))

    if name in ("sum", "min", "max"):
        i = ctx.add_op(ir.AggOp(name, vexpr=ctx.value_expr(args[0])))
        return LoweredAgg(label, sem, lambda outs, g: float(outs[i][g]))

    if name == "minmaxrange":
        i_min = ctx.add_op(ir.AggOp("min", vexpr=ctx.value_expr(args[0])))
        i_max = ctx.add_op(ir.AggOp("max", vexpr=ctx.value_expr(args[0])))
        return LoweredAgg(label, sem,
                          lambda outs, g: (float(outs[i_min][g]), float(outs[i_max][g])))

    if name == "avg":
        i = ctx.add_op(ir.AggOp("sum", vexpr=ctx.value_expr(args[0])))
        return LoweredAgg(label, sem, lambda outs, g: (float(outs[i][g]), int(outs[0][g])))

    if name in ("distinctcount", "distinctcountbitmap", "segmentpartitioneddistinctcount",
                "distinctsum", "distinctavg"):
        info = ctx.dict_info(args[0], sv_only=True)
        if info is None:
            raise UnsupportedQueryError(
                f"distinct aggregation needs a dict-encoded SV column: {args[0]}")
        ids_slot, card, dictionary = info
        i = ctx.add_op(ir.AggOp("distinct_bitmap", ids_slot=ids_slot, card=card))
        numeric = name in ("distinctsum", "distinctavg")

        def extract(outs, g, _i=i, _d=dictionary, _numeric=numeric):
            sel = _d.values[np.nonzero(outs[_i][g])[0]]
            if _numeric:
                return frozenset(float(v) for v in sel)
            return frozenset(sel.tolist())

        return LoweredAgg(label, sem, extract)

    if name in ("stddevpop", "stddevsamp", "varpop", "varsamp"):
        i_s = ctx.add_op(ir.AggOp("sum", vexpr=ctx.value_expr(args[0])))
        i_q = ctx.add_op(ir.AggOp("sumsq", vexpr=ctx.value_expr(args[0])))
        return LoweredAgg(
            label, sem,
            lambda outs, g: (int(outs[0][g]), float(outs[i_s][g]), float(outs[i_q][g])))

    if name in ("booland", "boolor", "boolagg"):
        # booleans are 0/1 ints: AND = min (empty→+inf→True), OR = max (empty→-inf→False)
        kind = "min" if name == "booland" else "max"
        i = ctx.add_op(ir.AggOp(kind, vexpr=ctx.value_expr(args[0])))
        return LoweredAgg(label, sem, lambda outs, g: bool(outs[i][g] > 0.5))

    raise UnsupportedQueryError(f"aggregation {name} not yet lowered to device")


# ---------------------------------------------------------------------------
# Host (numpy) states — used by the fallback engine and the test oracle
# ---------------------------------------------------------------------------


def host_state(name: str, values: Optional[np.ndarray]):
    """Per-group intermediate state from the group's (already filtered) raw
    values. Must produce states mergeable/finalizable by get_semantics(name)
    — i.e. identical shape to the device path's LoweredAgg.extract."""
    n = 0 if values is None else len(values)
    if name == "count":
        return n
    if values is None:
        raise UnsupportedQueryError(f"{name} requires an argument")
    if name in ("sum", "summv"):
        return float(np.sum(values)) if n else 0.0
    if name in ("min", "minmv"):
        return float(np.min(values)) if n else math.inf
    if name in ("max", "maxmv"):
        return float(np.max(values)) if n else -math.inf
    if name == "minmaxrange":
        return (float(np.min(values)), float(np.max(values))) if n else (math.inf, -math.inf)
    if name in ("avg", "avgmv"):
        return (float(np.sum(values)), n)
    if name in ("distinctcount", "distinctcountbitmap", "segmentpartitioneddistinctcount",
                "distinctcountmv"):
        return frozenset(np.unique(values).tolist())
    if name in ("distinctsum", "distinctavg"):
        return frozenset(float(v) for v in np.unique(values))
    if name in ("stddevpop", "stddevsamp", "varpop", "varsamp"):
        v = values.astype(np.float64)
        return (n, float(v.sum()), float((v * v).sum()))
    if name == "booland":
        return bool(np.all(values)) if n else True
    if name in ("boolor", "boolagg"):
        return bool(np.any(values)) if n else False
    raise UnsupportedQueryError(f"aggregation {name} not implemented on host")
