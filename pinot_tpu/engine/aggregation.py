"""Aggregation functions: device lowering + host semantics.

Reference: pinot-core/.../query/aggregation/function/ (93 impls behind
AggregationFunction.aggregate/aggregateGroupBySV — .../AggregationFunction.java:74-82).
The TPU design splits each SQL aggregation into:
  1. *primitive device reductions* (AggOp: count/sum/min/max/sumsq/
     distinct_bitmap/value_hist/hist_fixed) fused into the segment kernel
     (ops/kernels.py),
  2. a host-side *intermediate state* per group (analogue of the reference's
     intermediate results shipped in DataTables),
  3. shared `AggSemantics` (merge across segments/servers + finalize at
     broker reduce + result type) used identically by the device path and
     the host (numpy) fallback engine, so the two paths can never drift.

Approximate functions (DISTINCTCOUNTHLL / THETA / PERCENTILETDIGEST / ...)
use the mergeable sketch states in utils/sketches.py — value-based, so they
merge across segments whose dictionaries differ.

Result types follow the reference (AggregationFunction.getFinalResultColumnType):
COUNT→LONG, SUM/MIN/MAX/AVG/PERCENTILE*→DOUBLE, DISTINCTCOUNT→INT,
DISTINCTCOUNTHLL/THETA→LONG.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from decimal import Decimal
from typing import Callable, Optional

import numpy as np

from ..query.expressions import ExpressionContext, FunctionContext
from ..utils.sketches import (
    HyperLogLog,
    SmartDistinctSet,
    TDigest,
    ThetaSketch,
    ValueHist,
)
from . import ir


class UnsupportedQueryError(Exception):
    """Raised when a query shape can't lower to the device kernel; callers
    fall back to the host (numpy) engine."""


@dataclass
class AggSemantics:
    """Cross-segment merge + broker finalize for one aggregation function."""

    merge: Callable  # (a, b) -> state
    finalize: Callable  # (state) -> python scalar
    result_type: str
    empty_value: object  # result when zero rows matched (aggregation query)


# One table of (merge spec, finalize tag) per scalar aggregation, shared by
# the device lowering (VecAgg below) and the host vectorized group-by
# (host_executor._group_by_vectorized) so their GroupArrays stay mergeable.
VEC_RECIPES = {
    "count": (("add",), ("id", 0)),
    "sum": (("add",), ("id", 0)),
    "min": (("min",), ("id", 0)),
    "max": (("max",), ("id", 0)),
    "avg": (("add", "add"), ("div", 0, 1)),
    "minmaxrange": (("min", "max"), ("sub", 1, 0)),
}


@dataclass
class VecAgg:
    """Columnar (vectorized) form of one aggregation for the GroupArrays
    fast path: extract pulls per-component numpy columns for ALL groups at
    once; spec gives each component's cross-segment merge op; fin_tag is a
    picklable finalize recipe evaluated by the broker reducer
    (("id", c) | ("div", a, b) | ("sub", a, b) over component indices)."""

    spec: tuple  # per component: "add" | "min" | "max"
    extract: Callable  # (outs, gids) -> tuple[np.ndarray, ...]
    fin_tag: tuple


@dataclass
class LoweredAgg:
    """Device lowering of one SQL aggregation: how to read kernel outputs.

    extract(outs, g) builds the per-group intermediate state from the kernel
    output tuple (outs[0] is always the per-group row count). vec, when set,
    is the whole-table columnar form (GroupArrays fast path).
    """

    name: str
    semantics: AggSemantics
    extract: Callable  # (outs, g) -> state
    vec: "VecAgg | None" = None
    # optional batch form: prepare(outs) -> (g -> state). The executor uses
    # it on the dict path so per-output work (e.g. decoding the sparse
    # distinct pair list) runs ONCE, vectorized, instead of per group.
    prepare: "Callable | None" = None


# ---------------------------------------------------------------------------
# Argument model: leading args are data expressions, the rest are literal
# parameters (reference: PERCENTILE(col, 95), HISTOGRAM(col, 0, 100, 10),
# FIRSTWITHTIME(dataCol, timeCol, 'dataType')...).
# ---------------------------------------------------------------------------

_DATA_ARITY = {
    "count": 1,
    "covarpop": 2,
    "covarsamp": 2,
    "corr": 2,
    "exprmin": 2,
    "exprmax": 2,
    "firstwithtime": 2,
    "lastwithtime": 2,
}

# legacy digit-suffixed percentiles: PERCENTILE95(col) ≡ PERCENTILE(col, 95)
# (shared pattern — query/expressions.py uses it for is_aggregation too)
from ..query.expressions import PERCENTILE_SUFFIX_RE as _PCT_SUFFIX  # noqa: E402
# cycle-safe: funnel.py imports this module only lazily
from .funnel import FUNNEL_FNS as _FUNNEL_FNS  # noqa: E402


def canonicalize(name: str, extra: tuple) -> tuple[str, tuple]:
    m = _PCT_SUFFIX.match(name)
    if m:
        base = m.group(1) + (m.group(3) or "")
        return base, (int(m.group(2)),) + extra
    return name, extra


def split_args(fn: FunctionContext):
    """→ (data_arg_expressions, literal_extras)."""
    arity = _DATA_ARITY.get(_PCT_SUFFIX.sub(lambda m: m.group(1), fn.name), 1)
    data = list(fn.arguments[:arity])
    extra = []
    for a in fn.arguments[arity:]:
        if not a.is_literal:
            raise UnsupportedQueryError(
                f"{fn.name}: parameter {a} must be a literal")
        extra.append(a.literal)
    return data, tuple(extra)


def semantics_for(expr: ExpressionContext) -> AggSemantics:
    fn = expr.function
    if fn.name == "filter":  # FILTER (WHERE ...) wrapper: inner semantics
        return semantics_for(fn.arguments[0])
    if fn.name in _FUNNEL_FNS:  # funnel args aren't (data, literal*)-shaped
        from .funnel import funnel_semantics

        return funnel_semantics(fn)
    _, extra = split_args(fn)
    return get_semantics(fn.name, extra)


def _pct(extra, default=50.0) -> float:
    return float(extra[0]) if extra else default


def _merge_maybe(pick):
    """Merge for states that may be None (empty groups/segments)."""

    def merge(a, b):
        if a is None:
            return b
        if b is None:
            return a
        return pick(a, b)

    return merge


def _var_finalize(name: str):
    def fin(state):
        n, s, sq = state
        if n == 0 or (name.endswith("samp") and n < 2):
            return math.nan
        var = sq / n - (s / n) ** 2
        if name.endswith("samp"):
            var = var * n / (n - 1)
        var = max(var, 0.0)
        return math.sqrt(var) if name.startswith("stddev") else var

    return fin


def _merge3(a, b):
    return (a[0] + b[0], a[1] + b[1], a[2] + b[2])


def _merge_tuple(a, b):
    return tuple(x + y for x, y in zip(a, b))


def _covar_finalize(name: str):
    def fin(state):
        n, sx, sy, sxy, sxx, syy = state
        if n == 0 or (name == "covarsamp" and n < 2):
            return math.nan
        cov = sxy / n - (sx / n) * (sy / n)
        if name == "covarsamp":
            return cov * n / (n - 1)
        if name == "corr":
            vx = sxx / n - (sx / n) ** 2
            vy = syy / n - (sy / n) ** 2
            denom = math.sqrt(max(vx, 0.0) * max(vy, 0.0))
            return cov / denom if denom else math.nan
        return cov

    return fin


def _moments_finalize(name: str):
    def fin(state):
        n, s1, s2, s3, s4 = state
        if n == 0:
            return math.nan
        mu = s1 / n
        m2 = s2 / n - mu * mu
        if m2 <= 0:
            return math.nan
        if name == "skewness":
            m3 = s3 / n - 3 * mu * s2 / n + 2 * mu**3
            return m3 / m2**1.5
        m4 = s4 / n - 4 * mu * s3 / n + 6 * mu * mu * s2 / n - 3 * mu**4
        return m4 / (m2 * m2) - 3.0

    return fin


_EXACT_DISTINCT = (
    "distinctcount", "distinctcountbitmap", "segmentpartitioneddistinctcount",
    "distinctcountmv", "distinctcountbitmapmv",
)
_HLL_FNS = ("distinctcounthll", "distinctcounthllplus", "distinctcountull",
            "distinctcountcpc", "distinctcounthllmv", "distinctcounthllplusmv")
_THETA_FNS = ("distinctcounttheta", "distinctcountrawtheta")
_PCT_EXACT = ("percentile", "percentilemv")
_PCT_DIGEST = ("percentileest", "percentiletdigest", "percentilekll",
               "percentilesmarttdigest", "percentileestmv", "percentiletdigestmv",
               "percentilekllmv", "percentilerawest", "percentilerawtdigest",
               "percentilerawkll")


def get_semantics(name: str, extra: tuple = ()) -> AggSemantics:
    name, extra = canonicalize(name, extra)
    if name in ("count", "countmv"):
        return AggSemantics(lambda a, b: a + b, lambda s: s, "LONG", 0)
    if name in ("sum", "summv"):
        return AggSemantics(lambda a, b: a + b, lambda s: s, "DOUBLE", 0.0)
    if name == "sumprecision":
        return AggSemantics(lambda a, b: a + b, str, "BIG_DECIMAL", "0")  # Decimal state
    if name in ("min", "minmv"):
        return AggSemantics(min, lambda s: s, "DOUBLE", math.inf)
    if name in ("max", "maxmv"):
        return AggSemantics(max, lambda s: s, "DOUBLE", -math.inf)
    if name in ("minmaxrange", "minmaxrangemv"):
        return AggSemantics(lambda a, b: (min(a[0], b[0]), max(a[1], b[1])),
                            lambda s: s[1] - s[0], "DOUBLE", -math.inf)
    if name in ("avg", "avgmv"):
        return AggSemantics(lambda a, b: (a[0] + b[0], a[1] + b[1]),
                            lambda s: (s[0] / s[1]) if s[1] else math.nan,
                            "DOUBLE", math.nan)
    if name in _EXACT_DISTINCT:
        return AggSemantics(lambda a, b: a | b, len, "INT", 0)
    if name == "distinctsum":
        return AggSemantics(lambda a, b: a | b, lambda s: float(sum(s)), "DOUBLE", 0.0)
    if name == "distinctavg":
        return AggSemantics(lambda a, b: a | b,
                            lambda s: sum(s) / len(s) if s else math.nan, "DOUBLE", math.nan)
    if name in _HLL_FNS:
        return AggSemantics(lambda a, b: a.merge(b), lambda s: s.cardinality(), "LONG", 0)
    if name in _THETA_FNS:
        return AggSemantics(lambda a, b: a.merge(b), lambda s: s.cardinality(), "LONG", 0)
    if name in ("distinctcountsmart", "distinctcountsmarthll"):
        return AggSemantics(lambda a, b: a.merge(b), lambda s: s.cardinality(), "INT", 0)
    if name in _PCT_EXACT:
        pct = _pct(extra)
        return AggSemantics(lambda a, b: a.merge(b),
                            lambda s, _p=pct: s.percentile(_p), "DOUBLE", math.nan)
    if name in _PCT_DIGEST:
        pct = _pct(extra)
        return AggSemantics(lambda a, b: a.merge(b),
                            lambda s, _p=pct: s.quantile(_p / 100.0), "DOUBLE", math.nan)
    if name == "mode":
        return AggSemantics(lambda a, b: a.merge(b), lambda s: s.mode(), "DOUBLE", math.nan)
    if name == "histogram":
        return AggSemantics(lambda a, b: a + b,
                            lambda s: [float(x) for x in s], "DOUBLE_ARRAY", [])
    if name in ("stddevpop", "stddevsamp", "varpop", "varsamp"):
        return AggSemantics(_merge3, _var_finalize(name), "DOUBLE", math.nan)
    if name in ("skewness", "kurtosis"):
        return AggSemantics(_merge_tuple, _moments_finalize(name), "DOUBLE", math.nan)
    if name in ("covarpop", "covarsamp", "corr"):
        return AggSemantics(_merge_tuple, _covar_finalize(name), "DOUBLE", math.nan)
    if name == "booland":
        # empty state is the AND identity (True) on both engines
        return AggSemantics(lambda a, b: a and b, bool, "BOOLEAN", True)
    if name in ("boolor", "boolagg"):
        return AggSemantics(lambda a, b: a or b, bool, "BOOLEAN", False)
    if name in ("exprmin", "firstwithtime"):
        return AggSemantics(_merge_maybe(lambda a, b: a if a[0] <= b[0] else b),
                            lambda s: None if s is None else s[1], "OBJECT", None)
    if name in ("exprmax", "lastwithtime"):
        return AggSemantics(_merge_maybe(lambda a, b: a if a[0] >= b[0] else b),
                            lambda s: None if s is None else s[1], "OBJECT", None)
    if name in ("arrayagg", "listagg"):
        distinct = len(extra) > 1 and bool(extra[1])
        dtype = str(extra[0]).upper() if extra else "DOUBLE"

        def fin(s, _d=distinct):
            vals = list(dict.fromkeys(s)) if _d else list(s)
            return vals

        return AggSemantics(lambda a, b: a + b, fin, f"{dtype}_ARRAY", [])
    raise UnsupportedQueryError(f"aggregation {name} not implemented")


# ---------------------------------------------------------------------------
# Device lowering
# ---------------------------------------------------------------------------


# dense occupancy ceiling shared with the planner's mode selection
# (plan.DENSE_GROUP_LIMIT aliases this)
DENSE_GROUP_LIMIT = 1 << 21


class AggPlanContext:
    """Planner callback surface used by lowerings to register device ops."""

    def __init__(self):
        self.ops: list[ir.AggOp] = []
        # group cardinality product, set by the planner before lowering —
        # approximate aggs use it to bound their occupancy matrices
        self.group_card_hint = 1

    def add_op(self, op: ir.AggOp) -> int:
        """Register a primitive op, dedup'd; returns its kernel output index
        (output 0 is the group count)."""
        if op in self.ops:
            return 1 + self.ops.index(op)
        self.ops.append(op)
        return len(self.ops)

    # provided by SegmentPlanner (engine/plan.py):
    def value_expr(self, e: ExpressionContext) -> ir.ValueExpr:  # pragma: no cover
        raise NotImplementedError

    def mv_reduce_expr(self, e: ExpressionContext, op: str):  # pragma: no cover
        """(vexpr, vmin, vmax) or None — planners without MV support fall
        back to host."""
        return None

    # advanced null handling hooks (SegmentPlanner overrides; the defaults
    # are the basic-mode behavior)
    null_handling = False

    def agg_operand(self, e: ExpressionContext, identity):
        return self.value_expr(e)

    def nonnull_count_op(self, e: ExpressionContext) -> int:
        return 0

    def _null_cond_for(self, e: ExpressionContext):
        return None

    def dict_info(self, e: ExpressionContext, sv_only: bool = False):  # pragma: no cover
        raise NotImplementedError

    def col_meta(self, e: ExpressionContext):
        """Column metadata for a plain identifier, else None (feeds
        storage-aware lowerings like the f32 shadow-plane histogram)."""
        return None

    def col_minmax(self, e: ExpressionContext):  # pragma: no cover
        raise NotImplementedError

    def param(self, value) -> int:  # pragma: no cover
        raise NotImplementedError


_HIST_BINS = 2048  # fixed-bin device histogram resolution for raw columns
# digest compression for histogram-fed device digests: squeezing 2048
# weighted bins into the default ~100 centroids compounds the binning
# error (observed 1.2% drift vs the host's value-fed digest)
_TDIGEST_COMPRESSION = 500


def _mul(a: ir.ValueExpr, b: ir.ValueExpr) -> ir.ValueExpr:
    return ir.Bin("mul", a, b)


def _lower_mv_value_agg(ctx: AggPlanContext, name: str, label: str,
                        sem: AggSemantics, arg: ExpressionContext) -> LoweredAgg:
    """SUMMV-family: the MV column row-reduces to one value per doc
    (ir.MvLutReduce), then rides the standard scalar agg kernels. Host
    semantics flatten all entries of matched docs — identical totals."""

    def op(kind: str) -> int:
        if ctx._null_cond_for(arg) is not None:
            raise UnsupportedQueryError(
                f"{name} over nullable {arg} with enableNullHandling "
                "runs on the host engine")
        r = ctx.mv_reduce_expr(arg, kind)
        if r is None:
            raise UnsupportedQueryError(
                f"{name} on {arg} has no device MV form (host path)")
        ve, vmin, vmax = r
        agg_kind = "sum" if kind in ("sum", "count") else kind
        return ctx.add_op(ir.AggOp(agg_kind, vexpr=ve, vmin=vmin, vmax=vmax))

    if name == "countmv":
        i = op("count")
        spec, tag = VEC_RECIPES["count"]
        return LoweredAgg(
            label, sem, lambda outs, g: int(outs[i][g]),
            vec=VecAgg(spec, lambda outs, gids: (outs[i][gids],), tag))
    if name in ("summv", "minmv", "maxmv"):
        i = op(name[:-2])
        spec, tag = VEC_RECIPES[name[:-2]]
        return LoweredAgg(
            label, sem, lambda outs, g: float(outs[i][g]),
            vec=VecAgg(spec,
                       lambda outs, gids: (outs[i][gids].astype(float),), tag))
    if name == "minmaxrangemv":
        i_min, i_max = op("min"), op("max")
        spec, tag = VEC_RECIPES["minmaxrange"]
        return LoweredAgg(
            label, sem,
            lambda outs, g: (float(outs[i_min][g]), float(outs[i_max][g])),
            vec=VecAgg(spec,
                       lambda outs, gids: (outs[i_min][gids].astype(float),
                                           outs[i_max][gids].astype(float)),
                       tag))
    # avgmv: (sum of entries, COUNT OF ENTRIES — not docs)
    i_s, i_c = op("sum"), op("count")
    spec, tag = VEC_RECIPES["avg"]
    return LoweredAgg(
        label, sem,
        lambda outs, g: (float(outs[i_s][g]), int(outs[i_c][g])),
        vec=VecAgg(spec,
                   lambda outs, gids: (outs[i_s][gids].astype(float),
                                       outs[i_c][gids]), tag))


_FILTERABLE = frozenset(("count", "sum", "min", "max", "avg", "minmaxrange"))


def _count_op(ctx: AggPlanContext, arg, cond) -> int:
    """Kernel output index for a COUNT under null handling and/or a FILTER
    clause; 0 (the shared per-group doc count) when neither applies.
    add_op dedups, so COUNT(x) FILTER(c) and AVG(x) FILTER(c) share one
    op."""
    ncond = ctx._null_cond_for(arg) if arg is not None else None
    if cond is None and ncond is None:
        return 0
    one = ir.ConstParam(ctx.param(np.int32(1)))
    zero = ir.ConstParam(ctx.param(np.int32(0)))
    base = one if ncond is None else ir.Where(ncond, zero, one)
    ve = base if cond is None else ir.Where(cond, base, zero)
    return ctx.add_op(ir.AggOp("sum", vexpr=ve, vmin=0, vmax=1))


def _scalar_op(ctx: AggPlanContext, kind: str, arg, cond) -> int:
    """Kernel output index for a sum/min/max reduction over ``arg`` with
    null handling (agg_operand identity wrap) and an optional FILTER
    clause composed on top."""
    nullable = ctx._null_cond_for(arg) is not None
    if kind == "sum":
        bounds = _int_bounds(ctx, arg)
        if bounds and (nullable or cond is not None):
            # identity rows contribute 0
            bounds = {"vmin": min(0, bounds["vmin"]),
                      "vmax": max(0, bounds["vmax"])}
        ve = ctx.agg_operand(arg, 0)
        if cond is not None:
            ve = ir.Where(cond, ve, ir.ConstParam(ctx.param(np.int64(0))))
        return ctx.add_op(ir.AggOp("sum", vexpr=ve, **bounds))
    # min / max: identity rows need ±inf, so compare in f64
    ident_tok = "inf" if kind == "min" else "-inf"
    bounds = {} if (nullable or cond is not None) else _int_bounds(ctx, arg)
    ve = ctx.agg_operand(arg, ident_tok)
    if cond is not None:
        inf = np.inf if kind == "min" else -np.inf
        ve = ir.Where(cond, ir.Cast(ve, "DOUBLE"),
                      ir.ConstParam(ctx.param(np.float64(inf))))
    return ctx.add_op(ir.AggOp(kind, vexpr=ve, **bounds))


def lower_aggregation(ctx: AggPlanContext, expr: ExpressionContext,
                      _cond=None, _label=None) -> LoweredAgg:
    fn = expr.function
    if fn.name == "filter":
        # AGG(x) FILTER (WHERE cond) — reference
        # FilteredAggregationFunction: rows failing the clause contribute
        # the agg identity. The clause lowers through the PREDICATE path
        # (dict-id LUTs, intervals, index masks — and 3VL under null
        # handling), bridged into value space.
        inner, cond_expr = fn.arguments
        try:
            from ..query.converter import (FilterConversionError,
                                           filter_from_expression)

            cond = ir.FilterVal(ctx.lower_filter(
                filter_from_expression(cond_expr)))
        except (FilterConversionError, UnsupportedQueryError, AttributeError):
            cond = ctx.value_expr(cond_expr)  # boolean plane
            ncond = ctx._null_cond_for(cond_expr)
            if ncond is not None:  # 3VL: a null clause input is false
                cond = ir.Bin("and", cond, ir.Un("not", ncond))
        return lower_aggregation(ctx, inner, _cond=cond, _label=str(expr))
    raw_name, args = fn.name, fn.arguments
    label = _label or str(expr)
    data, extra = split_args(fn)
    name, extra = canonicalize(raw_name, extra)
    sem = get_semantics(name, extra)
    if _cond is not None and name not in _FILTERABLE:
        raise UnsupportedQueryError(
            f"FILTER clause over {name} has no device form (host path)")

    def cond_wrap(ve: ir.ValueExpr, ident: ir.ValueExpr) -> ir.ValueExpr:
        return ve if _cond is None else ir.Where(_cond, ve, ident)

    if name == "count":
        # advanced null handling counts non-null rows; a FILTER clause
        # counts clause-passing rows (composable)
        i = _count_op(ctx, data[0] if data else None, _cond)
        spec, tag = VEC_RECIPES["count"]
        return LoweredAgg(
            label, sem, lambda outs, g: int(outs[i][g]),
            vec=VecAgg(spec, lambda outs, gids: (outs[i][gids],), tag))

    if name in ("sum", "min", "max"):
        i = _scalar_op(ctx, name, data[0], _cond)
        spec, tag = VEC_RECIPES[name]
        return LoweredAgg(
            label, sem, lambda outs, g: float(outs[i][g]),
            vec=VecAgg(spec,
                       lambda outs, gids, _i=i: (outs[_i][gids].astype(float),),
                       tag))

    if name in ("countmv", "summv", "minmv", "maxmv", "avgmv", "minmaxrangemv"):
        return _lower_mv_value_agg(ctx, name, label, sem, data[0])

    if name == "minmaxrange":
        i_min = _scalar_op(ctx, "min", data[0], _cond)
        i_max = _scalar_op(ctx, "max", data[0], _cond)
        spec, tag = VEC_RECIPES["minmaxrange"]
        return LoweredAgg(
            label, sem,
            lambda outs, g: (float(outs[i_min][g]), float(outs[i_max][g])),
            vec=VecAgg(spec,
                       lambda outs, gids: (outs[i_min][gids].astype(float),
                                           outs[i_max][gids].astype(float)),
                       tag))

    if name == "avg":
        i = _scalar_op(ctx, "sum", data[0], _cond)
        # divide by the rows that CONTRIBUTED (non-null ∩ clause-passing)
        c = _count_op(ctx, data[0], _cond)
        spec, tag = VEC_RECIPES["avg"]
        return LoweredAgg(
            label, sem,
            lambda outs, g: (float(outs[i][g]), int(outs[c][g])),
            vec=VecAgg(spec,
                       lambda outs, gids, _i=i, _c=c: (
                           outs[_i][gids].astype(float), outs[_c][gids]),
                       tag))

    # branches below don't have device null-skipping forms; under advanced
    # null handling a nullable operand routes to the host engine (which
    # drops null rows before building states)
    for a in data:
        if ctx._null_cond_for(a) is not None:
            raise UnsupportedQueryError(
                f"{name} over nullable {a} with enableNullHandling "
                "runs on the host engine")

    if name in ("distinctcount", "distinctcountbitmap", "segmentpartitioneddistinctcount",
                "distinctsum", "distinctavg"):
        i, dictionary, card = _occupancy_op(ctx, data[0], name)
        numeric = name in ("distinctsum", "distinctavg")

        def state(ids, _d=dictionary, _numeric=numeric):
            sel = _d.values[ids]
            if _numeric:
                return frozenset(float(v) for v in sel)
            return frozenset(sel.tolist())

        def extract(outs, g, _i=i, _c=card, _state=state):
            return _state(_occ_ids(outs, _i, g, _c))

        return LoweredAgg(label, sem, extract,
                          prepare=_occ_prepare(i, card, state))

    if name in _HLL_FNS and not name.endswith("mv"):
        i, dictionary, card = _occupancy_op(ctx, data[0], name)
        log2m = int(extra[0]) if extra else 12

        def state(ids, _d=dictionary, _m=log2m):
            return HyperLogLog(_m).add_values(_d.values[ids])

        def extract(outs, g, _i=i, _c=card, _state=state):
            return _state(_occ_ids(outs, _i, g, _c))

        return LoweredAgg(label, sem, extract,
                          prepare=_occ_prepare(i, card, state))

    if name in _THETA_FNS:
        i, dictionary, card = _occupancy_op(ctx, data[0], name)

        def state(ids, _d=dictionary):
            return ThetaSketch().add_values(_d.values[ids])

        def extract(outs, g, _i=i, _c=card, _state=state):
            return _state(_occ_ids(outs, _i, g, _c))

        return LoweredAgg(label, sem, extract,
                          prepare=_occ_prepare(i, card, state))

    if name in ("distinctcountsmart", "distinctcountsmarthll"):
        i, dictionary, card = _occupancy_op(ctx, data[0], name)

        def state(ids, _d=dictionary):
            return SmartDistinctSet().add_values(_d.values[ids])

        def extract(outs, g, _i=i, _c=card, _state=state):
            return _state(_occ_ids(outs, _i, g, _c))

        return LoweredAgg(label, sem, extract,
                          prepare=_occ_prepare(i, card, state))

    if name in ("percentile", "mode"):
        i, dictionary = _value_hist_op(ctx, data[0], name)
        if not _numeric_dictionary(dictionary):
            raise UnsupportedQueryError(f"{name} requires a numeric column")

        def extract(outs, g, _i=i, _d=dictionary):
            row = outs[_i][g]
            nz = np.nonzero(row)[0]
            return ValueHist.from_arrays(_d.values[nz], row[nz])

        return LoweredAgg(label, sem, extract)

    if name in _PCT_DIGEST and not name.endswith("mv"):
        info = ctx.dict_info(data[0], sv_only=True)
        # exact value-hist only while groups × dict-card fits the dense
        # table; beyond it a high-card column (e.g. cent-rounded fares)
        # would otherwise reject the device path entirely. These are
        # APPROXIMATE functions by contract — the fixed-bin histogram's
        # quantile error ≤ (max-min)/bins stays inside the family's
        # tolerance (reference PercentileTDigestAggregationFunction is
        # itself a bounded-error sketch).
        if info is not None and _numeric_dictionary(info[2]) \
                and ctx.group_card_hint * info[1] <= DENSE_GROUP_LIMIT:
            i, dictionary = _value_hist_op(ctx, data[0], name)

            def extract(outs, g, _i=i, _d=dictionary):
                row = outs[_i][g]
                nz = np.nonzero(row)[0]
                return ValueHist.from_arrays(
                    _d.values[nz], row[nz]).to_tdigest(
                    compression=_TDIGEST_COMPRESSION)

            return LoweredAgg(label, sem, extract)
        # raw numeric column (or an occupancy-capped dict column)
        mm = ctx.col_minmax(data[0])
        if mm is None:
            raise UnsupportedQueryError(f"{name} needs numeric column stats")
        lo, hi = float(mm[0]), float(mm[1])
        if hi <= lo:
            hi = lo + 1.0
        pct = _pct(extra)

        from ..ops import mxu_groupby

        bins = min(64, max(1, (mxu_groupby.MAX_GROUPS - 1)
                           // max(1, ctx.group_card_hint)))
        if bins >= 8 and mxu_groupby.supports(
                ctx.group_card_hint * bins + 1, 1):
            # two-level adaptive device histogram (MXU count passes; see
            # kernels "hist_adaptive"): quantile resolution (hi-lo)/bins^2
            # concentrated around the asked percentile, 2*bins+1 output
            # words per group instead of _HIST_BINS.
            # Plain raw FLOAT/DOUBLE identifiers bin from a PRE-REBASED
            # f32 plane ((v - col_min) in HBM, half the f64 read
            # bandwidth); lo from col stats == the rebase base, so the
            # kernel's offsets line up exactly.
            vexpr = prebased = None
            e0 = data[0]
            m = ctx.col_meta(e0)
            if m is not None and m.encoding == "RAW" and m.single_value \
                    and str(m.data_type) in ("FLOAT", "DOUBLE"):
                vexpr = ir.Col(ctx.slot(e0.identifier, "rawf32r"))
                prebased = True
            if vexpr is None:
                # registering value_expr's raw/dict slots only on this
                # branch keeps the f64 plane OUT of the query's HBM
                # residency when the f32 shadow serves it alone
                vexpr, prebased = ctx.value_expr(data[0]), False
            i = ctx.add_op(ir.AggOp(
                "hist_adaptive", vexpr=vexpr, bins=bins,
                lo_param=ctx.param(np.float64(lo)),
                hi_param=ctx.param(np.float64(hi)), pct=float(pct),
                prebased=prebased))
            w1 = (hi - lo) / bins
            c1 = lo + (np.arange(bins) + 0.5) * w1

            def extract(outs, g, _i=i, _b=bins, _lo=lo, _w1=w1, _c1=c1):
                row = outs[_i][g]
                h1 = row[:_b].astype(np.float64)
                h2 = row[_b:2 * _b].astype(np.float64)
                bstar = int(row[2 * _b])
                # coarse weights minus the refined bucket, plus the
                # refined sub-bins centered inside it
                w = h1.copy()
                w[bstar] = 0.0
                lo_g = _lo + bstar * _w1
                c2 = lo_g + (np.arange(_b) + 0.5) * (_w1 / _b)
                d = TDigest(_TDIGEST_COMPRESSION).add_weighted(_c1, w)
                return d.add_weighted(c2, h2)

            return LoweredAgg(label, sem, extract)

        # fixed-bin device histogram → weighted t-digest
        i = ctx.add_op(ir.AggOp(
            "hist_fixed", vexpr=ctx.value_expr(data[0]), bins=_HIST_BINS,
            lo_param=ctx.param(np.float64(lo)), hi_param=ctx.param(np.float64(hi))))
        centers = lo + (np.arange(_HIST_BINS) + 0.5) * (hi - lo) / _HIST_BINS

        def extract(outs, g, _i=i, _c=centers):
            return TDigest(_TDIGEST_COMPRESSION).add_weighted(
                _c, outs[_i][g].astype(np.float64))

        return LoweredAgg(label, sem, extract)

    if name == "histogram":
        if len(extra) != 3:
            raise UnsupportedQueryError("histogram(col, lower, upper, numBins)")
        lo, hi, bins = float(extra[0]), float(extra[1]), int(extra[2])
        if hi <= lo or bins <= 0:
            raise UnsupportedQueryError("histogram requires upper > lower and numBins > 0")
        i = ctx.add_op(ir.AggOp(
            "hist_fixed", vexpr=ctx.value_expr(data[0]), bins=bins,
            lo_param=ctx.param(np.float64(lo)), hi_param=ctx.param(np.float64(hi))))
        return LoweredAgg(label, sem,
                          lambda outs, g: outs[i][g].astype(np.float64))

    if name in ("stddevpop", "stddevsamp", "varpop", "varsamp"):
        i_s = ctx.add_op(ir.AggOp("sum", vexpr=ctx.value_expr(data[0])))
        i_q = ctx.add_op(ir.AggOp("sumsq", vexpr=ctx.value_expr(data[0])))
        return LoweredAgg(
            label, sem,
            lambda outs, g: (int(outs[0][g]), float(outs[i_s][g]), float(outs[i_q][g])))

    if name in ("skewness", "kurtosis"):
        # cast before powering: int32 column planes overflow at v**4
        v = ir.Cast(ctx.value_expr(data[0]), "DOUBLE")
        i1 = ctx.add_op(ir.AggOp("sum", vexpr=v))
        i2 = ctx.add_op(ir.AggOp("sumsq", vexpr=v))
        i3 = ctx.add_op(ir.AggOp("sum", vexpr=_mul(_mul(v, v), v)))
        i4 = ctx.add_op(ir.AggOp("sum", vexpr=_mul(_mul(v, v), _mul(v, v))))
        return LoweredAgg(
            label, sem,
            lambda outs, g: (int(outs[0][g]), float(outs[i1][g]), float(outs[i2][g]),
                             float(outs[i3][g]), float(outs[i4][g])))

    if name in ("covarpop", "covarsamp", "corr"):
        x = ir.Cast(ctx.value_expr(data[0]), "DOUBLE")
        y = ir.Cast(ctx.value_expr(data[1]), "DOUBLE")
        ix = ctx.add_op(ir.AggOp("sum", vexpr=x))
        iy = ctx.add_op(ir.AggOp("sum", vexpr=y))
        ixy = ctx.add_op(ir.AggOp("sum", vexpr=_mul(x, y)))
        ixx = ctx.add_op(ir.AggOp("sumsq", vexpr=x))
        iyy = ctx.add_op(ir.AggOp("sumsq", vexpr=y))
        return LoweredAgg(
            label, sem,
            lambda outs, g: (int(outs[0][g]), float(outs[ix][g]), float(outs[iy][g]),
                             float(outs[ixy][g]), float(outs[ixx][g]), float(outs[iyy][g])))

    if name in ("booland", "boolor", "boolagg"):
        # booleans are 0/1 ints: AND = min (empty→+inf→True), OR = max (empty→-inf→False)
        kind = "min" if name == "booland" else "max"
        i = ctx.add_op(ir.AggOp(kind, vexpr=ctx.value_expr(data[0])))
        return LoweredAgg(label, sem, lambda outs, g: bool(outs[i][g] > 0.5))

    raise UnsupportedQueryError(f"aggregation {name} not yet lowered to device")


def _int_bounds(ctx, arg) -> dict:
    """Static integer bounds for the 32-bit kernel fast paths (see
    kernels._fits_i32/_segment_sum_exact_i64); {} when unknown or
    non-integer. QUANTIZED to power-of-two envelopes — the bounds are static
    jit args, and per-segment exact min/max would compile a fresh kernel
    per segment."""
    mm = ctx.col_minmax(arg)
    if mm is None:
        return {}
    lo, hi = mm
    if isinstance(lo, (int, np.integer)) and isinstance(hi, (int, np.integer)):
        lo, hi = int(lo), int(hi)
        qhi = (1 << max(hi, 1).bit_length()) - 1 if hi >= 0 else 0
        qlo = 0 if lo >= 0 else -(1 << max(-lo, 1).bit_length())
        return {"vmin": qlo, "vmax": qhi}
    return {}


def _occupancy_op(ctx: AggPlanContext, arg: ExpressionContext, name: str):
    info = ctx.dict_info(arg, sv_only=True)
    if info is None:
        raise UnsupportedQueryError(
            f"{name} needs a dict-encoded SV column: {arg}")
    ids_slot, card, dictionary = info
    i = ctx.add_op(ir.AggOp("distinct_bitmap", ids_slot=ids_slot, card=card))
    return i, dictionary, card


def _occ_row_ids(o: np.ndarray, g) -> np.ndarray:
    """Dict ids present in group g, from either occupancy form:
    - dense: (groups, card) boolean matrix → nonzero of row g
    - sparse: (slots, W) uint32 id bitmap words — little-endian bit j of
      word w encodes dict id w*32+j"""
    if o.dtype == np.uint32:
        return np.nonzero(np.unpackbits(
            np.ascontiguousarray(o[g]).view(np.uint8),
            bitorder="little"))[0]
    return np.nonzero(o[g])[0]


def _occ_ids(outs, i, g, card) -> np.ndarray:
    return _occ_row_ids(outs[i], g)


def _occ_prepare(i: int, card: int, state_fn):
    """Batch extractor for occupancy aggs; both forms decode row-wise
    (sparse bitmap rows are already per-slot).
    state_fn(ids: np.ndarray) builds the per-group state."""

    def prepare(outs):
        o = outs[i]
        return lambda g: state_fn(_occ_row_ids(o, g))

    return prepare


def _value_hist_op(ctx: AggPlanContext, arg: ExpressionContext, name: str):
    info = ctx.dict_info(arg, sv_only=True)
    if info is None:
        raise UnsupportedQueryError(
            f"{name} needs a dict-encoded SV column: {arg}")
    ids_slot, card, dictionary = info
    i = ctx.add_op(ir.AggOp("value_hist", ids_slot=ids_slot, card=card))
    return i, dictionary


def _numeric_dictionary(d) -> bool:
    return np.asarray(d.values).dtype.kind in ("i", "u", "f")


# ---------------------------------------------------------------------------
# Host (numpy) states — used by the fallback engine and the test oracle
# ---------------------------------------------------------------------------


def host_state_full(name: str, cols: list, extra: tuple):
    """Per-group intermediate state from the group's (already filtered) raw
    value arrays — one array per data argument. Must produce states
    mergeable/finalizable by get_semantics — i.e. identical shape to the
    device path's LoweredAgg.extract."""
    name, extra = canonicalize(name, extra)
    values = cols[0] if cols else None
    n = 0 if values is None else len(values)

    if name in ("count", "countmv"):
        return n
    if values is None:
        raise UnsupportedQueryError(f"{name} requires an argument")

    if name in ("sum", "summv"):
        return float(np.sum(values)) if n else 0.0
    if name == "sumprecision":
        # exact decimal sum (reference SumPrecisionAggregationFunction's
        # BigDecimal); column may be stored as strings
        return sum((Decimal(str(v)) for v in values), Decimal(0))
    if name in ("min", "minmv"):
        return float(np.min(values)) if n else math.inf
    if name in ("max", "maxmv"):
        return float(np.max(values)) if n else -math.inf
    if name in ("minmaxrange", "minmaxrangemv"):
        return (float(np.min(values)), float(np.max(values))) if n else (math.inf, -math.inf)
    if name in ("avg", "avgmv"):
        return (float(np.sum(values)), n)
    if name in _EXACT_DISTINCT:
        return frozenset(np.unique(values).tolist())
    if name in ("distinctsum", "distinctavg"):
        return frozenset(float(v) for v in np.unique(values))
    if name in _HLL_FNS:
        log2m = int(extra[0]) if extra else 12
        return HyperLogLog(log2m).add_values(np.unique(values))
    if name in _THETA_FNS:
        return ThetaSketch().add_values(np.unique(values))
    if name in ("distinctcountsmart", "distinctcountsmarthll"):
        return SmartDistinctSet().add_values(np.unique(values))
    if name in _PCT_EXACT or name == "mode":
        if np.asarray(values).dtype.kind not in ("i", "u", "f", "b"):
            raise UnsupportedQueryError(f"{name} requires a numeric column")
        return ValueHist.from_values(values)
    if name in _PCT_DIGEST:
        return TDigest().add_values(np.asarray(values, dtype=np.float64))
    if name == "histogram":
        if len(extra) != 3:
            raise UnsupportedQueryError("histogram(col, lower, upper, numBins)")
        lo, hi, bins = float(extra[0]), float(extra[1]), int(extra[2])
        if hi <= lo or bins <= 0:
            raise UnsupportedQueryError("histogram requires upper > lower and numBins > 0")
        v = np.asarray(values, dtype=np.float64)
        counts, _ = np.histogram(v[(v >= lo) & (v <= hi)], bins=bins, range=(lo, hi))
        return counts.astype(np.float64)
    if name in ("stddevpop", "stddevsamp", "varpop", "varsamp"):
        v = np.asarray(values, dtype=np.float64)
        return (n, float(v.sum()), float((v * v).sum()))
    if name in ("skewness", "kurtosis"):
        v = np.asarray(values, dtype=np.float64)
        return (n, float(v.sum()), float((v**2).sum()), float((v**3).sum()),
                float((v**4).sum()))
    if name in ("covarpop", "covarsamp", "corr"):
        x = np.asarray(cols[0], dtype=np.float64)
        y = np.asarray(cols[1], dtype=np.float64)
        return (n, float(x.sum()), float(y.sum()), float((x * y).sum()),
                float((x * x).sum()), float((y * y).sum()))
    if name == "booland":
        return bool(np.all(values)) if n else True
    if name in ("boolor", "boolagg"):
        return bool(np.any(values)) if n else False
    if name in ("exprmin", "exprmax"):
        # EXPR_MIN(projectionCol, measuringCol)
        proj, measure = cols[0], cols[1]
        if n == 0:
            return None
        idx = int(np.argmin(measure)) if name == "exprmin" else int(np.argmax(measure))
        return (_item(measure[idx]), _item(proj[idx]))
    if name in ("firstwithtime", "lastwithtime"):
        data_col, time_col = cols[0], cols[1]
        if n == 0:
            return None
        idx = int(np.argmin(time_col)) if name == "firstwithtime" else int(np.argmax(time_col))
        return (_item(time_col[idx]), _item(data_col[idx]))
    if name in ("arrayagg", "listagg"):
        return tuple(_item(v) for v in values)
    raise UnsupportedQueryError(f"aggregation {name} not implemented on host")


def host_state(name: str, values: Optional[np.ndarray], extra: tuple = ()):
    """Single-data-argument convenience wrapper (MV flatten path)."""
    return host_state_full(name, [values] if values is not None else [], extra)


def _item(v):
    return v.item() if isinstance(v, np.generic) else v
