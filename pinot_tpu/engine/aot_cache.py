"""AOT executable cache — compile-free cold starts across restarts.

Reference analogue: none in Pinot (JVM servers JIT-warm per process); the
problem is TPU-specific — the first query of every executable family
after a restart or traffic shift eats a full XLA compile in its tail.
This module persists compiled family programs via JAX AOT serialization
(``jax.export``): on a compile-guard miss the freshly-compiled family is
exported (StableHLO) and written to a byte-budgeted on-disk cache keyed
by the PR-11 ``family_fingerprint`` plus an environment tag (jaxlib
version, device kind/platform, mesh shape). At segment load / prefetch
time a table's top families are pre-warmed: deserialize → AOT-compile
off the serving path → install a ready callable the dispatcher picks up
with one dict lookup, so the first QUERY of the family reports
``numCompiles == 0``.

Safety contract: a persisted artifact is refused — and the dispatcher
falls back to a fresh compile — on any mismatch (jaxlib/device/mesh env
tag, payload checksum, deserialization failure) or runtime call failure.
Never a wrong answer, never a crash; the worst case is the compile that
would have happened anyway.

Cost discipline: the hot dispatch path pays one ``if AOT_READY:`` truth
test (empty dict → falsy) when the cache is cold/disabled, one dict
lookup when warm. Export/persist work happens only next to a real XLA
compile; deserialize+compile work happens only at prewarm time.

Knobs: ``PINOT_TPU_AOT_CACHE_DIR`` (unset = disabled),
``PINOT_TPU_AOT_CACHE_MB`` (byte budget, default 256),
``PINOT_TPU_AOT_PREWARM_BUDGET_MS`` (expected-compile-cost budget per
prewarm, default 5000 — ranked by live registry cost×recency score),
``PINOT_TPU_AOT_PREWARM_TOP_K`` (optional flat-count override of the
budget).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import pickle
import threading
import time

import numpy as np

from ..spi import faults

log = logging.getLogger(__name__)

_FORMAT_VERSION = 1

# gkey → ready callable (an AOT-compiled jax.stages.Compiled). Plain dict:
# reads are GIL-atomic; all writes happen under _LOCK. The dispatcher
# (engine/executor.py) guards with `if AOT_READY:` so the disabled/cold
# case costs a falsy truth test.
AOT_READY: dict = {}

_LOCK = threading.Lock()
_WARN_ONCE: set = set()

# thread-local table attribution: execute_segments stamps the current
# table so persisted artifacts can be prewarmed per table later
_TLS = threading.local()


def set_current_table(table) -> None:
    _TLS.table = table


def current_table():
    return getattr(_TLS, "table", None)


def enabled() -> bool:
    return bool(os.environ.get("PINOT_TPU_AOT_CACHE_DIR"))


def cache_dir():
    return os.environ.get("PINOT_TPU_AOT_CACHE_DIR")


def _budget_bytes() -> int:
    return int(float(os.environ.get("PINOT_TPU_AOT_CACHE_MB", 256))
               * 1024 * 1024)


def env_tag() -> dict:
    """The executable-validity environment: a persisted artifact is only
    ever deserialized under the exact (jax/jaxlib version, device kind,
    platform, local mesh shape) it was exported under."""
    import jax
    import jaxlib

    dev = jax.devices()[0]
    from ..parallel.mesh import mesh_device_count

    return {
        "jaxlib": f"{jax.__version__}/{jaxlib.__version__}",
        "deviceKind": str(dev.device_kind),
        "platform": str(dev.platform),
        "meshShape": [int(mesh_device_count())],
    }


def _env_hash(tag: dict) -> str:
    return hashlib.sha256(
        json.dumps(tag, sort_keys=True).encode()).hexdigest()


def _artifact_name(fingerprint: str, tag: dict) -> str:
    return f"{fingerprint[:24]}-{_env_hash(tag)[:8]}.aot"


# -- manifest -----------------------------------------------------------------


def _manifest_path(d: str) -> str:
    return os.path.join(d, "manifest.json")


def _load_manifest(d: str) -> dict:
    try:
        with open(_manifest_path(d)) as f:
            m = json.load(f)
        if isinstance(m, dict) and isinstance(m.get("files"), dict):
            return m
    except (OSError, ValueError):
        pass
    return {"files": {}}


def _save_manifest(d: str, manifest: dict) -> None:
    tmp = _manifest_path(d) + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, _manifest_path(d))


# -- persist (cold path, next to a real XLA compile) --------------------------


def _specs_of(example) -> tuple:
    """ShapeDtypeStruct pytree mirroring the (arrays, params, num_docs)
    example — shapes/dtypes read from attributes, never materializing a
    device array on host."""
    import jax

    def spec(a):
        return jax.ShapeDtypeStruct(tuple(np.shape(a)),
                                    np.dtype(getattr(a, "dtype", None)
                                             or np.asarray(a).dtype))

    arrays, params, num_docs = example
    return (tuple(spec(a) for a in arrays),
            tuple(spec(p) for p in params),
            spec(num_docs))


def _specs_json(specs) -> list:
    arrays, params, num_docs = specs
    enc = lambda s: [list(s.shape), str(np.dtype(s.dtype))]  # noqa: E731
    return [[enc(s) for s in arrays], [enc(s) for s in params],
            enc(num_docs)]


def _specs_from_json(j) -> tuple:
    import jax

    dec = lambda e: jax.ShapeDtypeStruct(  # noqa: E731
        tuple(e[0]), np.dtype(e[1]))
    return (tuple(dec(e) for e in j[0]), tuple(dec(e) for e in j[1]),
            dec(j[2]))


def _family_fn(kind: str, program, padded: int, packed: bool, fused: str,
               lut_meta: tuple):
    """The (arrays, params, num_docs) closure over the family's statics —
    the exact computation the dispatcher runs, so a deserialized artifact
    is bit-identical to the fresh-compile path."""
    from ..ops import kernels

    if kind == "batch":
        def fn(arrays, params, num_docs):
            return kernels.run_program_batch(program, arrays, params,
                                             num_docs, padded, packed=packed)
    else:
        def fn(arrays, params, num_docs):
            return kernels.run_program(program, arrays, params, num_docs,
                                       padded, packed=packed, fused=fused,
                                       fused_lut_meta=lut_meta)
    return fn


def on_compile(gkey, fingerprint, compile_ms: float, family: dict,
               kind: str, program, padded: int, packed: bool = False,
               fused: str = "", lut_meta: tuple = (),
               example=None) -> bool:
    """Persist hook, called from the compile-registry cold path right
    after a fresh XLA compile. Exports the family executable and writes
    it to the on-disk cache if the CompileRegistry's cost×reuse ranking
    (score at compile time: the compile cost itself) wins the byte
    budget. Returns True when an artifact was written. Never raises."""
    if not enabled() or fingerprint is None or example is None:
        return False
    d = cache_dir()
    try:
        tag = env_tag()
        name = _artifact_name(fingerprint, tag)
        path = os.path.join(d, name)
        with _LOCK:
            manifest = _load_manifest(d)
            if name in manifest["files"] and os.path.exists(path):
                return False  # already persisted under this env
        import jax
        from jax import export as jax_export

        specs = _specs_of(example)
        fn = _family_fn(kind, program, padded, packed, fused, lut_meta)
        exported = jax_export.export(jax.jit(fn))(*specs)
        payload = exported.serialize()
        blob = pickle.dumps({
            "version": _FORMAT_VERSION,
            "fingerprint": fingerprint,
            "envTag": tag,
            "gkey": gkey,
            "argSpecs": _specs_json(specs),
            "payload": payload,
            "payloadSha": hashlib.sha256(payload).hexdigest(),
            "family": family,
            "table": current_table(),
            "score": round(float(compile_ms), 3),
        })
        with _LOCK:
            manifest = _load_manifest(d)
            if not _make_room(d, manifest, len(blob), float(compile_ms)):
                return False
            os.makedirs(d, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            manifest["files"][name] = {
                "bytes": len(blob),
                "table": current_table(),
                "fingerprint": fingerprint,
                "score": round(float(compile_ms), 3),
                "savedAtMs": int(time.time() * 1000),
            }
            _save_manifest(d, manifest)
        return True
    except Exception as e:
        _warn_once("persist", "AOT persist failed (%s: %s); family stays "
                   "jit-only", type(e).__name__, e)
        return False


def _make_room(d: str, manifest: dict, need: int, score: float) -> bool:
    """Evict lowest-score artifacts until ``need`` bytes fit the budget.
    Only artifacts scoring BELOW the incoming family are evictable —
    the CompileRegistry ranking decides what persists. Caller holds
    _LOCK."""
    budget = _budget_bytes()
    if need > budget:
        return False
    files = manifest["files"]
    total = sum(int(m.get("bytes", 0)) for m in files.values())
    if total + need <= budget:
        return True
    evictable = sorted(
        ((m.get("score", 0.0), name) for name, m in files.items()
         if float(m.get("score", 0.0)) < score))
    for _, name in evictable:
        try:
            os.unlink(os.path.join(d, name))
        except OSError:
            pass
        total -= int(files.pop(name).get("bytes", 0))
        if total + need <= budget:
            return True
    return total + need <= budget


# -- load / prewarm (off the serving path) ------------------------------------


def _refuse(reason: str, name: str):
    from ..spi.metrics import SERVER_METRICS, ServerMeter

    SERVER_METRICS.add_meter(ServerMeter.AOT_CACHE_MISSES)
    _warn_once(("refuse", reason), "AOT artifact %s refused (%s); falling "
               "back to fresh compile", name, reason)
    return None


def load_artifact(path: str, expect_tag: dict = None):
    """Deserialize + AOT-compile one artifact and install its ready
    callable. Returns the gkey on success, None on any refusal (corrupt
    file, checksum, env mismatch, deserialization failure). Never
    raises."""
    name = os.path.basename(path)
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return _refuse("unreadable", name)
    if faults.ACTIVE:
        data = faults.corrupt_at("aot.load", data, path=name)
    try:
        blob = pickle.loads(data)
        if blob.get("version") != _FORMAT_VERSION:
            return _refuse("format version", name)
        payload = blob["payload"]
        if hashlib.sha256(payload).hexdigest() != blob["payloadSha"]:
            return _refuse("payload checksum", name)
        tag = expect_tag if expect_tag is not None else env_tag()
        if blob["envTag"] != tag:
            mism = [k for k in tag if blob["envTag"].get(k) != tag[k]]
            return _refuse(f"env mismatch ({','.join(mism) or '?'})", name)
        import jax
        from jax import export as jax_export

        exported = jax_export.deserialize(bytearray(payload))
        specs = _specs_from_json(blob["argSpecs"])
        compiled = jax.jit(exported.call).lower(*specs).compile()
        gkey = blob["gkey"]
    except Exception as e:
        return _refuse(f"{type(e).__name__}: {e}", name)
    _install(gkey, compiled, blob["fingerprint"], blob.get("family") or {})
    return gkey


def _install(gkey, compiled, fingerprint: str, family: dict) -> None:
    """Make the family compile-free: ready callable for the dispatcher,
    compile-guard seeded so the first query counts numCompiles == 0, and
    the compile registry taught the gkey→fingerprint edge so warm
    dispatches keep registering without an IR walk."""
    from .compile_registry import COMPILE_REGISTRY
    from .executor import _GUARD

    with _LOCK:
        AOT_READY[gkey] = compiled
    _GUARD.note(gkey)
    COMPILE_REGISTRY.note_preloaded(gkey, fingerprint, family)


def _raw_table(name) -> str:
    """Normalize a type-suffixed internal name (``events_OFFLINE``) to the
    raw broker-facing name artifacts are stamped with, so segment-load
    prewarm (internal name) finds artifacts persisted at query time (raw
    name)."""
    s = str(name)
    for suffix in ("_OFFLINE", "_REALTIME"):
        if s.endswith(suffix):
            return s[: -len(suffix)]
    return s


def _budget_candidates(items: list) -> list:
    """Cost-budgeted prewarm order: rank families by the LIVE registry
    score when the fingerprint is tracked in this process (compile cost ×
    dispatch recency — a family hot NOW outranks one that was merely
    expensive once), falling back to the persisted manifest score, then
    admit best-first while the summed expected compile cost stays within
    PINOT_TPU_AOT_PREWARM_BUDGET_MS (greedy fill: a family too costly for
    the remaining budget is skipped, cheaper ones behind it may still fit).
    Always admits at least one family so a cold process warms its most
    valuable executable."""
    budget_ms = float(os.environ.get(
        "PINOT_TPU_AOT_PREWARM_BUDGET_MS", 5000.0))
    from .compile_registry import COMPILE_REGISTRY

    live = {fp: score for fp, score, _fam in COMPILE_REGISTRY.aot_priority()}
    ranked = sorted(
        ((live.get(m.get("fingerprint"), float(m.get("score", 0.0))),
          float(m.get("score", 0.0)), name) for name, m in items),
        reverse=True)
    out, spent = [], 0.0
    for _rank, cost_ms, name in ranked:
        if out and spent + cost_ms > budget_ms:
            continue
        out.append(name)
        spent += cost_ms
    return out


def prewarm_table(table, top_k: int = None) -> dict:
    """Deserialize + warm the table's top-scored persisted families
    (segment-load / prefetch hook). All compile cost lands HERE, off the
    serving path, timed as aotPrewarmMs. Admission is budgeted by expected
    compile cost (PINOT_TPU_AOT_PREWARM_BUDGET_MS) unless a flat count is
    forced via the top_k arg or PINOT_TPU_AOT_PREWARM_TOP_K."""
    if not enabled():
        return {"loaded": 0, "refused": 0}
    d = cache_dir()
    env_k = os.environ.get("PINOT_TPU_AOT_PREWARM_TOP_K")
    t0 = time.perf_counter()
    want = None if table is None else _raw_table(table)
    with _LOCK:
        manifest = _load_manifest(d)
        items = [(name, m) for name, m in manifest["files"].items()
                 if want is None or _raw_table(m.get("table")) == want]
    if top_k is not None or env_k:
        k = int(top_k if top_k is not None else env_k)
        cand = [name for _, name in sorted(
            ((float(m.get("score", 0.0)), name) for name, m in items),
            reverse=True)[:k]]
    else:
        cand = _budget_candidates(items)
    loaded = refused = 0
    tag = env_tag()
    for name in cand:
        if load_artifact(os.path.join(d, name), expect_tag=tag) is not None:
            loaded += 1
        else:
            refused += 1
    ms = round((time.perf_counter() - t0) * 1000, 3)
    if loaded or refused:
        from ..spi.metrics import SERVER_METRICS, ServerTimer

        SERVER_METRICS.update_timer(ServerTimer.AOT_PREWARM_MS, ms)
    return {"loaded": loaded, "refused": refused, "prewarmMs": ms}


def aot_call(gkey, arrays, params, num_docs):
    """Hot-path entry: run the family's ready executable if one is
    installed. Returns the output pytree, or None (caller falls back to
    the jit path). A runtime failure evicts the callable — the family
    quietly reverts to jit-compiled dispatch."""
    fn = AOT_READY.get(gkey)
    if fn is None:
        return None
    try:
        outs = fn(arrays, params, num_docs)
    except Exception as e:
        with _LOCK:
            AOT_READY.pop(gkey, None)
        _warn_once(("call", type(e).__name__),
                   "AOT executable call failed (%s: %s); reverting family "
                   "to jit dispatch", type(e).__name__, e)
        return None
    from ..spi.metrics import SERVER_METRICS, ServerMeter

    SERVER_METRICS.add_meter(ServerMeter.AOT_CACHE_HITS)
    return outs


def stats() -> dict:
    """Scrape-time rollup for /debug/compiles and tools."""
    if not enabled():
        return {"enabled": False, "ready": len(AOT_READY)}
    d = cache_dir()
    with _LOCK:
        manifest = _load_manifest(d)
    files = manifest["files"]
    return {
        "enabled": True,
        "dir": d,
        "ready": len(AOT_READY),
        "artifacts": len(files),
        "bytes": sum(int(m.get("bytes", 0)) for m in files.values()),
        "budgetBytes": _budget_bytes(),
    }


def reset() -> None:
    """Test helper: drop in-memory ready state (disk artifacts stay)."""
    with _LOCK:
        AOT_READY.clear()
        _WARN_ONCE.clear()


def _warn_once(key, msg, *args) -> None:
    if key in _WARN_ONCE:
        return
    _WARN_ONCE.add(key)
    log.warning(msg, *args)
