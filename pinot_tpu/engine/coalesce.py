"""Cross-query coalescing — continuous batching for concurrent OLAP.

Reference analogue: none in Pinot (the JVM engine scales concurrency
with threads); the shape comes from ragged paged attention serving
(PAPERS.md, arxiv 2604.15464): stack heterogeneous concurrent requests
into one padded device dispatch. Here the batch-family stack axis is
promoted from "segments of one query" to "(query, segment) slots of many
concurrent queries": in-flight queries that share a ``batch_family_key``
AND segment set rendezvous here, the first arrival (the leader) holds
the family open for an opt-in window, stacks every member's per-segment
param planes behind ONE vmapped ``run_program_batch`` dispatch, and
demuxes per-query row slices before combine. vmap gives each [S·Q] slot
exactly the solo kernel body, so coalesced results are bit-identical to
solo execution — per-query params (filter literals, limits) ride as
stacked param planes where the program is param-polymorphic, and
families that embed params in the IR share a Program (hence a family
key) only on exact match, so they coalesce only then.

Arming: the hold window (``PINOT_TPU_COALESCE_WINDOW_MS``, default 0 =
never hold) only arms for (table, family) pairs the traffic tracker has
seen repeat within its decay window — the PR-10 workload-tracker rollup
idiom at family granularity — so one-off queries never pay latency.
Joining an ALREADY-open group is always free and needs no arming. A
group closes early at ``PINOT_TPU_COALESCE_MAX_QUERIES`` members.

Safety: any leader failure (dispatch error, family mismatch, OOM) marks
the group failed and every member — leader included — falls back to its
own normal dispatch path. Never a wrong answer, never a stall beyond
the follower timeout. ``SET coalesce = false`` opts a query out; traced
queries never coalesce (spans must describe the query's own device
work).
"""

from __future__ import annotations

import logging
import os
import threading
import time

log = logging.getLogger(__name__)


def window_ms() -> float:
    """The opt-in hold window. 0 disables holds (and therefore group
    formation) entirely — the default, so single-query workloads and the
    tier-1 suite see the pre-coalescing serving path bit-for-bit."""
    try:
        return float(os.environ.get("PINOT_TPU_COALESCE_WINDOW_MS", 0.0))
    except ValueError:
        return 0.0


def _max_queries() -> int:
    try:
        return max(2, int(os.environ.get(
            "PINOT_TPU_COALESCE_MAX_QUERIES", 16)))
    except ValueError:
        return 16


# -- per-query thread-local accounting (mirrors executor dispatch counters) --

_TLS = threading.local()


def reset_coalesce_stats() -> None:
    _TLS.stats = [0, 0.0]  # [peer queries shared with, wait ms]


def coalesce_stats() -> tuple:
    s = getattr(_TLS, "stats", None)
    return (s[0], round(s[1], 3)) if s else (0, 0.0)


def _note_stats(peers: int, wait_ms: float) -> None:
    s = getattr(_TLS, "stats", None)
    if s is not None:
        s[0] += peers
        s[1] += wait_ms


# -- (table, family) traffic nomination --------------------------------------


class FamilyTraffic:
    """Decaying per-(table, family) query counter — the workload-tracker
    rollup (cluster/workload.py ``_Rollup``) applied at family
    granularity. ``armed`` nominates pairs whose decayed rate says repeat
    traffic exists, so the hold window only delays queries that have
    peers to wait for."""

    def __init__(self, half_life_s: float = None, min_traffic: float = None):
        self.half_life_s = float(
            half_life_s if half_life_s is not None else
            os.environ.get("PINOT_TPU_COALESCE_TRAFFIC_HALFLIFE_S", 10.0))
        self.min_traffic = float(
            min_traffic if min_traffic is not None else
            os.environ.get("PINOT_TPU_COALESCE_MIN_TRAFFIC", 2.0))
        self._lock = threading.Lock()
        self._counts: dict = {}  # (table, hash(family)) → [value, t]
        self._max = 4096

    def _decayed(self, slot, now: float) -> float:
        value, t = slot
        dt = now - t
        return value * (2.0 ** (-dt / self.half_life_s)) if dt > 0 else value

    def note(self, table, family_key) -> float:
        """Fold one sighting in; returns the decayed count AFTER it (the
        armed() threshold compares this, so the second query inside the
        half-life arms the pair)."""
        key = (table, hash(family_key))
        now = time.time()
        with self._lock:
            slot = self._counts.get(key)
            value = self._decayed(slot, now) + 1.0 if slot else 1.0
            self._counts[key] = [value, now]
            if len(self._counts) > self._max:
                # decayed-out entries first; bound the table like the
                # workload tracker does
                for k in sorted(self._counts,
                                key=lambda k: self._counts[k][1])[:256]:
                    del self._counts[k]
        return value

    def armed(self, table, family_key) -> bool:
        # threshold is min_traffic - 0.5: a prior sighting still worth
        # half a query (≤ one half-life old) plus the fresh one arms —
        # strict >= min_traffic could never trigger at the default 2.0
        # (the older sighting always decays at least a little)
        key = (table, hash(family_key))
        now = time.time()
        with self._lock:
            slot = self._counts.get(key)
        return slot is not None and self._decayed(slot, now) \
            >= self.min_traffic - 0.5

    def snapshot(self) -> dict:
        now = time.time()
        with self._lock:
            per_table: dict = {}
            for (table, _), slot in self._counts.items():
                per_table[table] = per_table.get(table, 0.0) \
                    + self._decayed(slot, now)
        return {t: round(v, 3) for t, v in per_table.items()}


class CoalesceResult:
    """What a coalesced member gets back: its own S host-side output
    rows (zero-copy views of the group's fetched [S·Q, ...] arrays)."""

    __slots__ = ("outs", "peers", "wait_ms")

    def __init__(self, outs, peers: int, wait_ms: float):
        self.outs = outs
        self.peers = peers
        self.wait_ms = wait_ms


class _Group:
    __slots__ = ("key", "segs", "plans_list", "closed", "full", "done",
                 "outs", "error")

    def __init__(self, key, segs):
        self.key = key
        self.segs = segs
        self.plans_list: list = []
        self.closed = False
        self.full = threading.Event()
        self.done = threading.Event()
        self.outs = None
        self.error = None


class QueryCoalescer:
    """One per QueryExecutor. ``offer`` is the only entry point; it
    returns None whenever the query should take its normal solo path."""

    def __init__(self, traffic: FamilyTraffic = None):
        self.traffic = traffic if traffic is not None else FamilyTraffic()
        self._lock = threading.Lock()
        self._open: dict = {}
        # observability: lifetime groups/queries coalesced (scrape only)
        self.groups_formed = 0
        self.queries_coalesced = 0

    def offer(self, table, fkey, segs, plans, mesh, runner):
        """Coalesce this query's (family, segment-set) dispatch with
        concurrent peers. ``runner(segs_all, plans_all)`` must dispatch
        ONE family batch and return the fetched host arrays (leading
        [S·Q] axis). Returns a CoalesceResult with this query's row
        views, or None → caller dispatches normally."""
        w_ms = window_ms()
        if w_ms <= 0:
            return None
        key = (fkey, tuple(getattr(s, "name", id(s)) for s in segs), mesh)
        t0 = time.perf_counter()
        with self._lock:
            g = self._open.get(key)
            if g is not None and not g.closed:
                member = len(g.plans_list)
                g.plans_list.append(plans)
                if member + 1 >= _max_queries():
                    g.full.set()
                lead = False
            else:
                self.traffic.note(table, fkey)
                if not self.traffic.armed(table, fkey):
                    return None
                g = _Group(key, segs)
                g.plans_list.append(plans)
                self._open[key] = g
                lead = True
        if lead:
            return self._lead(g, key, len(plans), w_ms, t0, runner)
        return self._follow(g, member, len(plans), w_ms, t0)

    def _follow(self, g: _Group, member: int, s: int, w_ms: float,
                t0: float):
        """Registered under the lock in offer(); wait here, outside it.
        The generous timeout covers the leader's window + dispatch (a
        first-of-family compile can take seconds); on leader failure or
        timeout the member silently reverts to its own dispatch."""
        ok = g.done.wait(timeout=w_ms / 1000.0 + 60.0)
        wait_ms = (time.perf_counter() - t0) * 1000
        if not ok or g.outs is None:
            return None  # leader failed/timed out → solo fallback
        row0 = member * s
        outs = [o[row0:row0 + s] for o in g.outs]
        peers = len(g.plans_list) - 1
        self._account(peers, wait_ms)
        return CoalesceResult(outs, peers, wait_ms)

    def _lead(self, g: _Group, key, s: int, w_ms: float, t0: float,
              runner):
        g.full.wait(timeout=w_ms / 1000.0)  # window, or early-full close
        with self._lock:
            g.closed = True
            self._open.pop(key, None)
            plans_list = list(g.plans_list)
        q = len(plans_list)
        wait_ms = (time.perf_counter() - t0) * 1000
        if q == 1:
            # nobody joined: hand the slot back to the normal path
            g.error = TimeoutError("no peers joined the window")
            g.done.set()
            self._account(0, wait_ms)
            return None
        try:
            segs_all = list(g.segs) * q
            plans_all = [p for member in plans_list for p in member]
            g.outs = runner(segs_all, plans_all)
        except Exception as e:
            g.error = e
            g.done.set()
            log.warning(
                "coalesced dispatch failed (%s: %s); %d queries fall "
                "back to solo dispatch", type(e).__name__, e, q)
            return None
        g.done.set()
        with self._lock:
            self.groups_formed += 1
            self.queries_coalesced += q
        self._account(q - 1, wait_ms)
        from ..spi.metrics import SERVER_METRICS, ServerMeter

        SERVER_METRICS.add_meter(ServerMeter.COALESCED_QUERIES, q - 1)
        return CoalesceResult([o[:s] for o in g.outs], q - 1, wait_ms)

    @staticmethod
    def _account(peers: int, wait_ms: float) -> None:
        _note_stats(peers, wait_ms)
        from ..spi.metrics import SERVER_METRICS, ServerTimer

        SERVER_METRICS.update_timer(ServerTimer.COALESCE_WAIT_MS, wait_ms)

    def snapshot(self) -> dict:
        with self._lock:
            open_groups = len(self._open)
            groups = self.groups_formed
            queries = self.queries_coalesced
        return {"openGroups": open_groups, "groupsFormed": groups,
                "queriesCoalesced": queries,
                "windowMs": window_ms(),
                "tableTraffic": self.traffic.snapshot()}


def coalesce_enabled(query) -> bool:
    """``SET coalesce = false`` opts a query out; ON by default. Traced
    queries are handled at the call site (they never coalesce — their
    spans must describe their own dispatches)."""
    return str(query.query_options.get("coalesce")).lower() \
        not in ("false", "0", "off")
