"""Cross-segment combine.

Reference: pinot-core/.../operator/combine/ (GroupByCombineOperator merging
into ConcurrentIndexedTable keyed on group Records —
GroupByCombineOperator.java:102-140). Here intermediates are already keyed by
group VALUES, so combine is a dict merge using each aggregation's shared
AggSemantics.merge.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from . import ir
from .aggregation import AggSemantics
from .results import (
    AggIntermediate,
    GroupArrays,
    GroupByIntermediate,
    SelectionIntermediate,
)

_MERGE_INIT = {"add": 0.0, "min": np.inf, "max": -np.inf}
_MERGE_AT = {"add": np.add.at, "min": np.minimum.at, "max": np.maximum.at}


def combine_batched_dense(outs_b: Sequence, plans: Sequence) -> Optional[list]:
    """Vectorized decode of a batched dense group-by FAMILY's outputs
    (engine/executor.py:dispatch_plan_batch — every array carries a
    leading [S] member dim) into per-member GroupArrays: ONE np.nonzero
    over the whole [S, G] counts block and one scanned-docs reduction,
    instead of S of each. Key-value gathers stay per member because group
    dictionaries are segment-local. Bit-identical to running each member's
    slice through TpuSegmentExecutor.collect(); returns None when any
    member needs the general (dict-form) path."""
    p0 = plans[0].program
    if p0.mode != "group_by" or p0.mv_group_slot is not None:
        return None
    if any(not all(la.vec is not None for la in pl.lowered_aggs)
           for pl in plans):
        return None
    num_groups = p0.num_groups
    counts_b = np.asarray(outs_b[0])[:, :num_groups]
    rows, gids = np.nonzero(counts_b)  # row-major: member order preserved
    bounds = np.searchsorted(rows, np.arange(len(plans) + 1))
    scanned_b = counts_b.sum(axis=1)
    result = []
    for s, pl in enumerate(plans):
        g = gids[bounds[s]:bounds[s + 1]]
        outs_s = [o[s] for o in outs_b]  # zero-copy views
        key_cols = [
            np.asarray(dim.dictionary.values[(g // stride) % dim.cardinality])
            for dim, stride in zip(pl.group_dims, pl.program.group_strides)]
        result.append(GroupArrays(
            key_cols,
            [la.vec.extract(outs_s, g) for la in pl.lowered_aggs],
            [la.vec.spec for la in pl.lowered_aggs],
            [la.vec.fin_tag for la in pl.lowered_aggs],
            num_docs_scanned=int(scanned_b[s]), groups_trimmed=False))
    return result


def combine_batched_aggregation(outs_b: Sequence, plans: Sequence) -> list:
    """Per-member AggIntermediates from a batched aggregation family: the
    scanned-docs column reads once for the whole family; per-agg state
    extraction is O(1) per member (scalar indexing into the [S, ...]
    views). Bit-identical to per-member collect()."""
    scanned_b = np.asarray(outs_b[0])[:, 0]
    return [
        AggIntermediate(
            [la.extract([o[s] for o in outs_b], 0)
             for la in pl.lowered_aggs],
            num_docs_scanned=int(scanned_b[s]))
        for s, pl in enumerate(plans)]


def combine_group_arrays(
    intermediates: Sequence[GroupArrays],
) -> Optional[GroupArrays]:
    """Vectorized cross-segment merge of columnar group tables: factorize
    each key dimension over the concatenated columns, build a composite
    group id, and scatter-merge every state component with np.{add,min,max}.at
    — no per-group Python. Returns None when the composite id would overflow
    (caller falls back to the dict merge)."""
    first = intermediates[0]
    scanned = sum(im.num_docs_scanned for im in intermediates)
    trimmed = any(getattr(im, "groups_trimmed", False) for im in intermediates)
    if len(intermediates) == 1:
        first.num_docs_scanned = scanned
        return first
    ndim = len(first.key_cols)
    cat_keys = [np.concatenate([im.key_cols[d] for im in intermediates])
                for d in range(ndim)]
    total = len(cat_keys[0]) if ndim else 0
    if total == 0:
        return GroupArrays([np.empty(0, object)] * ndim,
                           [tuple(np.empty(0) for _ in s)
                            for s in first.vec_specs],
                           first.vec_specs, first.fin_tags, scanned,
                           groups_trimmed=trimmed)
    uniqs, composite, stride = [], np.zeros(total, dtype=np.int64), 1
    for col in reversed(cat_keys):
        uniq, inv = np.unique(col, return_inverse=True)
        if stride * len(uniq) >= ir.SPARSE_KEY_SPACE:
            return None  # composite id overflow; dict merge handles it
        composite += inv.astype(np.int64) * stride
        stride *= max(1, len(uniq))
        uniqs.append(uniq)
    uniqs.reverse()
    uniq_comp, inv = np.unique(composite, return_inverse=True)
    g = len(uniq_comp)
    # decode merged composite ids back to per-dim values
    out_keys = []
    rem = uniq_comp
    strides = [1] * ndim
    for d in range(ndim - 2, -1, -1):
        strides[d] = strides[d + 1] * max(1, len(uniqs[d + 1]))
    for d in range(ndim):
        out_keys.append(uniqs[d][(rem // strides[d]) % max(1, len(uniqs[d]))])
    out_states = []
    for ai, spec in enumerate(first.vec_specs):
        comps = []
        for ci, op in enumerate(spec):
            cat = np.concatenate(
                [im.state_cols[ai][ci] for im in intermediates])
            if op == "add":
                out = np.zeros(g, dtype=cat.dtype)
            else:
                out = np.full(g, _MERGE_INIT[op], dtype=np.float64)
            _MERGE_AT[op](out, inv, cat)
            comps.append(out)
        out_states.append(tuple(comps))
    return GroupArrays(out_keys, out_states, first.vec_specs,
                       first.fin_tags, scanned, groups_trimmed=trimmed)


def combine_group_by(
    intermediates: Sequence[GroupByIntermediate], semantics: list[AggSemantics]
) -> GroupByIntermediate:
    merged: dict[tuple, list] = {}
    scanned = 0
    trimmed = False
    for im in intermediates:
        scanned += im.num_docs_scanned
        trimmed |= getattr(im, "groups_trimmed", False)
        for key, states in im.groups.items():
            cur = merged.get(key)
            if cur is None:
                merged[key] = list(states)
            else:
                for i, sem in enumerate(semantics):
                    cur[i] = sem.merge(cur[i], states[i])
    return GroupByIntermediate(merged, scanned, groups_trimmed=trimmed)


def combine_aggregation(
    intermediates: Sequence[AggIntermediate], semantics: list[AggSemantics]
) -> AggIntermediate:
    it = iter(intermediates)
    first = next(it)
    states = list(first.states)
    scanned = first.num_docs_scanned
    for im in it:
        scanned += im.num_docs_scanned
        for i, sem in enumerate(semantics):
            states[i] = sem.merge(states[i], im.states[i])
    return AggIntermediate(states, scanned)


def combine_selection(
    intermediates: Sequence[SelectionIntermediate],
) -> SelectionIntermediate:
    it = iter(intermediates)
    first = next(it)
    rows = list(first.rows)
    scanned = first.num_docs_scanned
    for im in it:
        scanned += im.num_docs_scanned
        rows.extend(im.rows)
    return SelectionIntermediate(first.columns, rows, scanned)


# -- server-side group trim (reference: TableResizer in the IndexedTable) ----

DEFAULT_MIN_TRIM_SIZE = 5_000
DEFAULT_TRIM_THRESHOLD = 1_000_000


def trim_group_by(combined, query, semantics):
    """Trim an ordered group-by intermediate to max(5*limit, minTrimSize)
    groups when the group count exceeds the trim threshold (reference:
    TableResizer.resize — servers keep only the groups that can matter for
    the final ORDER BY ... LIMIT, ordered on the intermediate results).

    Trims ONLY when every ORDER BY expression is a group key or a finalized
    aggregation — anything else (post-aggregation arithmetic, HAVING) keeps
    the full set, correctness over memory.
    """
    if not query.is_group_by or not query.order_by_expressions:
        return combined
    opts = query.query_options
    min_trim = int(opts.get("minServerGroupTrimSize", DEFAULT_MIN_TRIM_SIZE))
    threshold = int(opts.get("groupTrimThreshold", DEFAULT_TRIM_THRESHOLD))
    if min_trim <= 0 or threshold <= 0 or query.having_filter is not None:
        return combined
    trim_size = max((query.limit or 0) * 5, min_trim)
    num_groups = combined.num_groups if isinstance(combined, GroupArrays) \
        else len(combined.groups)
    if num_groups <= max(trim_size, 0) or num_groups <= threshold:
        return combined

    group_strs = [str(g) for g in query.group_by_expressions]
    agg_strs = [str(a) for a in query.aggregations]
    alias_map = {a: str(se) for se, a in
                 zip(query.select_expressions, query.aliases) if a}

    if isinstance(combined, GroupArrays):
        colmap = {s: c for s, c in zip(group_strs, combined.key_cols)}
        from .reduce import _apply_fin_tag

        for s, tag, comps in zip(agg_strs, combined.fin_tags,
                                 combined.state_cols):
            colmap[s] = _apply_fin_tag(tag, comps)
        order = []
        for ob in query.order_by_expressions:
            key = str(ob.expression)
            key = alias_map.get(key, key)
            col = colmap.get(key)
            if col is None or (not ob.ascending and col.dtype == object):
                return combined  # unsupported order expr: no trim
            order.append((col, ob.ascending))
        perm = np.arange(num_groups)
        for col, asc in reversed(order):
            vals = col[perm]
            k = (np.argsort(vals, kind="stable") if asc
                 else np.argsort(-vals, kind="stable"))
            perm = perm[k]
        sel = np.sort(perm[:trim_size])
        return GroupArrays(
            [c[sel] for c in combined.key_cols],
            [tuple(comp[sel] for comp in comps)
             for comps in combined.state_cols],
            combined.vec_specs, combined.fin_tags,
            num_docs_scanned=combined.num_docs_scanned,
            # the ordered trim is LOSSLESS for the final ORDER BY/LIMIT —
            # it must not read as numGroupsLimitReached
            groups_trimmed=combined.groups_trimmed)

    # dict-form intermediate: build sort keys from key values / finalized
    # aggregation states
    def sort_value(key, states, expr_str):
        if expr_str in group_strs:
            return key[group_strs.index(expr_str)]
        if expr_str in agg_strs:
            i = agg_strs.index(expr_str)
            return semantics[i].finalize(states[i])
        return None

    order_exprs = []
    for ob in query.order_by_expressions:
        key = str(ob.expression)
        key = alias_map.get(key, key)
        if key not in group_strs and key not in agg_strs:
            return combined
        order_exprs.append((key, ob.ascending))

    def rank(item):
        key, states = item
        out = []
        for expr_str, asc in order_exprs:
            v = sort_value(key, states, expr_str)
            out.append(_TrimKey(v, asc))
        return tuple(out)

    import heapq

    kept = heapq.nsmallest(trim_size, combined.groups.items(), key=rank)
    return GroupByIntermediate(dict(kept), combined.num_docs_scanned,
                               groups_trimmed=combined.groups_trimmed)


class _TrimKey:
    """Orderable wrapper honoring per-key direction + cross-type safety."""

    __slots__ = ("v", "asc")

    def __init__(self, v, asc):
        self.v = v
        self.asc = asc

    def __lt__(self, other):
        a, b = self.v, other.v
        if a is None:
            return False
        if b is None:
            return True
        try:
            return a < b if self.asc else b < a
        except TypeError:
            return str(a) < str(b) if self.asc else str(b) < str(a)

    def __eq__(self, other):
        return self.v == other.v
