"""Cross-segment combine.

Reference: pinot-core/.../operator/combine/ (GroupByCombineOperator merging
into ConcurrentIndexedTable keyed on group Records —
GroupByCombineOperator.java:102-140). Here intermediates are already keyed by
group VALUES, so combine is a dict merge using each aggregation's shared
AggSemantics.merge.
"""

from __future__ import annotations

from typing import Sequence

from .aggregation import AggSemantics
from .results import AggIntermediate, GroupByIntermediate, SelectionIntermediate


def combine_group_by(
    intermediates: Sequence[GroupByIntermediate], semantics: list[AggSemantics]
) -> GroupByIntermediate:
    merged: dict[tuple, list] = {}
    scanned = 0
    for im in intermediates:
        scanned += im.num_docs_scanned
        for key, states in im.groups.items():
            cur = merged.get(key)
            if cur is None:
                merged[key] = list(states)
            else:
                for i, sem in enumerate(semantics):
                    cur[i] = sem.merge(cur[i], states[i])
    return GroupByIntermediate(merged, scanned)


def combine_aggregation(
    intermediates: Sequence[AggIntermediate], semantics: list[AggSemantics]
) -> AggIntermediate:
    it = iter(intermediates)
    first = next(it)
    states = list(first.states)
    scanned = first.num_docs_scanned
    for im in it:
        scanned += im.num_docs_scanned
        for i, sem in enumerate(semantics):
            states[i] = sem.merge(states[i], im.states[i])
    return AggIntermediate(states, scanned)


def combine_selection(
    intermediates: Sequence[SelectionIntermediate],
) -> SelectionIntermediate:
    it = iter(intermediates)
    first = next(it)
    rows = list(first.rows)
    scanned = first.num_docs_scanned
    for im in it:
        scanned += im.num_docs_scanned
        rows.extend(im.rows)
    return SelectionIntermediate(first.columns, rows, scanned)
