"""Cross-segment combine.

Reference: pinot-core/.../operator/combine/ (GroupByCombineOperator merging
into ConcurrentIndexedTable keyed on group Records —
GroupByCombineOperator.java:102-140). Here intermediates are already keyed by
group VALUES, so combine is a dict merge using each aggregation's shared
AggSemantics.merge.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from . import ir
from .aggregation import AggSemantics
from .results import (
    AggIntermediate,
    GroupArrays,
    GroupByIntermediate,
    SelectionIntermediate,
)

_MERGE_INIT = {"add": 0.0, "min": np.inf, "max": -np.inf}
_MERGE_AT = {"add": np.add.at, "min": np.minimum.at, "max": np.maximum.at}


def combine_group_arrays(
    intermediates: Sequence[GroupArrays],
) -> Optional[GroupArrays]:
    """Vectorized cross-segment merge of columnar group tables: factorize
    each key dimension over the concatenated columns, build a composite
    group id, and scatter-merge every state component with np.{add,min,max}.at
    — no per-group Python. Returns None when the composite id would overflow
    (caller falls back to the dict merge)."""
    first = intermediates[0]
    scanned = sum(im.num_docs_scanned for im in intermediates)
    if len(intermediates) == 1:
        first.num_docs_scanned = scanned
        return first
    ndim = len(first.key_cols)
    cat_keys = [np.concatenate([im.key_cols[d] for im in intermediates])
                for d in range(ndim)]
    total = len(cat_keys[0]) if ndim else 0
    if total == 0:
        return GroupArrays([np.empty(0, object)] * ndim,
                           [tuple(np.empty(0) for _ in s)
                            for s in first.vec_specs],
                           first.vec_specs, first.fin_tags, scanned)
    uniqs, composite, stride = [], np.zeros(total, dtype=np.int64), 1
    for col in reversed(cat_keys):
        uniq, inv = np.unique(col, return_inverse=True)
        if stride * len(uniq) >= ir.SPARSE_KEY_SPACE:
            return None  # composite id overflow; dict merge handles it
        composite += inv.astype(np.int64) * stride
        stride *= max(1, len(uniq))
        uniqs.append(uniq)
    uniqs.reverse()
    uniq_comp, inv = np.unique(composite, return_inverse=True)
    g = len(uniq_comp)
    # decode merged composite ids back to per-dim values
    out_keys = []
    rem = uniq_comp
    strides = [1] * ndim
    for d in range(ndim - 2, -1, -1):
        strides[d] = strides[d + 1] * max(1, len(uniqs[d + 1]))
    for d in range(ndim):
        out_keys.append(uniqs[d][(rem // strides[d]) % max(1, len(uniqs[d]))])
    out_states = []
    for ai, spec in enumerate(first.vec_specs):
        comps = []
        for ci, op in enumerate(spec):
            cat = np.concatenate(
                [im.state_cols[ai][ci] for im in intermediates])
            if op == "add":
                out = np.zeros(g, dtype=cat.dtype)
            else:
                out = np.full(g, _MERGE_INIT[op], dtype=np.float64)
            _MERGE_AT[op](out, inv, cat)
            comps.append(out)
        out_states.append(tuple(comps))
    return GroupArrays(out_keys, out_states, first.vec_specs,
                       first.fin_tags, scanned)


def combine_group_by(
    intermediates: Sequence[GroupByIntermediate], semantics: list[AggSemantics]
) -> GroupByIntermediate:
    merged: dict[tuple, list] = {}
    scanned = 0
    for im in intermediates:
        scanned += im.num_docs_scanned
        for key, states in im.groups.items():
            cur = merged.get(key)
            if cur is None:
                merged[key] = list(states)
            else:
                for i, sem in enumerate(semantics):
                    cur[i] = sem.merge(cur[i], states[i])
    return GroupByIntermediate(merged, scanned)


def combine_aggregation(
    intermediates: Sequence[AggIntermediate], semantics: list[AggSemantics]
) -> AggIntermediate:
    it = iter(intermediates)
    first = next(it)
    states = list(first.states)
    scanned = first.num_docs_scanned
    for im in it:
        scanned += im.num_docs_scanned
        for i, sem in enumerate(semantics):
            states[i] = sem.merge(states[i], im.states[i])
    return AggIntermediate(states, scanned)


def combine_selection(
    intermediates: Sequence[SelectionIntermediate],
) -> SelectionIntermediate:
    it = iter(intermediates)
    first = next(it)
    rows = list(first.rows)
    scanned = first.num_docs_scanned
    for im in it:
        scanned += im.num_docs_scanned
        rows.extend(im.rows)
    return SelectionIntermediate(first.columns, rows, scanned)
