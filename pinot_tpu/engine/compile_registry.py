"""Per-process compile & dispatch telemetry registry.

Reference analogue: none in Pinot — this is the evidence feed the
ROADMAP's "compile-free cold starts" item needs: which compiled
executable families exist in this process, what each cost to compile,
and how often each is dispatched. Entries are keyed by the PR-5 family
fingerprint (cache/keys.py ``family_fingerprint``: Program IR + padded
bucket + fused/LUT variant + batch size — the identity an AOT executable
cache would persist under), so ``GET /debug/compiles`` literally names
the fingerprints worth AOT-persisting, ranked by compile cost × reuse.

Cost discipline (pinned by tests/test_tracing_perf_guard.py): the
fingerprint — a canonical-bytes walk of the Program IR — is computed only
on compile-guard MISSES (cold path). Warm dispatches pay one dict lookup
on the guard key tuple the executor already built, plus two counter
bumps: no span allocations, no device syncs, no env reads.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Optional

# registry size tracks the compile-cache guard's own limit: an entry per
# live executable family plus headroom for evicted-then-recompiled ones
_MAX_ENTRIES = int(os.environ.get("PINOT_TPU_COMPILE_REGISTRY_MAX", 4096))

# recency window for the dispatch-rate term of the AOT-persist ranking:
# dispatches older than ~2 windows stop contributing, so the priority
# list tracks CURRENT traffic instead of all-time history. The warm path
# pays only an integer epoch compare + counter bump for this (no pow/exp,
# no extra clock read beyond the lastUsed stamp it already takes).
_RECENT_WINDOW_S = float(os.environ.get(
    "PINOT_TPU_COMPILE_RECENT_WINDOW_S", 300.0))


class CompileRegistry:
    """fingerprint → {compiles, compileMs, dispatches, family, lastUsed}."""

    def __init__(self, max_entries: int = _MAX_ENTRIES):
        self.max_entries = int(max_entries)
        self._lock = threading.Lock()
        # guard key tuple → fingerprint: the warm-path lookup table. The
        # key is the exact tuple _CompileCacheGuard.note() consumed, so
        # the warm dispatch never re-walks the Program IR.
        self._by_key: dict = {}
        self._entries: "OrderedDict[str, dict]" = OrderedDict()  # LRU

    @staticmethod
    def _bump_recent(ent: dict, now: float) -> None:
        """Two-bucket epoch window: ``recentW`` counts dispatches in the
        current window, ``recentWPrev`` holds the previous window's count.
        On an epoch boundary the buckets shift (skipping ≥2 windows zeroes
        both) — an integer compare + at most two assignments, so the warm
        path stays counter bumps with no pow/exp work."""
        epoch = int(now / _RECENT_WINDOW_S)
        delta = epoch - ent["recentEpoch"]
        if delta:
            ent["recentWPrev"] = ent["recentW"] if delta == 1 else 0
            ent["recentW"] = 0
            ent["recentEpoch"] = epoch
        ent["recentW"] += 1

    def note_compile(self, guard_key, compile_ms: float,
                     fingerprint: Optional[str], family: dict) -> None:
        """Record a compile-guard miss: a fresh executable was (or is
        about to be) compiled for ``guard_key``. ``fingerprint`` is None
        when the Program has no canonical encoding — the family is still
        counted under a key-local pseudo id so the totals stay honest."""
        fp = fingerprint or f"unfingerprintable:{abs(hash(guard_key)):x}"
        now = time.time()
        with self._lock:
            self._by_key[guard_key] = fp
            ent = self._entries.get(fp)
            if ent is None:
                ent = self._entries[fp] = {
                    "compiles": 0, "compileMsTotal": 0.0,
                    "compileMsLast": 0.0, "dispatches": 0,
                    "firstSeen": round(now, 3), "family": family,
                    "recentW": 0, "recentWPrev": 0,
                    "recentEpoch": int(now / _RECENT_WINDOW_S),
                }
            ent["compiles"] += 1
            ent["compileMsTotal"] = round(
                ent["compileMsTotal"] + float(compile_ms), 3)
            ent["compileMsLast"] = round(float(compile_ms), 3)
            ent["dispatches"] += 1
            ent["lastUsed"] = round(now, 3)
            self._bump_recent(ent, now)
            self._entries.move_to_end(fp)
            while len(self._entries) > self.max_entries:
                victim, _ = self._entries.popitem(last=False)
                self._by_key = {k: v for k, v in self._by_key.items()
                                if v != victim}

    def note_preloaded(self, guard_key, fingerprint: str,
                       family: dict) -> None:
        """An AOT-deserialized executable was installed for ``guard_key``
        (engine/aot_cache.py prewarm): teach the registry the
        key→fingerprint edge WITHOUT counting a compile, so later warm
        dispatches register under the persisted family with no IR walk.
        compileMsLast stays 0 — a preloaded family never re-persists."""
        now = time.time()
        with self._lock:
            self._by_key[guard_key] = fingerprint
            if fingerprint not in self._entries:
                self._entries[fingerprint] = {
                    "compiles": 0, "compileMsTotal": 0.0,
                    "compileMsLast": 0.0, "dispatches": 0,
                    "firstSeen": round(now, 3), "family": dict(family),
                    "lastUsed": round(now, 3),
                    "recentW": 0, "recentWPrev": 0,
                    "recentEpoch": int(now / _RECENT_WINDOW_S),
                }

    def note_dispatch(self, guard_key) -> None:
        """Warm-path hit: the executable family already exists. One dict
        lookup + counter bumps; silently ignores keys the registry no
        longer knows (entry evicted, or compiled before the registry
        loaded) — the next guard-cache clear re-registers them."""
        with self._lock:
            fp = self._by_key.get(guard_key)
            if fp is None:
                return
            ent = self._entries.get(fp)
            if ent is None:
                return
            now = time.time()
            ent["dispatches"] += 1
            ent["lastUsed"] = round(now, 3)
            self._bump_recent(ent, now)
            self._entries.move_to_end(fp)

    @staticmethod
    def _score(ent: dict, now: float) -> float:
        """AOT-persist priority: compile cost × recent traffic. The
        recency term interpolates the two window buckets (prev bucket
        fades linearly as the current window fills), so a family that
        stopped dispatching decays to bare compile cost within ~2 windows
        while a hot family's score tracks its current dispatch rate."""
        epoch = int(now / _RECENT_WINDOW_S)
        delta = epoch - ent["recentEpoch"]
        if delta == 0:
            frac = (now / _RECENT_WINDOW_S) - epoch
            recent = ent["recentW"] + (1.0 - frac) * ent["recentWPrev"]
        elif delta == 1:
            frac = (now / _RECENT_WINDOW_S) - epoch
            recent = (1.0 - frac) * ent["recentW"]
        else:
            recent = 0.0
        return float(ent["compileMsLast"]) * (1.0 + recent)

    def snapshot(self) -> dict:
        """The GET /debug/compiles payload: per-fingerprint entries ranked
        by decayed compile-cost × dispatch-recency (the AOT-persist
        priority order — tracks current traffic, not all-time history),
        plus process totals for /metrics. Scores are computed here, at
        scrape time, never on the dispatch path."""
        now = time.time()
        with self._lock:
            entries = {fp: dict(ent, family=dict(ent["family"]),
                                aotScore=round(self._score(ent, now), 3))
                       for fp, ent in self._entries.items()}
        ranked = sorted(entries.items(),
                        key=lambda kv: (-kv[1]["aotScore"],
                                        -kv[1]["compileMsTotal"]))
        out = []
        for fp, ent in ranked:
            ent = dict(ent, fingerprint=fp)
            ent.pop("recentEpoch", None)
            out.append(ent)
        return {
            "families": len(entries),
            "totalCompiles": sum(e["compiles"] for e in entries.values()),
            "totalCompileMs": round(sum(e["compileMsTotal"]
                                        for e in entries.values()), 3),
            "totalDispatches": sum(e["dispatches"]
                                   for e in entries.values()),
            "compiles": out,
        }

    def aot_priority(self) -> list:
        """[(fingerprint, score, family)] best-first — the AOT cache's
        persist/evict order. Unfingerprintable families are excluded:
        there is nothing stable to key an on-disk artifact by."""
        now = time.time()
        with self._lock:
            scored = [(fp, self._score(ent, now), dict(ent["family"]))
                      for fp, ent in self._entries.items()
                      if not fp.startswith("unfingerprintable:")]
        scored.sort(key=lambda t: -t[1])
        return scored

    def totals(self) -> dict:
        """Cheap rollup for scrape-time /metrics gauges."""
        with self._lock:
            return {
                "families": len(self._entries),
                "compiles": sum(e["compiles"]
                                for e in self._entries.values()),
                "compileMs": round(sum(e["compileMsTotal"]
                                       for e in self._entries.values()), 3),
            }

    def reset(self) -> None:
        with self._lock:
            self._by_key.clear()
            self._entries.clear()


COMPILE_REGISTRY = CompileRegistry()


def describe_family(program, padded: int, fused: str = "",
                    lut_meta: tuple = (), batch_size: int = 0) -> dict:
    """Human-readable family shape for the registry entry."""
    return {
        "mode": getattr(program, "mode", "?"),
        "padded": int(padded),
        "fused": str(fused),
        "lutRuns": len(lut_meta) if lut_meta else 0,
        "batchSize": int(batch_size),
    }
