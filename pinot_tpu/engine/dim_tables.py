"""Dimension-table registry for LOOKUP joins.

Reference analogue: dimension tables (TableConfig.isDimTable) are
replicated to every server and queried through the LOOKUP transform
(pinot-core/.../operator/transform/function/LookupTransformFunction.java:
LOOKUP('dimTable', 'valueColumn', 'pkColumn', factKeyExpr)), powered by
DimensionTableDataManager's in-memory pk → row map.

TPU-first redesign: the per-process registry holds plain column arrays
with a SORTED primary-key view. The device lowering never ships the whole
table — at plan time the fact column's dictionary (segment-local, small)
is translated pk→value into a cardinality-sized LUT that rides the kernel
as a ParamGather, so the join costs one device gather per row fused into
whatever kernel uses it (filter, group-by, aggregation input).
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class DimensionTable:
    def __init__(self, name: str, pk_column: str,
                 columns: dict[str, np.ndarray]):
        if pk_column not in columns:
            raise ValueError(f"pk column {pk_column} missing")
        self.name = name
        self.pk_column = pk_column
        self.columns = {c: np.asarray(v) for c, v in columns.items()}
        pk = self.columns[pk_column]
        order = np.argsort(pk, kind="stable")
        self._sorted_pk = pk[order]
        # the table is immutable after registration: pre-sort every column
        # once so lookup() is a pure searchsorted + gather
        self._sorted_cols = {c: v[order] for c, v in self.columns.items()}
        if len(self._sorted_pk) > 1 and \
                (self._sorted_pk[1:] == self._sorted_pk[:-1]).any():
            raise ValueError(f"duplicate primary keys in dim table {name}")

    def lookup(self, attr: str, keys: np.ndarray):
        """(values, found_mask) for an array of join keys. Missing keys get
        the attr dtype's null stand-in (0 / empty string) with found=False
        — LOOKUP's null result under basic null handling."""
        vals = self._sorted_cols[attr]
        keys = np.asarray(keys)
        if len(self._sorted_pk) == 0:
            empty = (np.zeros(len(keys)) if vals.dtype.kind in "iuf"
                     else np.full(len(keys), "", dtype=object))
            return empty, np.zeros(len(keys), dtype=bool)
        idx = np.clip(np.searchsorted(self._sorted_pk, keys), 0,
                      len(self._sorted_pk) - 1)
        found = self._sorted_pk[idx] == keys
        out = vals[idx]
        if out.dtype.kind in "iuf":
            out = np.where(found, out, 0)
        else:
            out = np.where(found, out, "")
        return out, found


_REGISTRY: dict[str, DimensionTable] = {}


def register_dimension_table(name: str, pk_column: str,
                             columns: dict[str, np.ndarray]) -> DimensionTable:
    t = DimensionTable(name, pk_column, columns)
    _REGISTRY[name] = t
    return t


def get_dimension_table(name: str) -> Optional[DimensionTable]:
    return _REGISTRY.get(name)


def alias_dimension_table(alias: str, name: str) -> None:
    """Expose a registered table under a second name (cluster tables
    register with their _OFFLINE suffix; LOOKUP callers use the raw name)."""
    if name in _REGISTRY:
        _REGISTRY[alias] = _REGISTRY[name]


def unregister_dimension_table(name: str) -> None:
    _REGISTRY.pop(name, None)
