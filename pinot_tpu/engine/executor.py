"""Per-segment device executor.

Reference analogue: the server-side operator chain execution under
ServerQueryExecutorV1Impl (pinot-core/.../query/executor/
ServerQueryExecutorV1Impl.java:141) — but one segment = ONE device dispatch
(run_program), not a pull loop of 10K-doc blocks. Host work is limited to:
planning (dictionary lookups), launching the kernel, and decoding occupied
group keys back to values.
"""

from __future__ import annotations

import logging
import os
import threading
import time

import jax
import numpy as np
import jax.numpy as jnp

from ..ops.kernels import PackedOuts, pack_outputs, run_program, unpack_outputs
from .aot_cache import AOT_READY, aot_call
from ..query.context import QueryContext
from ..segment.device_cache import (
    GLOBAL_DEVICE_CACHE,
    DeviceSegmentCache,
    clear_transfer_stats,
    reset_transfer_stats,
    transfer_stats,
)
from ..segment.loader import ImmutableSegment
from ..spi import faults
from ..spi.trace import TRACING
from .plan import SegmentPlan, SegmentPlanner
from .results import (
    AggIntermediate,
    GroupArrays,
    GroupByIntermediate,
    SelectionIntermediate,
)
from .selection import selection_from_mask


class _CompileCacheGuard:
    """Process-global valve over jax's UNBOUNDED executable cache.

    A long-lived server compiling unbounded distinct query shapes dies
    with LLVM "Cannot allocate memory" (observed at ~10K distinct shapes
    in a query-fuzz soak). The guard counts distinct (program, padded,
    fused-variant) keys — one per compiled executable family — at the
    same PROCESS scope the jax cache lives at, and drops all jit caches
    wholesale when the limit is hit: recompiling is slow but alive (the
    reference's DirectOOMHandler shed-load philosophy applied to compile
    caches). Bookkeeping is locked; the clear itself is best-effort
    against concurrently-compiling threads."""

    def __init__(self):
        self.limit = int(os.environ.get(
            "PINOT_TPU_COMPILE_CACHE_LIMIT", 4096))
        self._lock = threading.Lock()
        self._seen: set = set()
        self._validated: set = set()  # fused variants proven on-device

    def note(self, key) -> bool:
        """Record a compiled-executable-family key. Returns True when the
        key is NEW (a fresh compile is about to happen) — the per-query
        num_compiles counter feeds off this."""
        with self._lock:
            if key in self._seen:
                return False
            if len(self._seen) >= self.limit:
                logging.getLogger(__name__).warning(
                    "dropping jit caches after %d distinct compiled "
                    "variants (PINOT_TPU_COMPILE_CACHE_LIMIT)",
                    len(self._seen))
                try:
                    jax.clear_caches()
                except Exception:
                    pass  # another thread mid-compile: retry next miss
                else:
                    self._seen.clear()
                    self._validated.clear()
            self._seen.add(key)
            return True

    def validated(self, vkey) -> bool:
        with self._lock:
            return vkey in self._validated

    def mark_validated(self, vkey) -> None:
        with self._lock:
            self._validated.add(vkey)


_GUARD = _CompileCacheGuard()


def _register_compile(gkey, compile_ms: float, program, padded: int,
                      fused: str = "", lut_meta: tuple = (),
                      batch_size: int = 0, mesh: tuple = (),
                      packed: bool = False, aot_example=None) -> None:
    """Cold-path half of the compile telemetry registry: fingerprint the
    freshly-compiled family (a canonical-bytes IR walk — only ever paid
    on a compile-guard miss, next to an actual XLA compile) and record
    the compile cost under it. When the AOT executable cache is enabled
    and the caller provided an (arrays, params, num_docs) example, the
    family is also exported + persisted here — still the cold path, next
    to the XLA compile that just happened. Mesh-sharded executables
    never persist (their validity spans device topology)."""
    from ..cache.keys import family_fingerprint
    from .compile_registry import COMPILE_REGISTRY, describe_family

    fp = family_fingerprint(program, padded, fused, lut_meta, batch_size,
                            mesh=mesh)
    family = describe_family(program, padded, fused, lut_meta, batch_size)
    COMPILE_REGISTRY.note_compile(gkey, compile_ms, fp, family)
    if aot_example is not None and not mesh:
        from . import aot_cache

        if aot_cache.enabled():
            aot_cache.on_compile(
                gkey, fp, compile_ms, family,
                "batch" if batch_size else "solo", program, padded,
                packed=packed, fused=fused, lut_meta=lut_meta,
                example=aot_example)


def _register_dispatch(gkey) -> None:
    """Warm-path half: one dict lookup + counter bumps, no fingerprint
    walk, no spans, no syncs (tests/test_tracing_perf_guard.py)."""
    from .compile_registry import COMPILE_REGISTRY

    COMPILE_REGISTRY.note_dispatch(gkey)


# (program mode, error type) pairs whose mesh-sharded dispatch already
# failed once — warn once, then fall back quietly to solo batching
_MESH_WARNED: set = set()


def _warn_mesh_fallback(program, err: Exception) -> None:
    # every fallback counts toward the sentinel's fallback-surge window,
    # even when the once-per-key warning below stays quiet
    from .perf_ledger import PERF_LEDGER

    PERF_LEDGER.note_event("mesh-solo")
    key = (getattr(program, "mode", "?"), type(err).__name__)
    if key not in _MESH_WARNED:
        _MESH_WARNED.add(key)
        logging.getLogger(__name__).warning(
            "mesh-sharded dispatch failed (%s: %s); falling back to "
            "single-device batching for %s programs",
            type(err).__name__, err, key[0])

# Per-QUERY dispatch/compile counters. Thread-local because concurrent
# queries share this module: every device dispatch happens on the query's
# own thread (query_executor's host pool never dispatches), so a
# reset-at-start / read-at-end pair on the query thread sees exactly its
# own dispatches — a global snapshot delta would interleave queries.
_TLS = threading.local()


def reset_dispatch_counters() -> None:
    _TLS.counts = [0, 0]  # [num_device_dispatches, num_compiles]


def dispatch_counters() -> tuple[int, int]:
    c = getattr(_TLS, "counts", None)
    return (c[0], c[1]) if c else (0, 0)


def _count_dispatch(new_compile: bool) -> None:
    c = getattr(_TLS, "counts", None)
    if c is not None:
        c[0] += 1
        if new_compile:
            c[1] += 1


def _attach_dispatch_stats(span, cache: DeviceSegmentCache) -> None:
    """Fold the thread-local transfer counters + an HBM snapshot into a
    finished family-dispatch span (traced paths only)."""
    stats = transfer_stats()
    if stats is not None:
        span.set_attribute("transferBytes", stats["transferBytes"])
        if stats["transfers"]:
            span.set_attribute("transfers", dict(stats["transfers"]))
        span.set_attribute("stackHits", stats["stackHits"])
        span.set_attribute("stackMisses", stats["stackMisses"])
    span.attributes.update(cache.hbm_stats())
    clear_transfer_stats()


class BatchFamilyMismatch(Exception):
    """A family grouped by the host-side key turned out to gather planes of
    unequal dtype/shape — the caller falls back to per-segment dispatch."""


def _dict_pad(card: int) -> int:
    """Shape bucket for dictionary-values planes: next power of two ≥ card.
    Dict planes are only ever gathered by ids < the segment's OWN
    cardinality, so zero-padding to a shared bucket lets segments with
    different dictionary sizes join one batch family without changing any
    gathered value."""
    b = 1
    while b < card:
        b <<= 1
    return b


def batch_family_key(segment: ImmutableSegment, plan: SegmentPlan,
                     mesh: tuple = ()):
    """Host-computable batch family key: segments with equal keys gather
    identically-shaped device planes and params, so their kernel inputs can
    stack into [S, ...] arrays and run as ONE vmapped dispatch.

    The key is (program, padded bucket, per-slot dtype/packing signature,
    per-param dtype/shape signature) — derived purely from column METADATA
    (no device upload), so EXPLAIN and the dispatcher share it. When mesh
    execution is active the mesh shape joins the key so sharded and solo
    executables cache separately (compile_registry.family_fingerprint gains
    the same axis). It mirrors what gather_arrays_packed will produce;
    dispatch_plan_batch re-verifies the real gathered shapes and raises
    BatchFamilyMismatch if the mirror ever drifts. Returns None when a
    slot's shape can't be predicted."""
    from ..segment.device_cache import pad_bucket, packed_hbm_enabled
    from ..spi.data_types import DataType

    padded = pad_bucket(max(1, segment.num_docs))
    packed_on = packed_hbm_enabled()
    sig = []
    try:
        for column, kind in plan.slots:
            m = segment.column_metadata(column)
            if kind == "ids" and not m.single_value:
                kind = "mvids"  # view.dict_ids falls through to the matrix
            if kind == "ids":
                bits = getattr(m, "bits_per_value", 32) or 32
                width = 32
                if bits <= 16 and packed_on:
                    width = 8 if bits <= 8 else 16
                sig.append(("ids", width))
            elif kind == "mvids":
                sig.append(("mvids", max(1, m.max_number_of_multi_values)))
            elif kind == "raw":
                sig.append(("raw", str(DataType(m.data_type).numpy_dtype)))
            elif kind == "rawf32r":
                sig.append(("rawf32r",))
            elif kind == "dict":
                sig.append(("dict", str(DataType(m.data_type).numpy_dtype),
                            _dict_pad(int(m.cardinality))))
            elif kind == "null":
                sig.append(("null",))
            else:
                return None
        psig = tuple((str(np.asarray(p).dtype), np.asarray(p).shape)
                     for p in plan.params)
    except Exception:
        return None
    key = (plan.program, padded, tuple(sig), psig)
    if mesh:
        key = key + (("mesh",) + tuple(mesh),)
    return key


class TpuSegmentExecutor:
    """Executes one QueryContext against one segment on the device."""

    def __init__(self, cache: DeviceSegmentCache = None):
        self.cache = cache or GLOBAL_DEVICE_CACHE

    def plan(self, query: QueryContext, segment: ImmutableSegment) -> SegmentPlan:
        if getattr(segment, "is_mutable", False):
            # consuming-segment snapshots lower through the realtime
            # planner (value-space ranges, no MV/rebased planes); its
            # UnsupportedQueryError falls back to host like any other
            from ..realtime.device_plane import realtime_plan

            return realtime_plan(query, segment)
        return SegmentPlanner(query, segment).plan()

    def _view_for(self, segment):
        """Device view: the HBM cache for immutable segments, the
        realtime plane registry (delta-uploaded append-only planes) for
        consuming-segment snapshots."""
        if getattr(segment, "is_mutable", False):
            from ..realtime.device_plane import REALTIME_PLANES

            return REALTIME_PLANES.view(segment)
        return self.cache.view(segment)

    def execute(self, query: QueryContext, segment: ImmutableSegment):
        plan = self.plan(query, segment)
        return self.execute_plan(query, segment, plan)

    def execute_plan(self, query: QueryContext, segment: ImmutableSegment, plan: SegmentPlan):
        outs = self.dispatch_plan(segment, plan)
        return self.collect(query, segment, plan, outs)

    def dispatch_plan(self, segment: ImmutableSegment, plan: SegmentPlan):
        """Launch the kernel and return UN-materialized device outputs.

        JAX dispatch is asynchronous: the caller can dispatch every
        segment's kernel back-to-back so the device queue stays full, then
        collect() each — host planning/decoding overlaps device compute
        (replaces the reference's per-segment worker-pool combine,
        pinot-core/.../operator/combine/GroupByCombineOperator.java:54, with
        async device queueing instead of threads).

        When a trace is active, the dispatch runs under a family_dispatch
        span with the compile/execute split (compile detected via the
        compile-cache guard; execute measured around block_until_ready —
        which costs the async overlap, so traced runs are NOT perf runs),
        per-slot transfer bytes, and an HBM snapshot. Tracing off takes the
        first branch: one thread-local read, no spans, no added syncs."""
        if faults.ACTIVE:
            # kind="hbm_oom" specs raise RESOURCE_EXHAUSTED here and are
            # absorbed by the caller's with_oom_retry — the real OOM path
            faults.FAULTS.fire("device.dispatch", segment=segment.name)
        if TRACING.active_trace() is None:
            return self._dispatch_plan(segment, plan, None)
        with TRACING.scope("family_dispatch") as span:
            reset_transfer_stats()
            try:
                span.set_attribute("segment", segment.name)
                span.set_attribute("numSegments", 1)
                return self._dispatch_plan(segment, plan, span)
            finally:
                _attach_dispatch_stats(span, self.cache)

    def _dispatch_plan(self, segment: ImmutableSegment, plan: SegmentPlan,
                       span):
        view = self._view_for(segment)
        arrays, packed = plan.gather_arrays_packed(view)
        # params pass as host numpy: jit converts arguments itself — an
        # eager jnp.asarray per param costs a device dispatch each (~1ms ×
        # params × segments of pure host overhead per multi-segment query).
        # Python ints still pin to int64 (the dtype the old jnp.asarray
        # produced under x64).
        params = tuple(p if isinstance(p, (np.ndarray, np.generic))
                       else np.asarray(p) for p in plan.params)
        from ..ops import fused_groupby

        # decide HERE whether the fused kernel applies, so the failure
        # fallback below can never be tripped (and permanently disable
        # fusion) by an error from a program the fused path never touched.
        # Dict-LUT predicates (IN/LIKE/NOT...) join the fused scope when
        # their boolean LUT compresses to a few contiguous dict-id runs —
        # a dispatch-time property of the CONCRETE host params.
        fused = fused_groupby.active() if plan.fused_ok else ""
        lut_meta: tuple = ()
        base_params = params
        if fused:
            extra, lut_meta = fused_groupby.lut_run_params(
                plan.program, params)
            if plan.program.mode == "group_by" and fused_groupby.plan(
                    plan.program, arrays, lut_meta) is not None:
                params = params + extra  # run arrays ride as extra params
            else:
                fused, lut_meta = "", ()
        # one entry per compiled executable family: padded shape and the
        # fused/lut variants each compile separately
        gkey = (plan.program, view.padded, fused, lut_meta)
        new_compile = _GUARD.note(gkey)
        _count_dispatch(new_compile)
        if span is not None:
            span.set_attribute("mode", plan.program.mode)
            span.set_attribute("padded", view.padded)
            if fused:
                span.set_attribute("fused", fused)
        if span is not None or new_compile:
            t0 = time.perf_counter()
        nd = np.int32(segment.num_docs)
        try:
            # AOT-prewarmed family (engine/aot_cache.py): the persisted
            # executable serves the dispatch — zero compiles in this
            # process for the family. Empty/disabled cache costs one
            # falsy truth test. A failed AOT call returns None and the
            # jit path below runs (its compile then goes uncounted —
            # the guard was seeded at prewarm — a deliberate trade in a
            # corruption-recovery path that should never recur).
            outs = aot_call(gkey, arrays, params, nd) if AOT_READY else None
            if outs is None:
                outs = run_program(plan.program, arrays, params, nd,
                                   view.padded, packed=packed, fused=fused,
                                   fused_lut_meta=lut_meta)
            if new_compile:
                # jit's first call compiles synchronously before the async
                # dispatch, so host wall of run_program ≈ compile cost on
                # a guard miss — measurable WITHOUT a sync, so the compile
                # registry gets fed on untraced production dispatches too
                t1 = time.perf_counter()
                _register_compile(gkey, round((t1 - t0) * 1000, 3),
                                  plan.program, view.padded, fused, lut_meta,
                                  packed=packed,
                                  aot_example=(arrays, params, nd))
            else:
                _register_dispatch(gkey)
            if span is not None:
                if not new_compile:
                    t1 = time.perf_counter()
                span.set_attribute(
                    "compileMs",
                    round((t1 - t0) * 1000, 3) if new_compile else 0.0)
                jax.block_until_ready(outs)
                span.set_attribute(
                    "deviceExecMs", round((time.perf_counter() - t1) * 1000, 3))
            # the compiled fused kernel varies with lut_meta (run counts
            # are static), so validation is keyed per (program, meta)
            vkey = (plan.program, lut_meta)
            if fused and not _GUARD.validated(vkey):
                # dispatch is async: a device-side kernel failure would
                # otherwise surface at collect(), past this fallback. Block
                # ONCE per compiled variant to prove the kernel end-to-end;
                # later executions stay fully async.
                jax.block_until_ready(outs)
                _GUARD.mark_validated(vkey)
        except Exception as e:
            if not fused:
                raise
            # Mosaic/VMEM failure on this machine's toolchain: disable the
            # fused kernel for the process and recompile the two-step
            # path — with the ORIGINAL params so this compile is the one
            # every later (post-disable) dispatch of the program reuses
            fused_groupby.note_failure(e)
            from .perf_ledger import PERF_LEDGER

            PERF_LEDGER.note_event("fused-host")
            outs = run_program(plan.program, arrays, base_params,
                               np.int32(segment.num_docs), view.padded,
                               packed=packed, fused="")
            if span is not None:
                span.set_attribute("fusedFallback", True)
                jax.block_until_ready(outs)
                span.set_attribute(
                    "deviceExecMs",
                    round((time.perf_counter() - t0) * 1000, 3))
        # one flat buffer per query → one D2H transfer at collect() (a
        # tunneled device pays a fixed round trip PER materialized array)
        return pack_outputs(outs)

    def dispatch_plan_raw(self, segment: ImmutableSegment, plan: SegmentPlan):
        """dispatch_plan without the flat-buffer packing: returns the raw
        device output tuple for callers that keep computing ON DEVICE with
        the per-segment outputs (the sparse device combine,
        query_executor._try_sparse_device_combine) rather than fetching
        them. Sparse programs never take the fused path, so the fused
        negotiation is skipped."""
        if faults.ACTIVE:
            faults.FAULTS.fire("device.dispatch", segment=segment.name)
        if TRACING.active_trace() is None:
            return self._dispatch_plan_raw(segment, plan, None)
        with TRACING.scope("family_dispatch") as span:
            reset_transfer_stats()
            try:
                span.set_attribute("segment", segment.name)
                span.set_attribute("numSegments", 1)
                return self._dispatch_plan_raw(segment, plan, span)
            finally:
                _attach_dispatch_stats(span, self.cache)

    def _dispatch_plan_raw(self, segment: ImmutableSegment,
                           plan: SegmentPlan, span):
        view = self._view_for(segment)
        arrays, packed = plan.gather_arrays_packed(view)
        params = tuple(p if isinstance(p, (np.ndarray, np.generic))
                       else np.asarray(p) for p in plan.params)
        gkey = (plan.program, view.padded, "", ())
        new_compile = _GUARD.note(gkey)
        _count_dispatch(new_compile)
        if span is None and not new_compile:
            _register_dispatch(gkey)
            return run_program(plan.program, arrays, params,
                               np.int32(segment.num_docs), view.padded,
                               packed=packed, fused=""), view
        if span is not None:
            span.set_attribute("mode", plan.program.mode)
            span.set_attribute("padded", view.padded)
        t0 = time.perf_counter()
        outs = run_program(plan.program, arrays, params,
                           np.int32(segment.num_docs), view.padded,
                           packed=packed, fused="")
        t1 = time.perf_counter()
        compile_ms = round((t1 - t0) * 1000, 3) if new_compile else 0.0
        if new_compile:
            _register_compile(gkey, compile_ms, plan.program, view.padded)
        else:
            _register_dispatch(gkey)
        if span is None:
            return outs, view
        span.set_attribute("compileMs", compile_ms)
        jax.block_until_ready(outs)
        span.set_attribute("deviceExecMs",
                           round((time.perf_counter() - t1) * 1000, 3))
        return outs, view

    def _gather_batch(self, segments: list, plans: list, ndev: int = 1):
        """Gather + stack a batch family's kernel inputs: per-member planes
        come from the per-segment HBM cache (gather_arrays_packed — upload
        happens at most once per plane), the [S, ...] stacks from the
        cache's stacked-view layer (derived copies under the same byte
        budget). With ndev > 1 the stacks are built SHARDED across the
        segment mesh axis (NamedSharding over the leading dim) and ragged
        families pad to a multiple of ndev by repeating the last member
        with num_docs=0 — the kernel's row-validity mask makes pad slots
        contribute nothing. Raises BatchFamilyMismatch if the members'
        gathered planes disagree in dtype/shape/packing — the host-side
        family key should prevent that; the check makes a drift fall back,
        not corrupt."""
        views = [self._view_for(s) for s in segments]
        gathered = [pl.gather_arrays_packed(v)
                    for pl, v in zip(plans, views)]
        packed = gathered[0][1]
        nslots = len(gathered[0][0])
        for arrs, pk in gathered[1:]:
            if pk != packed or len(arrs) != nslots:
                raise BatchFamilyMismatch("packing/slot-count mismatch")
        pad = 0
        if ndev > 1:
            pad = (-len(segments)) % ndev
        sview = self.cache.stacked_view(segments)
        stacked = []
        for i in range(nslots):
            col = [g[0][i] for g in gathered]
            if plans[0].slots[i][1] == "dict":
                # dictionary sizes are segment-local: zero-pad every
                # member's values plane to the family's shared power-of-two
                # bucket (see _dict_pad — pads are never gathered)
                target = _dict_pad(max(a.shape[0] for a in col))
                col = [a if a.shape[0] == target
                       else jnp.pad(a, (0, target - a.shape[0]))
                       for a in col]
            a0 = col[0]
            if any(a.shape != a0.shape or a.dtype != a0.dtype
                   for a in col[1:]):
                raise BatchFamilyMismatch(
                    f"slot {i} ({plans[0].slots[i]}): unequal plane "
                    f"shapes/dtypes across family members")
            if pad:
                col = col + [col[-1]] * pad
            pkey = (plans[0].slots[i], str(a0.dtype), tuple(a0.shape))
            if ndev > 1:
                from ..parallel import mesh as pmesh

                pkey = pkey + (("mesh", ndev),)

                def build(c=tuple(col), nd=ndev):
                    stack = jnp.stack(c)
                    return jax.device_put(
                        stack, pmesh.segment_sharding(nd, stack.ndim))

                stacked.append(sview.plane(pkey, build))
            else:
                stacked.append(sview.plane(pkey, lambda c=tuple(col):
                                           jnp.stack(c)))
        nparams = len(plans[0].params)
        if any(len(pl.params) != nparams for pl in plans):
            raise BatchFamilyMismatch("param-count mismatch")
        params_b = []
        for j in range(nparams):
            ps = [np.asarray(pl.params[j]) for pl in plans]
            p0 = ps[0]
            if any(p.shape != p0.shape or p.dtype != p0.dtype
                   for p in ps[1:]):
                raise BatchFamilyMismatch(f"param {j}: shape/dtype mismatch")
            if pad:
                ps = ps + [ps[-1]] * pad
            params_b.append(np.stack(ps))
        num_docs = np.asarray([s.num_docs for s in segments] + [0] * pad,
                              dtype=np.int32)
        return views, tuple(stacked), tuple(params_b), packed, num_docs

    def _dispatch_batch(self, segments: list, plans: list, mesh: tuple = (),
                        pack: bool = False):
        if faults.ACTIVE:
            faults.FAULTS.fire("device.dispatch",
                               segment=segments[0].name,
                               batch_size=len(segments))
        if TRACING.active_trace() is None:
            return self._dispatch_batch_inner(segments, plans, None,
                                              mesh=mesh, pack=pack)
        with TRACING.scope("family_dispatch") as span:
            reset_transfer_stats()
            try:
                span.set_attribute("numSegments", len(segments))
                return self._dispatch_batch_inner(segments, plans, span,
                                                  mesh=mesh, pack=pack)
            finally:
                _attach_dispatch_stats(span, self.cache)

    def _dispatch_batch_sharded(self, segments: list, plans: list, span,
                                ndev: int, pack: bool):
        """ONE sharded dispatch for the whole family: the [S, ...] stacks
        split across mesh[SEGMENT_AXIS] so every local chip runs S/ndev
        members concurrently, then results merge ON DEVICE (pack → flat on
        device 0, or raw gather over ICI) before the query's single host
        crossing. Per-row math is the solo vmap body — bit-identical."""
        from ..parallel import mesh as pmesh

        views, arrays, params_b, packed, num_docs = self._gather_batch(
            segments, plans, ndev=ndev)
        plan0 = plans[0]
        asig = tuple((str(a.dtype), tuple(a.shape)) for a in arrays)
        gkey = ("batchmesh", ndev, plan0.program, views[0].padded, packed,
                asig, len(segments))
        new_compile = _GUARD.note(gkey)
        if span is not None:
            span.set_attribute("mode", plan0.program.mode)
            span.set_attribute("padded", views[0].padded)
            span.set_attribute("meshDevices", ndev)
        t0 = time.perf_counter()
        outs = pmesh.run_program_batch_sharded(
            plan0.program, arrays, params_b, num_docs, views[0].padded,
            ndev, packed=packed)
        t1 = time.perf_counter()
        # counted only after the sharded dispatch succeeded: a trace-time
        # failure falls back to the solo path, which counts itself — so
        # numDeviceDispatches stays exactly one per family either way
        _count_dispatch(new_compile)
        compile_ms = round((t1 - t0) * 1000, 3) if new_compile else 0.0
        if new_compile:
            _register_compile(gkey, compile_ms, plan0.program,
                              views[0].padded, batch_size=len(segments),
                              mesh=(ndev,))
        else:
            _register_dispatch(gkey)
        if span is not None:
            span.set_attribute("compileMs", compile_ms)
            stamps = pmesh.block_per_device(outs, ndev, t1)
            span.set_attribute(
                "deviceExecMs", stamps[-1][1] if stamps else 0.0)
            for did, ms in stamps:
                with TRACING.scope(f"mesh_device:{did}") as dspan:
                    dspan.set_attribute("device", did)
                    dspan.set_attribute("deviceExecMs", ms)
        t2 = time.perf_counter()
        if pack:
            try:
                # preferred: shuffle-inside-the-program — all_gather over
                # the mesh axis + on-device pack, no dev0 funnel of raw outs
                result = pmesh.pack_outputs_collective(
                    outs, len(segments), ndev)
            except Exception as e:
                from .oom import HbmExhaustedError

                if isinstance(e, HbmExhaustedError):
                    raise
                result = pmesh.pack_outputs_gathered(outs, len(segments))
            sync_target = result.flat
        else:
            result = pmesh.gather_outputs(outs, len(segments))
            sync_target = result
        if span is not None:
            jax.block_until_ready(sync_target)
            combine_ms = round((time.perf_counter() - t2) * 1000, 3)
            span.set_attribute("crossChipCombineMs", combine_ms)
            try:
                from ..spi.metrics import SERVER_METRICS, ServerTimer

                SERVER_METRICS.update_timer(
                    ServerTimer.CROSS_CHIP_COMBINE_MS, combine_ms)
            except Exception:
                pass
        return result, views

    def _dispatch_batch_inner(self, segments: list, plans: list, span,
                              mesh: tuple = (), pack: bool = False):
        from ..ops.kernels import run_program_batch

        ndev = int(mesh[0]) if mesh else 1
        if ndev > 1 and len(segments) >= ndev:
            try:
                return self._dispatch_batch_sharded(segments, plans, span,
                                                    ndev, pack)
            except BatchFamilyMismatch:
                raise
            except Exception as e:
                from .oom import HbmExhaustedError

                if isinstance(e, HbmExhaustedError):
                    raise
                _warn_mesh_fallback(plans[0].program, e)
        views, arrays, params_b, packed, num_docs = self._gather_batch(
            segments, plans)
        plan0 = plans[0]
        # batch compiles are keyed per FAMILY (program, bucket, slot sig,
        # batch size) — the executable cache scales with families, not S
        asig = tuple((str(a.dtype), tuple(a.shape)) for a in arrays)
        gkey = ("batch", plan0.program, views[0].padded, packed, asig,
                len(segments))
        new_compile = _GUARD.note(gkey)
        _count_dispatch(new_compile)
        if span is None and not new_compile:
            _register_dispatch(gkey)
            outs = aot_call(gkey, arrays, params_b, num_docs) \
                if AOT_READY else None
            if outs is None:
                outs = run_program_batch(plan0.program, arrays, params_b,
                                         num_docs, views[0].padded,
                                         packed=packed)
            return outs, views
        if span is not None:
            span.set_attribute("mode", plan0.program.mode)
            span.set_attribute("padded", views[0].padded)
        t0 = time.perf_counter()
        outs = aot_call(gkey, arrays, params_b, num_docs) \
            if AOT_READY else None
        if outs is None:
            outs = run_program_batch(plan0.program, arrays, params_b,
                                     num_docs, views[0].padded,
                                     packed=packed)
        t1 = time.perf_counter()
        compile_ms = round((t1 - t0) * 1000, 3) if new_compile else 0.0
        if new_compile:
            _register_compile(gkey, compile_ms, plan0.program,
                              views[0].padded, batch_size=len(segments),
                              packed=packed,
                              aot_example=(arrays, params_b, num_docs))
        else:
            _register_dispatch(gkey)
        if span is None:
            return outs, views
        span.set_attribute("compileMs", compile_ms)
        jax.block_until_ready(outs)
        span.set_attribute("deviceExecMs",
                           round((time.perf_counter() - t1) * 1000, 3))
        return outs, views

    def dispatch_plan_batch(self, segments: list, plans: list,
                            mesh: tuple = ()):
        """ONE vmapped device dispatch for a whole batch family (equal
        batch_family_key). Returns a PackedOuts whose arrays carry a
        leading [S] dim; the caller slices row s for member s and feeds the
        slices through collect() unchanged — bit-for-bit what S separate
        dispatch_plan(..., fused='') calls would return, for one launch and
        one D2H transfer. With `mesh=(ndev,)` and S ≥ ndev the stack shards
        across the local device mesh and the byte-pack happens on device
        with the flat committed to device 0 — still one launch, one D2H.
        Raises BatchFamilyMismatch to request the per-segment fallback."""
        outs, _ = self._dispatch_batch(segments, plans, mesh=mesh, pack=True)
        return outs if isinstance(outs, PackedOuts) else pack_outputs(outs)

    def dispatch_plan_batch_raw(self, segments: list, plans: list,
                                mesh: tuple = ()):
        """dispatch_plan_batch without the flat-buffer packing: returns
        (outs, views) with every output carrying a leading [S] dim, for
        callers that keep computing on device (the batched sparse device
        combine slices per-member rows lazily — the slices never leave
        HBM). Mesh-sharded dispatches gather their outputs to device 0
        over ICI first so downstream device math colocates."""
        return self._dispatch_batch(segments, plans, mesh=mesh)

    def collect(self, query: QueryContext, segment: ImmutableSegment,
                plan: SegmentPlan, outs):
        """Materialize device outputs (blocks) and decode the intermediate."""
        outs = unpack_outputs(outs) if isinstance(outs, PackedOuts) \
            else [np.asarray(o) for o in outs]
        mode = plan.program.mode
        if mode == "selection":
            return self._selection_result(query, segment, plan, outs[0])
        if mode == "aggregation":
            states = [la.extract(outs, 0) for la in plan.lowered_aggs]
            return AggIntermediate(states, num_docs_scanned=int(outs[0][0]))
        return self._group_by_result(plan, outs)

    def _group_by_result(self, plan: SegmentPlan, outs) -> GroupByIntermediate:
        num_groups = plan.program.num_groups
        mv_docs = None
        if plan.program.mv_group_slot is not None:
            # MV expansion: pair counts ≠ docs; the kernel appends the
            # matched DOC count as one extra trailing output
            mv_docs = int(outs[-1][0])
            outs = outs[:-1]
        counts = outs[0][:num_groups]
        gids = np.nonzero(counts)[0]
        if plan.program.mode == "group_by_sparse":
            # sparse kernels emit the surviving composite keys as the last
            # output; gids are table slots, keys carry the dict-id composite
            composite = outs[-1][gids].astype(np.int64)
        else:
            composite = gids
        # decompose composite key → per-dim dict ids → values
        # (inverse of DictionaryBasedGroupKeyGenerator's cartesian key,
        # pinot-core/.../groupby/DictionaryBasedGroupKeyGenerator.java:119-137)
        key_cols = []
        for dim, stride in zip(plan.group_dims, plan.program.group_strides):
            ids = (composite // stride) % dim.cardinality
            key_cols.append(dim.dictionary.values[ids])
        scanned = int(counts.sum())
        trimmed = False
        if plan.program.mode == "group_by_sparse":
            # sparse trash slot = valid rows whose group was trimmed; they
            # were still scanned (reference reports all post-filter docs)
            trash = int(outs[0][num_groups])
            scanned += trash
            # an ORDER-BY-pushdown trim is exact — not a groups-limit event
            trimmed = trash > 0 and not plan.program.exact_trim
        if mv_docs is not None:
            scanned = mv_docs  # docs matched, not (doc × entry) pairs
        if all(la.vec is not None for la in plan.lowered_aggs):
            # columnar fast path: states stay numpy end-to-end (dict form
            # costs ~µs/group in Python — fatal at numGroupsLimit scale)
            return GroupArrays(
                [np.asarray(col) for col in key_cols],
                [la.vec.extract(outs, gids) for la in plan.lowered_aggs],
                [la.vec.spec for la in plan.lowered_aggs],
                [la.vec.fin_tag for la in plan.lowered_aggs],
                num_docs_scanned=scanned, groups_trimmed=trimmed)
        # per-agg batch extractors: prepare() runs once per output (e.g.
        # decoding the sparse distinct pair list in one vectorized pass)
        extractors = [
            la.prepare(outs) if la.prepare is not None
            else (lambda g, _la=la: _la.extract(outs, g))
            for la in plan.lowered_aggs]
        groups = {}
        for row, g in enumerate(gids):
            key = tuple(_to_python(col[row]) for col in key_cols)
            groups[key] = [ex(g) for ex in extractors]
        return GroupByIntermediate(groups, num_docs_scanned=scanned,
                                   groups_trimmed=trimmed)

    def _selection_result(self, query, segment, plan, mask) -> SelectionIntermediate:
        evaluator = None
        if plan.selection_exprs:
            from .host_executor import HostSegmentExecutor

            host = HostSegmentExecutor()
            evaluator = lambda e, doc_ids: host.eval_value_at(e, segment, doc_ids)  # noqa: E731
        # kernel emits the mask bit-packed (kernels.py selection mode);
        # decode through the repo's one little-endian bitmap helper
        from ..segment.bitpack import unpack_bitmap

        bits = unpack_bitmap(np.asarray(mask), segment.num_docs)
        return selection_from_mask(query, segment, plan.selection_columns,
                                   bits,
                                   extra_exprs=plan.selection_exprs or None,
                                   evaluator=evaluator)


def _to_python(v):
    if isinstance(v, np.generic):
        return v.item()
    return v
