"""EXPLAIN PLAN FOR — single-stage engine.

Reference: pinot-core's EXPLAIN output (ExplainPlanDataTableReducer et al.)
renders an operator tree as (Operator, Operator_Id, Parent_Id) rows:
BROKER_REDUCE → COMBINE → per-segment plan operators. Here the per-segment
"operators" are the kernel IR the query compiles to — one fused device
program — so the tree shows the program mode, the lowered filter algebra,
group dims/strides, and the primitive device reductions, plus which
segments pruned and whether the shape falls back to the host engine.
"""

from __future__ import annotations

from . import ir
from .aggregation import UnsupportedQueryError
from .plan import SegmentPlanner
from .results import DataSchema, ResultTable


def explain_plan(query, table, pruner, backend: str = "auto",
                 use_star_tree: bool = True) -> ResultTable:
    import copy

    from ..query.optimizer import optimize_filter

    # explain what EXECUTES: the same canonicalized filter the executor
    # runs (NOT elimination, EQ/IN + range merging, constant folding)
    query = copy.copy(query)
    query.filter = optimize_filter(query.filter)

    rows: list[list] = []
    next_id = [0]

    def add(op: str, parent: int) -> int:
        oid = next_id[0]
        next_id[0] += 1
        rows.append([op, oid, parent])
        return oid

    ob = ""
    if query.order_by_expressions:
        ob = ", sort:[" + ", ".join(map(str, query.order_by_expressions)) + "]"
    having = f", having:{query.having_filter}" if query.having_filter else ""
    root = add(f"BROKER_REDUCE(limit:{query.limit}{ob}{having})", -1)

    segments = [s for s in table.segments
                if not getattr(s, "is_mutable", False)]
    kept, pruned = pruner.prune(query, segments) if segments else ([], 0)
    mutable = len(table.segments) - len(segments)

    if query.is_aggregation_query or query.is_group_by or query.distinct:
        combine = "COMBINE_GROUP_BY" if (query.is_group_by or query.distinct) \
            else "COMBINE_AGGREGATE"
    else:
        combine = "COMBINE_SELECT"
    cid = add(f"{combine}(segments:{len(kept)}, pruned:{pruned}"
              + (f", consuming(host):{mutable}" if mutable else "") + ")",
              root)

    if not kept:
        add("EMPTY(no immutable segments matched)", cid)
        return _table(rows)

    # mirror _segment_route: star-tree rewrite happens before planning
    plan_query, plan_seg = query, kept[0]
    star = None
    if use_star_tree and getattr(kept[0], "valid_doc_ids", None) is None:
        from ..segment.startree import try_rewrite

        star = try_rewrite(query, kept[0])
        if star is not None:
            plan_query, plan_seg = star.query, star.view
            cid = add("FILTER_STARTREE_INDEX(pre-aggregated docs)", cid)

    try:
        plan = SegmentPlanner(plan_query, plan_seg).plan()
    except UnsupportedQueryError as e:
        add(f"HOST_ENGINE(numpy fallback: {e})", cid)
        return _table(rows)

    engine = "HOST_KERNEL" if backend == "host" else "DEVICE_KERNEL"
    p = plan.program
    desc = f"{engine}(mode:{p.mode}"
    if p.mode in ("group_by", "group_by_sparse"):
        dims = ", ".join(f"{d.column}[card:{d.cardinality}]"
                         for d in plan.group_dims)
        desc += f", groups:{p.num_groups}, dims:[{dims}]"
        # the concrete group-by kernel variant: dense segment_sum table,
        # MXU one-hot matmul, or one of the sparse sort strategies
        # (ir.sparse_groupby_path mirrors the kernel's branch)
        path = "dense"
        if p.mode == "group_by_sparse":
            path = ir.sparse_groupby_path(p)
            desc += f", key_space:{p.key_space}"
            if p.exact_trim:
                desc += ", orderByTrim:exact"
        if p.mv_group_slot is not None:
            desc += ", mvExpansion:true"
        if backend != "host":
            from ..ops import fused_groupby

            if fused_groupby.plan(p, None) is not None:
                # single-pass MXU kernel shape (ops/fused_groupby.py);
                # actual use still depends on plane dtypes + backend
                desc += ", fusedMxu:eligible"
                if p.mode == "group_by":
                    path = "mxu"
        desc += f", path:{path}"
    kid = add(desc + ")", cid)

    if getattr(query, "explain", False) == "implementation" and \
            p.mode in ("group_by", "group_by_sparse"):
        # implementation mode also names HOW the per-segment tables merge:
        # the sparse device concat+edge-reduce, the columnar factorize/
        # scatter merge, or the per-group dict merge fallback
        import numpy as np

        vec_ok = all(la.vec is not None for la in plan.lowered_aggs)
        if (p.mode == "group_by_sparse" and backend != "host"
                and len(kept) > 1 and p.group_strides == (1,)
                and len(p.group_slots) == 1 and plan.group_dims and vec_ok
                and np.issubdtype(
                    plan.group_dims[0].dictionary.values.dtype, np.integer)):
            impl = "device-sparse(concat+edge-reduce)"
        elif vec_ok:
            impl = "host-columnar-scatter"
        else:
            impl = "host-dict-merge"
        add(f"SERVER_COMBINE(impl:{impl}, segments:{len(kept)})", cid)

    if getattr(query, "explain", False) == "implementation" and \
            backend != "host" and len(kept) > 1:
        # stacked segment batching: families = device dispatches
        # (query_executor._batch_families over the same host-side key the
        # dispatcher groups by)
        from .executor import batch_family_key

        if str(query.query_options.get("segmentBatch")).lower() in (
                "false", "0", "off"):
            add("SEGMENT_BATCH(disabled)", cid)
        else:
            fams: set = set()
            planned = 0
            for seg in kept:
                pq, ps = query, seg
                if use_star_tree and getattr(
                        seg, "valid_doc_ids", None) is None:
                    from ..segment.startree import try_rewrite

                    st = try_rewrite(query, seg)
                    if st is not None:
                        pq, ps = st.query, st.view
                try:
                    pl = SegmentPlanner(pq, ps).plan()
                except UnsupportedQueryError:
                    continue
                fk = batch_family_key(ps, pl)
                fams.add(fk if fk is not None else ("solo", id(ps)))
                planned += 1
            if planned:
                add(f"SEGMENT_BATCH(families:{len(fams)}, "
                    f"segments:{planned})", cid)

    for a in query.aggregations:
        # SQL-level functions; COUNT(*) answers from the shared per-group
        # count column and registers no primitive op of its own
        add(f"AGGREGATE(fn:{a})", kid)
    reduce_tag = "HOST_REDUCE" if backend == "host" else "DEVICE_REDUCE"
    for agg in p.aggs:
        label = f"{reduce_tag}(op:{agg.kind}"
        if agg.card is not None:
            label += f", card:{agg.card}"
        if agg.bins is not None:
            label += f", bins:{agg.bins}"
        if agg.vmin is not None:
            label += f", bounds:[{agg.vmin},{agg.vmax}]"
        add(label + ")", kid)
    if not p.aggs and p.mode == "selection":
        cols = ", ".join(str(e) for e in query.select_expressions)
        add(f"SELECT(columns:[{cols}])", kid)

    fid = add("FILTER" if p.filter is not None else "MATCH_ALL", kid)
    if p.filter is not None:
        _walk_filter(p.filter, fid, add)
    return _table(rows)


# span attributes rendered on EXPLAIN ANALYZE nodes, in display order;
# everything else (HBM gauge snapshots, internals) stays in trace_info
_ANALYZE_ATTRS = ("segment", "numSegments", "segments", "device",
                  "meshDevices", "mode", "padded",
                  "fused", "workers", "leaf_pushdown", "rows_in", "rows_out",
                  "shuffled_rows", "shuffled_bytes", "join_impl",
                  "cross_stage_bytes", "device_partition_ms",
                  "host_crossings", "compileMs",
                  "deviceExecMs", "crossChipCombineMs", "transferBytes",
                  "cache")


def _cache_outcome(resp) -> str:
    """One word for the run's cache behaviour: broker result-cache outcome
    when known, else the segment-cache hit/miss counters."""
    outcome = getattr(resp, "cache_outcome", None)
    if outcome == "hit":
        return "hit"
    hits = getattr(resp, "num_segments_cache_hit", 0)
    misses = getattr(resp, "num_segments_cache_miss", 0)
    if hits and not misses and not getattr(resp, "num_device_dispatches", 0):
        return "hit"
    if hits and misses:
        return "partial"
    if misses:
        return "miss"
    if hits:
        return "hit"
    # no segment-cache traffic at all: report the broker result-cache
    # outcome (a cacheable run that missed is "miss", bypass is "off")
    return "miss" if outcome == "miss" else "off"


def analyze_table(trace_json: list, resp, table_name: str = "") -> ResultTable:
    """Render an executed run's span tree as the (Operator, Operator_Id,
    Parent_Id) plan table, each node annotated with its observed stats —
    the EXPLAIN ANALYZE product. Works on both the engine-local trace
    (integer span ids) and the broker's merged cross-server trace
    (ids namespaced ``instance:id``); spans whose parent is missing attach
    to the root so a partial trace still renders one connected tree."""
    rows: list[list] = []
    next_id = [0]

    def add(op: str, parent: int) -> int:
        oid = next_id[0]
        next_id[0] += 1
        rows.append([op, oid, parent])
        return oid

    n_rows = len(resp.result_table.rows) if getattr(
        resp, "result_table", None) is not None else 0
    parts = [f"table:{table_name}"] if table_name else []
    parts += [f"rows:{n_rows}",
              f"timeMs:{round(getattr(resp, 'time_used_ms', 0.0), 3)}",
              f"docsScanned:{getattr(resp, 'num_docs_scanned', 0)}",
              f"segments:{getattr(resp, 'num_segments_processed', 0)}",
              f"dispatches:{getattr(resp, 'num_device_dispatches', 0)}",
              f"compiles:{getattr(resp, 'num_compiles', 0)}",
              f"cacheHit:{getattr(resp, 'num_segments_cache_hit', 0)}",
              f"cacheMiss:{getattr(resp, 'num_segments_cache_miss', 0)}",
              f"cache:{_cache_outcome(resp)}"]
    if getattr(resp, "num_hedged_requests", 0):
        parts.append(f"hedged:{resp.num_hedged_requests}")
    if getattr(resp, "num_scatter_retries", 0):
        parts.append(f"retries:{resp.num_scatter_retries}")
    if getattr(resp, "num_coalesced_queries", 0):
        parts.append(f"coalescedWith:{resp.num_coalesced_queries}")
        parts.append(
            f"coalesceWaitMs:{round(getattr(resp, 'coalesce_wait_ms', 0.0), 3)}")
    root = add("EXPLAIN_ANALYZE(" + ", ".join(parts) + ")", -1)

    by_span: dict = {}  # trace spanId -> plan row id
    for s in trace_json:
        label = s.get("operator", "?")
        bits = []
        attrs = s.get("attributes") or {}
        for k in _ANALYZE_ATTRS:
            if k in attrs:
                bits.append(f"{k}:{attrs[k]}")
        bits.append(f"ms:{s.get('durationMs', 0.0)}")
        server = s.get("server")
        if server:
            label = f"{server}/{label}"
        parent = by_span.get(s.get("parentId"), root)
        by_span[s.get("spanId")] = add(
            label + "(" + ", ".join(bits) + ")", parent)
    if not trace_json:
        if getattr(resp, "cache_outcome", None) == "hit":
            # broker result-cache hit: nothing executed, no spans — the
            # whole answer came from the cache tier
            add(f"RESULT_CACHE(hit, rows:{n_rows}, dispatches:0)", root)
        else:
            add("NO_TRACE(execution recorded no spans)", root)
    return _table(rows)


def _walk_filter(node, parent: int, add) -> None:
    if isinstance(node, ir.FAnd):
        oid = add("AND", parent)
        for c in node.children:
            _walk_filter(c, oid, add)
    elif isinstance(node, ir.FOr):
        oid = add("OR", parent)
        for c in node.children:
            _walk_filter(c, oid, add)
    elif isinstance(node, ir.FNot):
        oid = add("NOT", parent)
        _walk_filter(node.child, oid, add)
    elif isinstance(node, ir.Interval):
        add(f"RANGE(slot dict-id/value interval, "
            f"inclusive:[{node.lo_inclusive},{node.hi_inclusive}])", parent)
    elif isinstance(node, ir.Lut):
        add(f"DICT_LUT(ids_slot:{node.ids_slot}, mv:{node.mv})", parent)
    elif isinstance(node, ir.Isin):
        add("RAW_IN", parent)
    elif isinstance(node, ir.Null):
        add(f"IS_NULL(slot:{node.null_slot})", parent)
    elif isinstance(node, ir.MaskParam):
        add("HOST_INDEX_MASK(text/json/vector posting list)", parent)
    elif isinstance(node, ir.FConst):
        add(f"CONST({node.value})", parent)
    else:
        add(type(node).__name__.upper(), parent)


def _table(rows) -> ResultTable:
    return ResultTable(
        DataSchema(["Operator", "Operator_Id", "Parent_Id"],
                   ["STRING", "INT", "INT"]), rows)
