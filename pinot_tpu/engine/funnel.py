"""FUNNEL aggregation family.

Reference analogues being replaced:
- pinot-core/.../query/aggregation/function/funnel/
  FunnelCountAggregationFunction.java (+ Set/Bitmap strategies):
  FUNNEL_COUNT(STEPS(expr, ...), CORRELATE_BY(col)[, SETTINGS(...)]) —
  per-step conversion counts: count of correlation values that matched
  step 0..i (cascading set intersection at finalize,
  SetMergeStrategy.extractFinalResult).
- pinot-core/.../aggregation/function/funnel/window/
  FunnelBaseAggregationFunction.java + FunnelMaxStep/FunnelMatchStep/
  FunnelCompleteCount: FUNNEL_*(tsExpr, windowSize, numSteps, stepExpr...,
  [mode...]) — rows become (timestamp, firstMatchingStep) events, merged
  across segments as a sorted queue, finalized with a sliding-window scan
  honoring STRICT_DEDUPLICATION / STRICT_ORDER / STRICT_INCREASE /
  KEEP_ALL and MAXSTEPDURATION.

TPU-first shape: the per-row work (step predicate masks, first-step
selection, event extraction) is whole-segment vectorized numpy/JAX-ready
column algebra; only the tiny per-group event-sequence scan at FINALIZE is
sequential Python — the same split the engine uses for exprmin/percentile
states. Intermediate states are plain numpy arrays / sets, so they ride
DataTables across servers unchanged.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..query.expressions import ExpressionContext, FunctionContext

WINDOW_FNS = frozenset(
    ("funnelmaxstep", "funnelmatchstep", "funnelcompletecount"))
FUNNEL_FNS = WINDOW_FNS | {"funnelcount"}

_MODES = ("STRICT_DEDUPLICATION", "STRICT_ORDER", "STRICT_INCREASE",
          "KEEP_ALL")


class FunnelParseError(Exception):
    pass


# ---------------------------------------------------------------------------
# Specs
# ---------------------------------------------------------------------------


@dataclass
class FunnelCountSpec:
    step_exprs: list  # boolean ExpressionContexts
    correlate_expr: ExpressionContext
    settings: tuple = ()

    @property
    def num_steps(self) -> int:
        return len(self.step_exprs)


@dataclass
class FunnelWindowSpec:
    name: str
    ts_expr: ExpressionContext
    window: int
    num_steps: int
    step_exprs: list
    modes: set = field(default_factory=set)
    max_step_duration: int = 0


def parse_funnel(fn: FunctionContext):
    if fn.name == "funnelcount":
        return _parse_count(fn)
    return _parse_window(fn)


def _parse_count(fn: FunctionContext) -> FunnelCountSpec:
    steps = None
    correlate = None
    settings: tuple = ()
    for a in fn.arguments:
        inner = a.function if a.is_function else None
        if inner is not None and inner.name == "steps":
            steps = list(inner.arguments)
        elif inner is not None and inner.name in ("correlateby", "correlate_by"):
            if not inner.arguments:
                raise FunnelParseError("CORRELATE_BY needs a column")
            correlate = inner.arguments[0]
        elif inner is not None and inner.name == "settings":
            settings = tuple(str(x.literal) for x in inner.arguments)
        else:
            raise FunnelParseError(
                f"FUNNEL_COUNT argument must be STEPS(...)/CORRELATE_BY(...)"
                f"/SETTINGS(...), got {a}")
    if not steps or correlate is None:
        raise FunnelParseError(
            "FUNNEL_COUNT requires STEPS(...) and CORRELATE_BY(...)")
    # settings select a counting strategy in the reference (bitmap / set /
    # theta_sketch / partitioned / sorted); every strategy answers the same
    # counts modulo sketch error — this engine always counts exactly, so
    # settings are accepted and ignored.
    return FunnelCountSpec(steps, correlate, settings)


def _parse_window(fn: FunctionContext) -> FunnelWindowSpec:
    args = fn.arguments
    if len(args) < 4:
        raise FunnelParseError(
            f"{fn.name} expects (tsExpr, windowSize, numSteps, stepExpr...)")
    try:
        window = int(args[1].literal)
        num_steps = int(args[2].literal)
    except (TypeError, ValueError, AttributeError) as e:
        raise FunnelParseError(
            f"{fn.name}: windowSize/numSteps must be integer literals") from e
    if window <= 0:
        raise FunnelParseError("window size must be > 0")
    if len(args) < 3 + num_steps:
        raise FunnelParseError(
            f"{fn.name}: expected {num_steps} step expressions")
    spec = FunnelWindowSpec(fn.name, args[0], window, num_steps,
                            list(args[3:3 + num_steps]))
    # extras: bare mode names, or MODE=A,B / MAXSTEPDURATION=n key-values
    # (reference FunnelConfigs)
    for a in args[3 + num_steps:]:
        raw = str(a.literal).upper().strip()
        if "=" in raw:
            k, v = (x.strip() for x in raw.split("=", 1))
            if k == "MAXSTEPDURATION":
                spec.max_step_duration = int(v)
                if spec.max_step_duration <= 0:
                    raise FunnelParseError("MaxStepDuration must be > 0")
            elif k == "MODE":
                for m in v.split(","):
                    m = m.strip()
                    if m not in _MODES:
                        raise FunnelParseError(f"unrecognized funnel mode {m}")
                    spec.modes.add(m)
            else:
                raise FunnelParseError(f"unrecognized argument {raw}")
        elif raw in _MODES:
            spec.modes.add(raw)
        else:
            raise FunnelParseError(f"unrecognized funnel mode {raw}")
    return spec


# ---------------------------------------------------------------------------
# Row → state (vectorized per segment)
# ---------------------------------------------------------------------------


def window_row_arrays(executor, spec: FunnelWindowSpec, segment):
    """(ts int64, step int32, valid bool) whole-segment arrays. Step = the
    FIRST matching step expression (reference scans steps in order and
    breaks on the first hit); rows matching none are invalid unless
    KEEP_ALL, which emits step -1 dummy events."""
    n = segment.num_docs
    ts = np.asarray(executor.eval_value(spec.ts_expr, segment),
                    dtype=np.int64)
    step = np.full(n, -1, dtype=np.int32)
    found = np.zeros(n, dtype=bool)
    for j, e in enumerate(spec.step_exprs):
        m = executor._clause_mask(e, segment, False)
        step[~found & m] = j
        found |= m
    valid = np.ones(n, dtype=bool) if "KEEP_ALL" in spec.modes else found
    return ts, step, valid


def window_state(ts: np.ndarray, step: np.ndarray, rows: np.ndarray):
    """Intermediate state: the group's (ts, step) event arrays (unsorted —
    the merge is concat, ordering happens once at finalize, mirroring the
    reference's priority-queue merge)."""
    return (np.ascontiguousarray(ts[rows]), np.ascontiguousarray(step[rows]))


def merge_window_state(a, b):
    return (np.concatenate([a[0], b[0]]), np.concatenate([a[1], b[1]]))


def count_row_arrays(executor, spec: FunnelCountSpec, segment):
    """(correlate values, [step masks]) whole-segment arrays."""
    corr = np.asarray(executor.eval_value(spec.correlate_expr, segment))
    masks = [executor._clause_mask(e, segment, False)
             for e in spec.step_exprs]
    return corr, masks


def count_state(corr: np.ndarray, masks: list, rows: np.ndarray):
    """Per-step sets of correlation values that matched that step."""
    cr = corr[rows]
    return [set(np.unique(cr[m[rows]]).tolist()) for m in masks]


def merge_count_state(a, b):
    return [x | y for x, y in zip(a, b)]


def finalize_count(sets) -> list:
    """Cascading intersection (reference SetMergeStrategy
    .extractFinalResult): counts[i] = |S0 ∩ … ∩ Si|."""
    out = []
    running = None
    for s in sets:
        running = set(s) if running is None else (running & s)
        out.append(len(running))
    return out


# ---------------------------------------------------------------------------
# Finalize: sliding-window scans (reference FunnelBaseAggregationFunction)
# ---------------------------------------------------------------------------


def _sorted_events(state):
    ts, step = state
    if len(ts) == 0:
        return ts, step
    order = np.lexsort((step, ts))  # ts asc, step asc — FunnelStepEvent order
    return ts[order], step[order]


class _EventQueue:
    """Pointer over the sorted event arrays, deque-compatible with the
    reference's PriorityQueue consumption pattern."""

    def __init__(self, ts, step):
        self.ts = ts
        self.step = step
        self.i = 0

    def empty(self):
        return self.i >= len(self.ts)

    def peek(self):
        return self.ts[self.i], self.step[self.i]

    def poll(self):
        e = (int(self.ts[self.i]), int(self.step[self.i]))
        self.i += 1
        return e


def _fill_window(q: _EventQueue, win: deque, spec: FunnelWindowSpec) -> None:
    """Slide so the window starts at a step-0 event, then absorb events
    inside [start, start+window) (bounded by MAXSTEPDURATION gaps)."""
    while win and win[0][1] != 0:
        win.popleft()
    if not win:
        while not q.empty() and q.peek()[1] != 0:
            q.poll()
        if q.empty():
            return
        win.append(q.poll())
    window_end = win[0][0] + spec.window
    while not q.empty() and q.peek()[0] < window_end:
        if spec.max_step_duration > 0 and \
                q.peek()[0] - win[-1][0] > spec.max_step_duration:
            break
        win.append(q.poll())


def _scan_max_step(win: deque, spec: FunnelWindowSpec) -> int:
    """Longest step prefix within one window (FunnelMaxStep.processWindow)."""
    dedup = "STRICT_DEDUPLICATION" in spec.modes
    order = "STRICT_ORDER" in spec.modes
    increase = "STRICT_INCREASE" in spec.modes
    max_step = 0
    prev_ts = -1
    for ts, step in win:
        if dedup and step == max_step - 1:
            return max_step
        if order and step != max_step:
            return max_step
        if increase and prev_ts == ts:
            continue
        if max_step == step:
            max_step += 1
            prev_ts = ts
        if max_step == spec.num_steps:
            break
    return max_step


def max_step(state, spec: FunnelWindowSpec) -> int:
    ts, step = _sorted_events(state)
    q = _EventQueue(ts, step)
    win: deque = deque()
    best = 0
    while not q.empty() or win:
        _fill_window(q, win, spec)
        if not win:
            break
        best = max(best, _scan_max_step(win, spec))
        if best == spec.num_steps:
            break
        if win:
            win.popleft()
    return best


def match_step(state, spec: FunnelWindowSpec) -> list:
    """[1]*maxStep + [0]*(numSteps-maxStep) (FunnelMatchStep)."""
    m = max_step(state, spec)
    return [1] * m + [0] * (spec.num_steps - m)


def complete_count(state, spec: FunnelWindowSpec) -> int:
    """Number of completed funnel rounds (FunnelCompleteCount): maxStep
    RESETS (not returns) on mode violations, and a completed round resets
    the scan with the window re-anchored past the completing event."""
    dedup = "STRICT_DEDUPLICATION" in spec.modes
    order = "STRICT_ORDER" in spec.modes
    increase = "STRICT_INCREASE" in spec.modes
    ts_a, step_a = _sorted_events(state)
    q = _EventQueue(ts_a, step_a)
    win: deque = deque()
    total = 0
    while not q.empty() or win:
        _fill_window(q, win, spec)
        if not win:
            break
        window_start = win[0][0]
        max_stp = 0
        prev_ts = -1
        for ts, step in win:
            if dedup and step == max_stp - 1:
                max_stp = 0
            if order and step != max_stp:
                max_stp = 0
            if increase and prev_ts == ts:
                continue
            prev_ts = ts
            if max_stp == step:
                max_stp += 1
            if max_stp == spec.num_steps:
                total += 1
                max_stp = 0
                window_start = ts
        if win:
            win.popleft()
        while win and win[0][0] < window_start:
            win.popleft()
    return total


# ---------------------------------------------------------------------------
# AggSemantics wiring (engine/aggregation.py dispatches funnel names here)
# ---------------------------------------------------------------------------


def funnel_semantics(fn: FunctionContext):
    """AggSemantics for a funnel expression (imported lazily by
    aggregation.semantics_for to avoid a module cycle)."""
    from .aggregation import AggSemantics

    spec = parse_funnel(fn)
    if isinstance(spec, FunnelCountSpec):
        return AggSemantics(
            merge=merge_count_state,
            finalize=finalize_count,
            result_type="LONG_ARRAY",
            empty_value=[0] * spec.num_steps)
    if spec.name == "funnelmaxstep":
        return AggSemantics(merge_window_state,
                            lambda s, _sp=spec: int(max_step(s, _sp)),
                            "INT", 0)
    if spec.name == "funnelmatchstep":
        return AggSemantics(merge_window_state,
                            lambda s, _sp=spec: match_step(s, _sp),
                            "INT_ARRAY", [0] * spec.num_steps)
    return AggSemantics(merge_window_state,
                        lambda s, _sp=spec: int(complete_count(s, _sp)),
                        "LONG", 0)
