"""Gapfill post-processing for time-bucketed group-by results.

Reference: BaseGapfillProcessor / GapfillProcessor (pinot-core/.../query/
reduce/BaseGapfillProcessor.java) — a gapfill query names a time-bucket
expression plus [start, end) and the bucket width; the reducer inserts a row
for every missing (series, bucket) pair, with per-column fill strategies:

    SELECT gapfill(<bucket_expr>, <startMs>, <endMs>, <bucketMs>), key...,
           fill(SUM(m), 'FILL_PREVIOUS_VALUE') ...
    GROUP BY gapfill(<bucket_expr>, ...), key...

``gapfill`` and ``fill`` evaluate as identity transforms during execution
(query/transforms.py) — the bucketing itself is the user's expression, as in
the reference where GapFill wraps the subquery's time column. Series keys
default to every non-time group-by output (the reference's TIMESERIESON).
Fill modes: FILL_PREVIOUS_VALUE (last seen value in the series, scanning
buckets ascending) and FILL_DEFAULT_VALUE (type default); columns without a
FILL wrapper fill with null. Rows outside [start, end) are dropped; output
is time-major (bucket asc, then series), offset/limit apply after filling.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..query.context import QueryContext
from .results import DataSchema, ResultTable

FILL_PREVIOUS = "FILL_PREVIOUS_VALUE"
FILL_DEFAULT = "FILL_DEFAULT_VALUE"

_TYPE_DEFAULTS = {"INT": 0, "LONG": 0, "FLOAT": 0.0, "DOUBLE": 0.0,
                  "BOOLEAN": False, "TIMESTAMP": 0}


@dataclass
class GapfillSpec:
    time_idx: int
    start: int
    end: int
    bucket: int
    fill_modes: dict = field(default_factory=dict)  # select idx → mode
    series_idxs: list = field(default_factory=list)
    value_idxs: list = field(default_factory=list)


def extract_gapfill(query: QueryContext) -> Optional[GapfillSpec]:
    time_idx = None
    spec_args = None
    fill_modes: dict[int, str] = {}
    for i, se in enumerate(query.select_expressions):
        if se.is_function and se.function.name == "gapfill":
            if len(se.function.arguments) < 4:
                continue
            time_idx = i
            spec_args = se.function.arguments[1:4]
        elif se.is_function and se.function.name == "fill":
            args = se.function.arguments
            if len(args) >= 2 and args[1].is_literal:
                fill_modes[i] = str(args[1].literal).upper()
    if time_idx is None:
        return None
    try:
        start, end, bucket = (int(a.literal) for a in spec_args)
    except (TypeError, ValueError):
        return None
    if bucket <= 0 or end < start:
        return None
    group_strs = {str(g) for g in query.group_by_expressions}
    series, values = [], []
    for i, se in enumerate(query.select_expressions):
        if i == time_idx:
            continue
        (series if str(se) in group_strs else values).append(i)
    return GapfillSpec(time_idx, start, end, bucket, fill_modes, series, values)


MAX_GAPFILL_BUCKETS = 200_000
MAX_GAPFILL_ROWS = 2_000_000


def apply_gapfill(result: ResultTable, spec: GapfillSpec) -> ResultTable:
    n_cols = len(result.schema.column_names)
    num_buckets = (spec.end - spec.start + spec.bucket - 1) // spec.bucket
    if num_buckets > MAX_GAPFILL_BUCKETS:
        raise ValueError(
            f"gapfill would materialize {num_buckets} buckets "
            f"(limit {MAX_GAPFILL_BUCKETS}); widen the bucket or narrow "
            f"[start, end)")
    buckets = list(range(spec.start, spec.end, spec.bucket))
    # (series key tuple) → {bucket: row}
    by_series: dict[tuple, dict[int, list]] = {}
    series_order: list[tuple] = []
    for row in result.rows:
        t = row[spec.time_idx]
        if t is None:
            continue
        t = int(t)
        if not spec.start <= t < spec.end:
            continue
        key = tuple(row[i] for i in spec.series_idxs)
        if key not in by_series:
            by_series[key] = {}
            series_order.append(key)
        # snap to the bucket grid so observed and filled rows share the same
        # time axis; two result rows landing in one (series, bucket) would
        # mean the time expression is finer than the bucket — aggregates of
        # sub-buckets cannot be merged post-hoc, so reject loudly instead of
        # silently dropping rows
        b = spec.start + ((t - spec.start) // spec.bucket) * spec.bucket
        if b in by_series[key]:
            raise ValueError(
                "gapfill time expression produces multiple rows per bucket "
                f"(series {key}, bucket {b}); bucket-align the group-by "
                "time expression to the gapfill bucket width")
        if t != b:
            row = list(row)
            row[spec.time_idx] = b
        by_series[key][b] = row
    if num_buckets * max(1, len(series_order)) > MAX_GAPFILL_ROWS:
        raise ValueError(
            f"gapfill would emit {num_buckets * len(series_order)} rows "
            f"(limit {MAX_GAPFILL_ROWS})")

    types = result.schema.column_types
    out: list[list] = []
    for key in series_order:
        seen = by_series[key]
        prev: dict[int, object] = {}
        for b in buckets:
            row = seen.get(b)
            if row is not None:
                for vi in spec.value_idxs:
                    prev[vi] = row[vi]
                out.append(row)
                continue
            filled = [None] * n_cols
            filled[spec.time_idx] = b
            for si, kv in zip(spec.series_idxs, key):
                filled[si] = kv
            for vi in spec.value_idxs:
                mode = spec.fill_modes.get(vi)
                if mode == FILL_PREVIOUS and vi in prev:
                    filled[vi] = prev[vi]
                elif mode in (FILL_PREVIOUS, FILL_DEFAULT):
                    filled[vi] = _TYPE_DEFAULTS.get(types[vi])
                # no FILL wrapper → null
            out.append(filled)
    # time-major: bucket asc, then series in first-seen order
    series_rank = {k: i for i, k in enumerate(series_order)}
    out.sort(key=lambda r: (r[spec.time_idx],
                            series_rank[tuple(r[i] for i in spec.series_idxs)]))
    return ResultTable(DataSchema(result.schema.column_names, types), out)
