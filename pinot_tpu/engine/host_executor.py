"""Host (numpy) per-segment engine — fallback path + differential oracle.

Role mirrors the reference's scalar CPU engine remaining the default while
the TPU backend handles supported shapes (BASELINE.json: "the existing CPU
path remains the default"). Semantics here define correctness: the device
executor must produce identical intermediates (tests/test_queries.py runs
both and compares). Kept deliberately simple — vectorized numpy where easy,
python where not — clarity over speed.
"""

from __future__ import annotations

import re

import numpy as np

from ..query.context import QueryContext
from ..query.expressions import ExpressionContext
from ..query.filter import FilterContext, FilterNodeType, Predicate, PredicateType
from ..segment.loader import ImmutableSegment
from ..query.transforms import get_transform
from . import funnel
from .aggregation import (
    VEC_RECIPES,
    UnsupportedQueryError,
    host_state,
    host_state_full,
    split_args,
)
from .plan import like_to_regex
from .results import AggIntermediate, GroupByIntermediate, SelectionIntermediate
from .selection import selection_from_mask


class HostSegmentExecutor:
    def execute(self, query: QueryContext, segment: ImmutableSegment):
        mask = self._filter_mask(query.filter, segment,
                                 nh=query.null_handling)
        if query.is_aggregation_query or query.distinct or query.is_group_by:
            group_exprs = list(query.group_by_expressions)
            if query.distinct and not query.is_aggregation_query:
                group_exprs = list(query.select_expressions)
            if group_exprs:
                return self._group_by(query, segment, mask, group_exprs)
            return self._aggregation(query, segment, mask)
        return self._selection(query, segment, mask)

    # -- filter ------------------------------------------------------------
    def _filter_mask(self, f, segment: ImmutableSegment,
                     nh: bool = False) -> np.ndarray:
        n = segment.num_docs
        if f is None:
            mask = np.ones(n, dtype=bool)
        elif nh:
            mask, _unknown = self._eval_filter3(f, segment)
        else:
            mask = self._eval_filter(f, segment)
        vd = getattr(segment, "valid_doc_ids", None)
        if vd is not None:  # upsert validity plane (see plan._and_valid_docs)
            mask = mask & vd.mask(n)
        return mask

    def _eval_filter(self, f: FilterContext, segment) -> np.ndarray:
        n = segment.num_docs
        if f.type == FilterNodeType.AND:
            m = np.ones(n, dtype=bool)
            for c in f.children:
                m &= self._eval_filter(c, segment)
            return m
        if f.type == FilterNodeType.OR:
            m = np.zeros(n, dtype=bool)
            for c in f.children:
                m |= self._eval_filter(c, segment)
            return m
        if f.type == FilterNodeType.NOT:
            return ~self._eval_filter(f.children[0], segment)
        if f.type == FilterNodeType.CONSTANT:
            return np.full(n, f.constant_value, dtype=bool)
        return self._eval_predicate(f.predicate, segment)

    def _eval_filter3(self, f: FilterContext, segment):
        """Kleene 3-valued evaluation → (definitely-true, unknown) masks;
        mirrors plan.SegmentPlanner._lower_filter3."""
        n = segment.num_docs
        if f.type == FilterNodeType.AND:
            t = np.ones(n, dtype=bool)
            tu = np.ones(n, dtype=bool)  # true-or-unknown
            for c in f.children:
                ct, cu = self._eval_filter3(c, segment)
                t &= ct
                tu &= ct | cu
            return t, tu & ~t
        if f.type == FilterNodeType.OR:
            t = np.zeros(n, dtype=bool)
            u = np.zeros(n, dtype=bool)
            for c in f.children:
                ct, cu = self._eval_filter3(c, segment)
                t |= ct
                u |= cu
            return t, u & ~t
        if f.type == FilterNodeType.NOT:
            ct, cu = self._eval_filter3(f.children[0], segment)
            return ~ct & ~cu, cu
        if f.type == FilterNodeType.CONSTANT:
            return (np.full(n, f.constant_value, dtype=bool),
                    np.zeros(n, dtype=bool))
        m = self._eval_predicate(f.predicate, segment)
        if f.predicate.type in (PredicateType.IS_NULL,
                                PredicateType.IS_NOT_NULL):
            return m, np.zeros(n, dtype=bool)
        u = self._nulls_of(f.predicate.lhs.columns(), segment, n)
        return m & ~u, u

    def _nulls_of(self, cols, segment, n) -> np.ndarray:
        out = np.zeros(n, dtype=bool)
        for c in sorted(cols):
            if segment.has_column(c):
                nb = segment.get_null_bitmap(c)
                if nb is not None:
                    out |= nb
        return out

    def _eval_predicate(self, p: Predicate, segment) -> np.ndarray:
        n = segment.num_docs
        if p.type in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
            col = p.lhs.identifier
            nulls = segment.get_null_bitmap(col)
            m = np.zeros(n, dtype=bool) if nulls is None else nulls.copy()
            return ~m if p.type == PredicateType.IS_NOT_NULL else m
        if p.type in (PredicateType.JSON_MATCH, PredicateType.TEXT_MATCH,
                      PredicateType.VECTOR_SIMILARITY):
            return eval_host_mask(p, segment)
        geo = self._eval_geo_range(p, segment)
        if geo is not None:
            return geo

        m = self._eval_predicate_with_index(p, segment)
        if m is not None:
            return m

        # MV columns: row matches if ANY value matches (reference MV predicate
        # semantics)
        if p.lhs.is_identifier and not segment.column_metadata(p.lhs.identifier).single_value:
            return self._eval_mv_predicate(p, segment)

        mm = eval_map_index_predicate(p, segment)
        if mm is not None:
            return mm

        v = self.eval_value(p.lhs, segment)
        return self._compare_values(p, v, n)

    def _compare_values(self, p: Predicate, v: np.ndarray, n: int) -> np.ndarray:
        if p.type == PredicateType.EQ:
            return v == _coerce_to(v, p.values[0])
        if p.type == PredicateType.NOT_EQ:
            return v != _coerce_to(v, p.values[0])
        if p.type in (PredicateType.IN, PredicateType.NOT_IN):
            m = np.zeros(n, dtype=bool)
            for val in p.values:
                m |= v == _coerce_to(v, val)
            return ~m if p.type == PredicateType.NOT_IN else m
        if p.type == PredicateType.RANGE:
            m = np.ones(n, dtype=bool)
            if p.lower is not None:
                lo = _coerce_to(v, p.lower)
                m &= (v >= lo) if p.lower_inclusive else (v > lo)
            if p.upper is not None:
                hi = _coerce_to(v, p.upper)
                m &= (v <= hi) if p.upper_inclusive else (v < hi)
            return m
        if p.type in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
            regex = (like_to_regex(p.values[0]) if p.type == PredicateType.LIKE
                     else re.compile(str(p.values[0])))
            return np.asarray([regex.search(str(x)) is not None for x in v], dtype=bool)
        raise UnsupportedQueryError(f"host predicate {p.type}")

    def _eval_geo_range(self, p: Predicate, segment):
        """ST_DISTANCE(latCol, lngCol, lat, lng) < r accelerates through the
        geo grid index: candidate cells → exact haversine refine (reference:
        H3IndexFilterOperator's two-phase cells+refine). Returns None when
        the shape doesn't match — the generic transform path still answers
        it exactly, just without pruning."""
        if p.type != PredicateType.RANGE or p.upper is None:
            return None
        e = p.lhs
        if not (e.is_function and e.function.name in ("stdistance", "distance")):
            return None
        args = e.function.arguments
        if len(args) != 4 or not (args[0].is_identifier and args[1].is_identifier
                                  and args[2].is_literal and args[3].is_literal):
            return None
        lat_col, lng_col = args[0].identifier, args[1].identifier
        idx = segment.get_geo_index(lat_col, lng_col, or_build=True) \
            if hasattr(segment, "get_geo_index") else None
        if idx is None:
            return None
        from ..segment.indexes import haversine_m

        lat0, lng0 = float(args[2].literal), float(args[3].literal)
        cand = idx.candidate_docs(lat0, lng0, float(p.upper))
        mask = np.zeros(segment.num_docs, dtype=bool)
        if len(cand):
            cand = cand[cand < segment.num_docs]
            lat = np.asarray(segment.get_values(lat_col), dtype=np.float64)[cand]
            lng = np.asarray(segment.get_values(lng_col), dtype=np.float64)[cand]
            d = haversine_m(lat, lng, lat0, lng0)
            ok = (d <= p.upper) if p.upper_inclusive else (d < p.upper)
            if p.lower is not None:
                ok &= (d >= p.lower) if p.lower_inclusive else (d > p.lower)
            mask[cand[ok]] = True
        return mask

    def _eval_predicate_with_index(self, p: Predicate, segment):
        """Index-backed predicate evaluation (reference: index-backed
        BaseFilterOperators, pinot-core/.../operator/filter/). Returns None
        when no applicable index exists — caller scans."""
        lhs = p.lhs
        if not lhs.is_identifier or not segment.has_column(lhs.identifier):
            return None
        col = lhs.identifier
        n = segment.num_docs
        m = segment.column_metadata(col)
        if m.encoding == "DICT" and m.single_value:
            d = segment.get_dictionary(col)
            inv = segment.get_inverted_index(col)
            srt = segment.get_sorted_index(col)
            if inv is None and srt is None:
                return None
            if p.type in (PredicateType.EQ, PredicateType.NOT_EQ):
                did = d.index_of(p.values[0])
                mask = self._ids_to_mask(inv, srt, did, did, n)
                return ~mask if p.type == PredicateType.NOT_EQ else mask
            if p.type in (PredicateType.IN, PredicateType.NOT_IN):
                mask = np.zeros(n, dtype=bool)
                for v in p.values:
                    did = d.index_of(v)
                    if did >= 0:
                        mask |= self._ids_to_mask(inv, srt, did, did, n)
                return ~mask if p.type == PredicateType.NOT_IN else mask
            if p.type == PredicateType.RANGE:
                lo_id = 0
                hi_id = m.cardinality - 1
                if p.lower is not None:
                    lo_id = d.insertion_index(p.lower, "left" if p.lower_inclusive else "right")
                if p.upper is not None:
                    hi_id = d.insertion_index(p.upper, "right" if p.upper_inclusive else "left") - 1
                return self._ids_to_mask(inv, srt, lo_id, hi_id, n)
            return None
        if m.encoding == "RAW" and m.single_value and p.type == PredicateType.RANGE:
            rng = segment.get_range_index(col)
            if rng is not None:
                return rng.mask_in_range(n, p.lower, p.upper,
                                         p.lower_inclusive, p.upper_inclusive)
        return None

    @staticmethod
    def _ids_to_mask(inv, srt, lo_id, hi_id, n) -> np.ndarray:
        if hi_id < lo_id or lo_id < 0:
            return np.zeros(n, dtype=bool)
        if srt is not None:
            s, e = srt.doc_range(lo_id, hi_id)
            mask = np.zeros(n, dtype=bool)
            mask[s:e] = True
            return mask
        return inv.mask_for_range(lo_id, hi_id, n)

    def _eval_mv_predicate(self, p: Predicate, segment) -> np.ndarray:
        col = p.lhs.identifier
        rows = segment.get_mv_values(col)

        def match_one(val) -> bool:
            if p.type == PredicateType.EQ:
                return any(x == p.values[0] for x in val)
            if p.type == PredicateType.NOT_EQ:
                return any(x != p.values[0] for x in val)
            if p.type == PredicateType.IN:
                return any(x in p.values for x in val)
            if p.type == PredicateType.NOT_IN:
                return any(x not in p.values for x in val)
            if p.type == PredicateType.RANGE:
                for x in val:
                    ok = True
                    if p.lower is not None:
                        ok &= (x >= p.lower) if p.lower_inclusive else (x > p.lower)
                    if p.upper is not None:
                        ok &= (x <= p.upper) if p.upper_inclusive else (x < p.upper)
                    if ok:
                        return True
                return False
            raise UnsupportedQueryError(f"host MV predicate {p.type}")

        return np.asarray([match_one(r) for r in rows], dtype=bool)

    # -- value expressions -------------------------------------------------
    def eval_value(self, e: ExpressionContext, segment) -> np.ndarray:
        n = segment.num_docs
        if e.is_literal:
            v = e.literal
            if isinstance(v, bool):
                v = int(v)
            return np.full(n, v)
        if e.is_identifier:
            vals = segment.get_values(e.identifier)
            from ..spi.data_types import DataType

            if DataType(segment.column_metadata(e.identifier).data_type) == DataType.BOOLEAN:
                return vals.astype(np.int64)
            return vals
        fn = e.function
        name, args = fn.name, fn.arguments
        if name in _NP_BIN:
            return _NP_BIN[name](self.eval_value(args[0], segment), self.eval_value(args[1], segment))
        if name in _NP_UN:
            return _NP_UN[name](self.eval_value(args[0], segment))
        if name == "cast":
            return _np_cast(self.eval_value(args[0], segment), str(args[1].literal).upper())
        if name == "case":
            out = self.eval_value(args[-1], segment)
            for i in range(len(args) - 3, -1, -2):
                cond = self.eval_value(args[i], segment).astype(bool)
                out = np.where(cond, self.eval_value(args[i + 1], segment), out)
            return out
        if name == "coalesce" and args and args[0].is_identifier:
            base = self.eval_value(args[0], segment)
            nulls = segment.get_null_bitmap(args[0].identifier)
            if nulls is None or len(args) < 2:
                return base
            fallback = self.eval_value(args[1], segment)
            return np.where(nulls, fallback, base)
        td = get_transform(name)
        if td is not None:
            if td.mv_arg and args and args[0].is_identifier and segment.has_column(
                    args[0].identifier) and not segment.column_metadata(
                    args[0].identifier).single_value:
                rows = segment.get_mv_values(args[0].identifier)
                arr = np.empty(len(rows), dtype=object)
                arr[:] = [list(r) for r in rows]
                rest = [a.literal if a.is_literal else self.eval_value(a, segment)
                        for a in args[1:]]
                return td.eval_np(arr, *rest)
            vals = [(int(a.literal) if isinstance(a.literal, bool) else a.literal)
                    if a.is_literal else self.eval_value(a, segment) for a in args]
            return td.eval_np(*vals)
        raise UnsupportedQueryError(f"host transform {name}")

    # -- shapes ------------------------------------------------------------
    def _aggregation(self, query, segment, mask) -> AggIntermediate:
        nh = query.null_handling
        states = []
        for agg in query.aggregations:
            states.append(self._agg_state(agg, segment, mask, nh))
        return AggIntermediate(states, num_docs_scanned=int(mask.sum()))

    def _clause_mask(self, cond: ExpressionContext, segment,
                     nh: bool) -> np.ndarray:
        """FILTER (WHERE cond) clause mask via the same predicate
        machinery as WHERE (LIKE/IN/IS NULL all work; 3VL under null
        handling), mirroring the device's FilterVal lowering."""
        from ..query.converter import FilterConversionError, filter_from_expression

        try:
            fc = filter_from_expression(cond)
        except FilterConversionError:
            m = np.asarray(self.eval_value(cond, segment)).astype(bool)
            if nh:  # a null clause input is false
                m &= ~self._nulls_of(cond.columns(), segment, segment.num_docs)
            return m
        if nh:
            t, _u = self._eval_filter3(fc, segment)
            return t
        return self._eval_filter(fc, segment)

    def _agg_state(self, agg: ExpressionContext, segment, mask, nh=False):
        name = agg.function.name
        if name == "filter":  # AGG(x) FILTER (WHERE cond)
            inner, cond = agg.function.arguments
            return self._agg_state(
                inner, segment, mask & self._clause_mask(cond, segment, nh), nh)
        if name in funnel.FUNNEL_FNS:
            return self._funnel_builder(agg.function, segment)(
                np.nonzero(mask)[0])
        data, extra = split_args(agg.function)
        if nh and data:
            # skip rows where ANY operand column is null (COUNT(expr) too;
            # multi-arg states must stay row-aligned)
            cols_ref = set().union(*(a.columns() for a in data)) - {"*"}
            drop = self._nulls_of(cols_ref, segment, segment.num_docs)
            if drop.any():
                mask = mask & ~drop
        if name == "count":
            return int(mask.sum())
        arg = data[0] if data else None
        if (len(data) == 1 and arg.is_identifier and segment.has_column(arg.identifier)
                and not segment.column_metadata(arg.identifier).single_value):
            # MV argument: aggregate over ALL values of the selected rows
            # (reference *MV aggregation functions)
            mv_rows = segment.get_mv_values(arg.identifier)
            flat = [v for i in np.nonzero(mask)[0] for v in mv_rows[i]]
            return host_state(name, np.asarray(flat), extra)
        cols = [np.asarray(self.eval_value(a, segment))[mask] for a in data]
        return host_state_full(name, cols, extra)

    def _funnel_builder(self, fn, segment):
        """rows_idx → funnel intermediate state, with the whole-segment row
        arrays (step masks, timestamps, correlation values) computed once
        and shared across groups (engine/funnel.py)."""
        spec = funnel.parse_funnel(fn)
        if isinstance(spec, funnel.FunnelCountSpec):
            corr, masks = funnel.count_row_arrays(self, spec, segment)

            def build_count(rows_idx):
                return funnel.count_state(corr, masks, rows_idx)

            return build_count
        ts, step, valid = funnel.window_row_arrays(self, spec, segment)

        def build_window(rows_idx):
            r = rows_idx[valid[rows_idx]]
            return funnel.window_state(ts, step, r)

        return build_window

    def _group_by(self, query, segment, mask, group_exprs) -> GroupByIntermediate:
        if any(e.is_identifier and segment.has_column(e.identifier)
               and not segment.column_metadata(e.identifier).single_value
               for e in group_exprs):
            return self._group_by_mv(query, segment, mask, group_exprs)
        key_cols = [np.asarray(self.eval_value(e, segment)) for e in group_exprs]
        sel = np.nonzero(mask)[0]
        fast = self._group_by_vectorized(query, segment, sel, key_cols, mask)
        if fast is not None:
            return fast
        groups: dict[tuple, list] = {}
        # factorize each key col then group by linear code
        codes = np.zeros(len(sel), dtype=np.int64)
        uniqs = []
        for col in key_cols:
            u, inv = np.unique(col[sel], return_inverse=True)
            codes = codes * len(u) + inv if len(u) else codes
            uniqs.append(u)
        order = np.argsort(codes, kind="stable")
        sel_sorted = sel[order]
        codes_sorted = codes[order]
        boundaries = np.nonzero(np.diff(codes_sorted))[0] + 1
        starts = np.concatenate([[0], boundaries])
        ends = np.concatenate([boundaries, [len(sel_sorted)]])
        agg_args = self._classify_agg_args(query, segment)
        for s, e in zip(starts, ends):
            if s == e:
                continue
            rows = sel_sorted[s:e]
            key = tuple(_to_python(col[rows[0]]) for col in key_cols)
            states = []
            for (kind, cols, extra, drop, fname) in agg_args:
                r = rows if drop is None else rows[~drop[rows]]
                if kind == "count":
                    states.append(len(r))
                elif kind == "funnel":
                    states.append(cols(r))
                elif kind == "mv":
                    flat = [v for i in r for v in cols[i]]
                    states.append(
                        host_state(fname, np.asarray(flat), extra))
                else:
                    states.append(
                        host_state_full(fname, [c[r] for c in cols], extra))
            groups[key] = states
        return GroupByIntermediate(groups, num_docs_scanned=int(mask.sum()))

    def _classify_agg_args(self, query, segment) -> list:
        """Per aggregation: (kind, payload, extra, drop, name) where kind is
        "count" | "mv" (MV column decoded ONCE per query) | "sv" (eval'd
        value arrays), drop is a bitmap of rows to skip for this agg
        (advanced null handling ∪ a FILTER (WHERE ...) clause; None = keep
        all), and name is the state function to build (the INNER name for
        filter-wrapped aggs). Shared by the SV and MV group-by paths."""
        nh = query.null_handling
        n = segment.num_docs
        agg_args = []
        mv_cache: dict[str, object] = {}

        def drop_for(exprs, clause_drop):
            d = clause_drop
            if nh:
                cols = set()
                for a in exprs:
                    cols |= a.columns()
                nd = self._nulls_of(cols - {"*"}, segment, n)
                if nd.any():
                    d = nd if d is None else (d | nd)
            return d

        for agg in query.aggregations:
            fexpr = agg.function
            clause_drop = None
            if fexpr.name == "filter":  # AGG(x) FILTER (WHERE cond)
                inner, cond = fexpr.arguments
                clause_drop = ~self._clause_mask(cond, segment, nh)
                fexpr = inner.function
            name = fexpr.name
            if name in funnel.FUNNEL_FNS:
                agg_args.append(("funnel", self._funnel_builder(fexpr, segment),
                                 (), clause_drop, name))
                continue
            data, extra = split_args(fexpr)
            if name == "count":
                # advanced null handling: COUNT(col) counts non-null rows
                agg_args.append(
                    ("count", None, (), drop_for(data, clause_drop), name))
                continue
            if (len(data) == 1 and data[0].is_identifier
                    and segment.has_column(data[0].identifier)
                    and not segment.column_metadata(
                        data[0].identifier).single_value):
                # MV argument: per group, aggregate over ALL entries of the
                # group's rows (same flattening as the ungrouped _agg_state
                # MV branch)
                col = data[0].identifier
                if col not in mv_cache:
                    mv_cache[col] = segment.get_mv_values(col)
                agg_args.append(("mv", mv_cache[col], extra,
                                 drop_for(data, clause_drop), name))
            else:
                agg_args.append(
                    ("sv", [np.asarray(self.eval_value(a, segment))
                            for a in data], extra,
                     drop_for(data, clause_drop), name))
        return agg_args

    def _group_by_mv(self, query, segment, mask, group_exprs) -> GroupByIntermediate:
        """MV group key(s): one expanded row per (doc × entry) combination
        per MV dim (cross product when several) — a doc contributes to the
        group of EACH of its values, and docs with empty arrays drop out
        (reference MVGroupKeyGenerator). Docs scanned counts matched DOCS,
        not expanded rows."""
        sel = np.nonzero(mask)[0]
        docs = sel
        expanded: dict[int, np.ndarray] = {}
        for di, e in enumerate(group_exprs):
            if not (e.is_identifier and segment.has_column(e.identifier)
                    and not segment.column_metadata(e.identifier).single_value):
                continue
            rows = segment.get_mv_values(e.identifier)
            lens = np.fromiter((len(rows[d]) for d in docs),
                               dtype=np.int64, count=len(docs))
            vals = [v for d in docs for v in rows[d]]
            for k in expanded:
                expanded[k] = np.repeat(expanded[k], lens)
            docs = np.repeat(docs, lens)
            expanded[di] = np.asarray(vals, dtype=object)
        key_cols = []
        for di, e in enumerate(group_exprs):
            if di in expanded:
                key_cols.append(expanded[di])
            else:
                key_cols.append(np.asarray(self.eval_value(e, segment))[docs])

        agg_args = self._classify_agg_args(query, segment)

        groups: dict[tuple, list] = {}
        order = np.lexsort([np.asarray([repr(v) for v in c], dtype=object)
                            for c in reversed(key_cols)]) \
            if key_cols and len(docs) else np.arange(len(docs))
        # group contiguity via sorted tuples
        keys_sorted = [tuple(_to_python(c[i]) for c in key_cols) for i in order]
        i = 0
        while i < len(order):
            j = i
            while j < len(order) and keys_sorted[j] == keys_sorted[i]:
                j += 1
            rows_idx = docs[order[i:j]]
            states = []
            for (kind, cols, extra, drop, fname) in agg_args:
                r = rows_idx if drop is None else rows_idx[~drop[rows_idx]]
                if kind == "count":
                    states.append(len(r))
                elif kind == "funnel":
                    states.append(cols(r))
                elif kind == "mv":
                    flat = [v for d in r for v in cols[d]]
                    states.append(
                        host_state(fname, np.asarray(flat), extra))
                else:
                    states.append(host_state_full(
                        fname, [c[r] for c in cols], extra))
            groups[keys_sorted[i]] = states
            i = j
        return GroupByIntermediate(groups, num_docs_scanned=int(mask.sum()))

    # scalar aggs with a columnar (GroupArrays) host form: same set the
    # device fast path supports, so host and device baselines are comparable
    _VEC_AGGS = frozenset(VEC_RECIPES)

    def _group_by_vectorized(self, query, segment, sel, key_cols, mask):
        """np.unique + scatter-reduce group-by → GroupArrays, no per-group
        Python. Returns None when any aggregation lacks a columnar form
        (the general host_state_full loop handles it)."""
        from .results import GroupArrays

        nh = query.null_handling
        agg_vals = []
        for agg in query.aggregations:
            name = agg.function.name
            if name not in self._VEC_AGGS:
                return None
            if name == "count" and not nh:
                agg_vals.append(None)
                continue
            data, extra = split_args(agg.function)
            if nh and any(self._nulls_of(a.columns() - {"*"}, segment,
                                         segment.num_docs).any()
                          for a in data):
                return None  # null-skipping states: general loop handles
            if name == "count":
                agg_vals.append(None)
                continue
            if len(data) != 1 or extra:
                return None
            try:
                v = np.asarray(self.eval_value(data[0], segment))
            except Exception:
                return None
            if v.dtype.kind not in "ifb" or v.shape != mask.shape:
                return None
            agg_vals.append(v[sel].astype(np.float64))

        codes = np.zeros(len(sel), dtype=np.int64)
        for col in key_cols:
            u, inv = np.unique(col[sel], return_inverse=True)
            codes = codes * max(1, len(u)) + inv
        ucodes, first_idx, inv2 = np.unique(
            codes, return_index=True, return_inverse=True)
        g = len(ucodes)
        rep = sel[first_idx]  # representative row per group
        out_keys = [col[rep] for col in key_cols]
        counts = np.bincount(inv2, minlength=g).astype(np.int64)

        def scatter_sum(vals):
            out = np.zeros(g)
            np.add.at(out, inv2, vals)
            return out

        def scatter_min(vals):
            out = np.full(g, np.inf)
            np.minimum.at(out, inv2, vals)
            return out

        def scatter_max(vals):
            out = np.full(g, -np.inf)
            np.maximum.at(out, inv2, vals)
            return out

        states, specs, tags = [], [], []
        for agg, vals in zip(query.aggregations, agg_vals):
            name = agg.function.name
            spec, tag = VEC_RECIPES[name]  # shared with the device lowering
            if name == "count":
                states.append((counts,))
            elif name == "sum":
                states.append((scatter_sum(vals),))
            elif name == "min":
                states.append((scatter_min(vals),))
            elif name == "max":
                states.append((scatter_max(vals),))
            elif name == "avg":
                states.append((scatter_sum(vals), counts))
            else:  # minmaxrange
                states.append((scatter_min(vals), scatter_max(vals)))
            specs.append(spec)
            tags.append(tag)
        return GroupArrays(out_keys, states, specs, tags,
                           num_docs_scanned=int(mask.sum()))

    def _selection(self, query, segment, mask) -> SelectionIntermediate:
        from .selection import selection_columns_for

        cols, exprs = selection_columns_for(query, segment)
        return selection_from_mask(
            query, segment, cols, mask, extra_exprs=exprs or None,
            evaluator=lambda e, doc_ids: self.eval_value_at(e, segment, doc_ids))

    def eval_value_at(self, e: ExpressionContext, segment, doc_ids) -> np.ndarray:
        """Evaluate a transform expression over a row subset only (LIMIT-k
        selections must not pay O(num_docs) python time)."""
        from ..query.transforms import eval_expr_np

        try:
            out = eval_expr_np(e, lambda name: segment.get_values(name)[doc_ids])
        except UnsupportedQueryError:
            return np.asarray(self.eval_value(e, segment))[doc_ids]
        out = np.asarray(out)
        if out.ndim == 0:
            out = np.broadcast_to(out, (len(doc_ids),)).copy()
        return out


def eval_json_match(p: Predicate, segment) -> np.ndarray:
    """JSON_MATCH(col, 'filter') → doc mask via the column's JSON index;
    builds a transient index when none was persisted (reference requires the
    index; transient keeps the host oracle able to verify it)."""
    col = p.lhs.identifier
    if col is None or not segment.has_column(col):
        raise UnsupportedQueryError(f"JSON_MATCH needs a column: {p.lhs}")
    idx = segment.get_json_index(col, or_build=True)
    return idx.mask_match(str(p.values[0]), segment.num_docs)


def eval_map_index_predicate(p: Predicate, segment):
    """Predicate over mapvalue(col, 'key') answered from a map index's
    dense planes (segment/map_index.py) — one vector compare instead of a
    row-wise JSON parse per doc. None when no index/key applies (the
    generic transform path still answers exactly). Absent keys follow the
    row-wise None semantics: they fail EQ/IN/RANGE and pass NOT_EQ/NOT_IN."""
    from ..segment.map_index import map_value_args

    args = map_value_args(p.lhs)
    if args is None:
        return None
    col, key, default = args
    if default is not None or not hasattr(segment, "get_map_index") \
            or not segment.has_column(col):
        return None
    idx = segment.get_map_index(col)
    if idx is None or not idx.has_key(key):
        return None
    lits = list(p.values or ())
    lits += [x for x in (p.lower, p.upper) if x is not None]
    try:
        lits = [float(x) for x in lits]
    except (TypeError, ValueError):
        return None  # non-numeric comparison: dense planes are numeric
    v, present = idx.value_plane(key)
    if p.type in (PredicateType.EQ, PredicateType.IN):
        m = np.zeros(len(v), dtype=bool)
        for x in lits:
            m |= v == x
        return m & present
    if p.type in (PredicateType.NOT_EQ, PredicateType.NOT_IN):
        m = np.zeros(len(v), dtype=bool)
        for x in lits:
            m |= v == x
        return ~(m & present)
    if p.type == PredicateType.RANGE:
        m = np.ones(len(v), dtype=bool)
        if p.lower is not None:
            lo = float(p.lower)
            m &= (v >= lo) if p.lower_inclusive else (v > lo)
        if p.upper is not None:
            hi = float(p.upper)
            m &= (v <= hi) if p.upper_inclusive else (v < hi)
        return m & present
    return None


def eval_host_mask(p: Predicate, segment) -> np.ndarray:
    """Index-backed predicates without a vector form → boolean doc plane.
    Shared by the host engine and the device planner's MaskParam lowering
    (reference: these run as index-backed filter operators —
    TextMatchFilterOperator, VectorSimilarityFilterOperator,
    JsonMatchFilterOperator)."""
    if p.type == PredicateType.JSON_MATCH:
        return eval_json_match(p, segment)
    col = p.lhs.identifier
    if col is None or not segment.has_column(col):
        raise UnsupportedQueryError(f"{p.type.value} needs a column: {p.lhs}")
    if p.type == PredicateType.TEXT_MATCH:
        idx = segment.get_text_index(col, or_build=True)
        if idx is None:
            raise UnsupportedQueryError(
                f"TEXT_MATCH on consuming segment column {col}")
        return idx.mask_match(str(p.values[0]), segment.num_docs)
    if p.type == PredicateType.VECTOR_SIMILARITY:
        idx = segment.get_vector_index(col, or_build=True)
        if idx is None:
            raise UnsupportedQueryError(
                f"VECTOR_SIMILARITY on consuming segment column {col}")
        vec, k = p.values
        return idx.mask_top_k(np.asarray(vec, dtype=np.float32), int(k),
                              segment.num_docs)
    raise UnsupportedQueryError(f"host mask predicate {p.type}")


_NP_BIN = {
    "plus": np.add, "minus": np.subtract, "times": np.multiply,
    "divide": np.true_divide, "mod": np.mod, "pow": np.power, "power": np.power,
    "equals": lambda a, b: a == b, "notequals": lambda a, b: a != b,
    "lessthan": lambda a, b: a < b, "lessthanorequal": lambda a, b: a <= b,
    "greaterthan": lambda a, b: a > b, "greaterthanorequal": lambda a, b: a >= b,
    "and": np.logical_and, "or": np.logical_or,
    "least": np.minimum, "greatest": np.maximum,
}

_NP_UN = {
    "neg": np.negative, "abs": np.abs, "not": np.logical_not, "exp": np.exp,
    "ln": np.log, "log10": np.log10, "log2": np.log2, "sqrt": np.sqrt,
    "ceiling": np.ceil, "ceil": np.ceil, "floor": np.floor, "sign": np.sign,
}


def _np_cast(v, to):
    m = {"INT": np.int32, "LONG": np.int64, "FLOAT": np.float32, "DOUBLE": np.float64,
         "BOOLEAN": bool, "STRING": np.str_, "TIMESTAMP": np.int64}
    if to not in m:
        raise UnsupportedQueryError(f"cast to {to}")
    return v.astype(m[to])


def _coerce_to(arr: np.ndarray, value):
    if isinstance(value, bool) and np.issubdtype(arr.dtype, np.number):
        return int(value)
    return value


def _to_python(v):
    if isinstance(v, np.generic):
        return v.item()
    return v
