"""Kernel program IR — the static shape of a per-segment query kernel.

This is the TPU build's replacement for the reference's operator tree
(pinot-core/.../plan/ — GroupByPlanNode/AggregationPlanNode/SelectionPlanNode
over Operator.nextBlock pull loops). Instead of virtual-call operators pulling
10K-doc blocks, a query compiles to a *Program*: a small frozen (hashable)
tree interpreted once inside `jax.jit` (ops/kernels.py:run_program). Because
the Program is a static jit argument, all literal values live in the runtime
`params` tuple — structurally identical queries over same-shaped segments hit
the XLA compile cache regardless of literals.

Slot model: `arrays[i]` are device-resident column planes (dict-id planes,
raw value planes, numeric dictionaries, null bitmaps); `params[i]` are
per-query values (interval bounds, LUTs, IN-lists). The planner
(engine/plan.py) assigns slots.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

# Sparse group-by composite keys must stay strictly below this value: the
# kernel uses it as the masked-row sort sentinel (rows with key >= sentinel
# are treated as filtered out), and the planner rejects cardinality products
# reaching it. One constant, imported by both sides, so the invariant can't
# drift (ops/kernels._run_sparse_group_by, engine/plan.SegmentPlanner.plan).
SPARSE_KEY_SPACE = 1 << 62

# ---------------------------------------------------------------------------
# Value expressions (→ reference TransformFunction,
# pinot-core/.../operator/transform/function/TransformFunction.java:35)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ValueExpr:
    pass


@dataclass(frozen=True)
class Col(ValueExpr):
    """A raw value plane already on device."""

    slot: int


@dataclass(frozen=True)
class DictGather(ValueExpr):
    """dictionary[dict_ids] — numeric dict decode on device."""

    ids_slot: int
    dict_slot: int


@dataclass(frozen=True)
class IdsCol(ValueExpr):
    """The dict-id plane itself (used for group keys / dict-space compares)."""

    slot: int


@dataclass(frozen=True)
class ConstParam(ValueExpr):
    """Scalar literal passed at runtime (params[idx])."""

    idx: int


@dataclass(frozen=True)
class ParamGather(ValueExpr):
    """params[param_idx][ids] — a host-computed lookup table gathered on
    device. The planner uses this for dictionary transforms: a string/complex
    transform function is evaluated ONCE over the column's dictionary on host
    (cardinality values, not num_docs), and the per-row result becomes a
    single gather — the TPU analogue of the reference evaluating dictionary-
    based transforms per 10K-doc block."""

    ids: ValueExpr  # int plane (IdsCol or another ParamGather for remaps)
    param_idx: int


@dataclass(frozen=True)
class Bin(ValueExpr):
    op: str  # add sub mul div fdiv mod pow eq ne lt le gt ge and or min max
    a: ValueExpr
    b: ValueExpr


@dataclass(frozen=True)
class Un(ValueExpr):
    op: str  # neg abs not exp ln log10 log2 sqrt ceil floor sign
    a: ValueExpr


@dataclass(frozen=True)
class Cast(ValueExpr):
    a: ValueExpr
    to: str  # INT LONG FLOAT DOUBLE BOOLEAN


@dataclass(frozen=True)
class Where(ValueExpr):
    cond: ValueExpr
    a: ValueExpr
    b: ValueExpr


@dataclass(frozen=True)
class FilterVal(ValueExpr):
    """A lowered FILTER subtree used as a boolean VALUE plane — the bridge
    that lets FILTER (WHERE ...) clause conditions reuse the whole
    predicate lowering (dict-id LUTs, intervals, host index masks) inside
    an aggregation operand wrap. Declared after FilterNode; the field is
    typed loosely to avoid a forward reference."""

    filter: object  # FilterNode


@dataclass(frozen=True)
class NullCol(ValueExpr):
    """The column's null bitmap plane as a boolean value (advanced null
    handling: agg operands wrap as Where(NullCol, identity, v) so null
    rows contribute the op identity — reference
    QueryContext.isNullHandlingEnabled semantics)."""

    null_slot: int


@dataclass(frozen=True)
class MvLutReduce(ValueExpr):
    """Per-doc reduce of an MV column: params[lut_param][mv_ids] is a
    (docs, max_mv) value matrix whose pad-sentinel slot (index card) holds
    the op identity, row-reduced to one value per doc. op="count" needs no
    LUT at all — it counts non-sentinel slots (lut_param None, card set).
    Lowers SUMMV / COUNTMV / MINMV / MAXMV / AVGMV onto the standard
    scalar agg kernels (reference SumMVAggregationFunction et al., which
    loop per-doc value arrays — here the ragged column is a rectangular
    matrix and the reduce is one fused device op)."""

    ids_slot: int
    lut_param: Optional[int]
    op: str  # sum | min | max | count
    card: Optional[int] = None  # count: the pad sentinel id


# ---------------------------------------------------------------------------
# Filter nodes (→ reference BaseFilterOperator tree,
# pinot-core/.../operator/filter/; predicates become vector compares)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class FilterNode:
    pass


@dataclass(frozen=True)
class FConst(FilterNode):
    value: bool


@dataclass(frozen=True)
class Interval(FilterNode):
    """lo <= v <= hi with optional open bounds; params hold the bounds.

    Dict-encoded predicates are normalized on host to a dict-id interval
    (sorted dictionaries make value ranges id ranges); raw predicates compare
    in value space.
    """

    vexpr: ValueExpr
    lo_param: Optional[int] = None
    hi_param: Optional[int] = None
    lo_inclusive: bool = True
    hi_inclusive: bool = True


@dataclass(frozen=True)
class Lut(FilterNode):
    """mask = lut[dict_ids] — arbitrary dictionary predicate (IN, LIKE, REGEXP,
    NOT_IN...) evaluated against the dictionary on host into a boolean LUT.
    MV-safe: LUT is sized cardinality+1 with the pad sentinel false."""

    ids_slot: int
    lut_param: int
    mv: bool = False


@dataclass(frozen=True)
class Isin(FilterNode):
    """Raw-column IN: compare against a small padded value array
    (pad = repeat of first value, harmless for membership)."""

    vexpr: ValueExpr
    values_param: int


@dataclass(frozen=True)
class Null(FilterNode):
    """mask = null bitmap plane (IS_NULL)."""

    null_slot: int


@dataclass(frozen=True)
class MaskParam(FilterNode):
    """mask = params[idx] — a boolean doc plane evaluated on HOST at plan
    time (JSON_MATCH / TEXT_MATCH posting lists, precomputed index masks),
    padded to the segment's shape bucket before dispatch."""

    idx: int


@dataclass(frozen=True)
class FAnd(FilterNode):
    children: tuple[FilterNode, ...]


@dataclass(frozen=True)
class FOr(FilterNode):
    children: tuple[FilterNode, ...]


@dataclass(frozen=True)
class FNot(FilterNode):
    child: FilterNode


# ---------------------------------------------------------------------------
# Aggregation ops (primitive device reductions; SQL agg functions lower to
# one or more of these — engine/aggregation.py)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AggOp:
    kind: str  # count | sum | min | max | sumsq | distinct_bitmap | value_hist | hist_fixed | hist_adaptive
    vexpr: Optional[ValueExpr] = None
    # distinct_bitmap / value_hist: dict-id plane slot + static cardinality
    ids_slot: Optional[int] = None
    card: Optional[int] = None
    # hist_fixed / hist_adaptive: static bin count + runtime [lo, hi] bounds
    bins: Optional[int] = None
    lo_param: Optional[int] = None
    hi_param: Optional[int] = None
    # hist_adaptive: the target percentile (static) — level-2 bins refine
    # each group's coarse bucket containing this quantile
    pct: Optional[float] = None
    # static integer value bounds when the planner knows them (column
    # metadata / dictionary min-max) — lets integer sums skip limbs and the
    # negative-count pass in the exact i32-scatter decomposition
    vmin: Optional[int] = None
    vmax: Optional[int] = None
    # hist_adaptive over a raw float column: vexpr evaluates to a PRE-REBASED
    # f32 offset plane ((v - column_min) stored f32 in HBM — half the read
    # bandwidth of the f64 plane and no per-row f64 subtract; the TPU has no
    # f64 ALU). lo_param still carries the f64 base for host-side decode.
    prebased: bool = False


# ---------------------------------------------------------------------------
# Program
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Program:
    mode: str  # "group_by" | "group_by_sparse" | "aggregation" | "selection"
    filter: Optional[FilterNode]
    aggs: tuple[AggOp, ...] = ()
    # group-by: per-dim dict-id plane slots + cartesian strides
    # (reference DictionaryBasedGroupKeyGenerator cartesian-product int keys,
    # pinot-core/.../groupby/DictionaryBasedGroupKeyGenerator.java:119-137).
    # Dense mode materializes a (num_groups+1,) table per agg; sparse mode
    # (cardinality product beyond the dense HBM limit) sorts 64-bit composite
    # keys on device and emits at most num_groups = numGroupsLimit groups —
    # the device analogue of the reference's hash-map key generators with
    # numGroupsLimit trim (InstancePlanMakerImplV2.java:245-270). Sparse
    # kernels append a (num_groups,) int64 key plane as the LAST output.
    group_slots: tuple[int, ...] = ()
    group_strides: tuple[int, ...] = ()
    num_groups: int = 1
    # expression group keys (derived dimensions): per-dim int ValueExprs,
    # same strides. Used when a group-by key is a transform of a dict column
    # (ids remapped through a host-computed LUT — ParamGather). When set,
    # group_slots is empty.
    group_vexprs: tuple[ValueExpr, ...] = ()
    # sparse mode: the FULL composite key space (cardinality product before
    # the numGroupsLimit cap). Static, so the kernel can sort 32-bit keys
    # when they fit — 64-bit sorts and scatters are emulated on TPU
    key_space: int = 0
    # sparse mode: the device trim is an ORDER BY pushdown (ASC group-key
    # prefix + LIMIT) — result is exact, so don't flag numGroupsLimitReached
    exact_trim: bool = False
    # sparse mode: the SINGLE group key is a dict column whose id plane is
    # nondecreasing over the segment (ColumnMetadata.is_sorted — sorted
    # ingestion order, e.g. an order-key or time column). The kernel then
    # skips lax.sort entirely: group runs are already contiguous, so edges
    # come straight from transitions in the raw id plane (the reference's
    # SortedGroupByOperator analogue).
    keys_presorted: bool = False
    # MV group-by: ONE group dim may be a multi-value column. The kernel
    # expands (doc × mv-slot) pairs up front — every 1-D plane broadcasts
    # across the MV width, the MV id matrix flattens, non-entries mask off
    # — then the dense/sparse machinery runs unchanged on the pairs
    # (reference MVGroupKeyGenerator emits one group key per MV entry).
    # Group-by outputs gain ONE extra trailing (1,) int64: matched DOC
    # count (pair counts no longer equal docs scanned).
    mv_group_slot: Optional[int] = None
    mv_group_card: Optional[int] = None
    # slots holding per-DOC 1-D planes (ids/raw/null) that the expansion
    # must broadcast across the MV width — dictionary planes are
    # cardinality-sized and must pass through untouched
    mv_doc_slots: tuple = ()


def sparse_groupby_path(p: Program) -> str:
    """The sparse kernel variant a Program lowers to — mirrors the branch
    taken by ops/kernels._run_sparse_group_by so EXPLAIN IMPLEMENTATION can
    name it without tracing the kernel: `sparse-presorted` skips lax.sort,
    `sparse-sort+gather` sorts (key[, distinct_ids], iota32) and gathers the
    >=2 payload operands through the permutation, `sparse-sort` carries a
    single payload through the sort network directly."""
    if p.keys_presorted:
        return "sparse-presorted"
    payloads = sum(1 for a in p.aggs
                   if a.kind in ("sum", "sumsq", "min", "max"))
    return "sparse-sort+gather" if payloads >= 2 else "sparse-sort"
