"""Device-memory (HBM) pressure handling — the DirectOOMHandler analogue.

Reference analogue being replaced:
pinot-core/src/main/java/org/apache/pinot/core/transport/DirectOOMHandler.java
— on a direct-memory OOM the reference tears down Netty channels to shed
load rather than letting the process die. Here the scarce resource is
device HBM: an XLA RESOURCE_EXHAUSTED during plane upload, kernel
dispatch, or result fetch triggers ONE orderly LRU eviction of cold
segment planes from the device cache followed by a single retry; a second
failure fails the QUERY cleanly (surfaced as a broker-style exception,
metered), never the process.

Async-dispatch caveat: XLA dispatch is async, so an OOM raised while the
kernel runs surfaces at the fetch/collect call on error-poisoned output
buffers. Re-fetching those buffers re-raises the stored error no matter
how much memory eviction freed — the retry callable for a fetch seam must
RE-DISPATCH, which is why with_oom_retry takes a separate ``retry_fn``.
"""

from __future__ import annotations

from typing import Callable, Optional

from ..spi.metrics import SERVER_METRICS, ServerMeter


class HbmExhaustedError(Exception):
    """Device memory exhausted even after evicting cold segment planes;
    the query fails cleanly (reference: QueryException on OOM-kill)."""


def _jax_runtime_error_types() -> tuple:
    try:
        from jax.errors import JaxRuntimeError

        return (JaxRuntimeError,)
    except ImportError:  # older jaxlib layout
        try:
            from jaxlib.xla_extension import XlaRuntimeError

            return (XlaRuntimeError,)
        except ImportError:
            return ()


def is_hbm_oom(exc: BaseException) -> bool:
    """XLA surfaces HBM exhaustion as XlaRuntimeError/JaxRuntimeError
    RESOURCE_EXHAUSTED. Message shapes vary by backend/runtime version, so
    within the XLA error type match broadly; for any other RuntimeError
    only the unambiguous RESOURCE_EXHAUSTED tag qualifies (a host-side
    'error allocating thread pool' must not trigger device eviction)."""
    if isinstance(exc, MemoryError):
        return True
    if not isinstance(exc, RuntimeError):
        return False
    msg = str(exc).lower()
    if "resource_exhausted" in msg:
        return True
    if isinstance(exc, _jax_runtime_error_types()):
        return any(m in msg for m in ("out of memory", "failed to allocate",
                                      "allocating", "hbm"))
    return False


def relieve_pressure(keep_segment=None, cache=None) -> int:
    """Evict every cached segment's device planes except the one currently
    executing (its uploads would just be redone), then nudge the runtime to
    actually release the buffers. Stacked [S, N] segment-batch views are
    evicted wholesale first (evict_all_except drops every stack — they are
    derived data, rebuildable from the per-segment planes). Returns bytes
    freed (host-side estimate). ``cache`` defaults to the process-global
    device cache; pass the executor's own cache when it uses a private
    one."""
    import gc

    if cache is None:
        from ..segment.device_cache import GLOBAL_DEVICE_CACHE as cache

    freed, victims = cache.evict_all_except(keep_segment)
    if victims:
        SERVER_METRICS.add_meter(ServerMeter.HBM_OOM_EVICTIONS, victims)
    # realtime device planes are rebuildable from the host segment (the
    # next query re-uploads from row 0) — under OOM they are cold cache
    # like any other plane. keep_segment is a snapshot view; keep its
    # UNDERLYING segment's planes (they back the retry's uploads).
    try:
        from ..realtime.device_plane import REALTIME_PLANES

        keep = getattr(keep_segment, "_seg", keep_segment)
        freed += REALTIME_PLANES.clear(keep=keep)
    except Exception:  # pragma: no cover - relief must never raise
        pass
    gc.collect()  # drop dangling jax.Array refs so XLA can free HBM now
    return freed


def with_oom_retry(fn: Callable, keep_segment=None, cache=None,
                   retry_fn: Optional[Callable] = None,
                   on_relief: Optional[Callable[[int], None]] = None):
    """Run ``fn``; on an HBM OOM, relieve pressure once and retry; on a
    second OOM raise HbmExhaustedError (clean query failure). All other
    exceptions pass through untouched.

    ``retry_fn`` (default ``fn``) is what runs after eviction — pass a
    re-dispatching callable when ``fn`` fetches async outputs, because the
    original output buffers are error-poisoned after an OOM."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — classified below, re-raised if not OOM
        if not is_hbm_oom(e):
            raise
        SERVER_METRICS.add_meter(ServerMeter.HBM_OOM_EVENTS)
        freed = relieve_pressure(keep_segment, cache=cache)
        if on_relief is not None:
            on_relief(freed)
        try:
            return (retry_fn or fn)()
        except Exception as e2:  # noqa: BLE001
            if not is_hbm_oom(e2):
                raise
            SERVER_METRICS.add_meter(ServerMeter.HBM_OOM_QUERY_FAILURES)
            raise HbmExhaustedError(
                f"device memory exhausted after evicting {freed} cached "
                f"bytes and retrying: {e2}") from e2
