"""Per-plan performance ledger + alert book (continuous regression sentinel).

The serving arc multiplied the ways a plan gets slower WITHOUT any query
returning a wrong answer: a compile-cache miss, a coalesce group that
stops forming, a cold-tier warm, a fused device plan falling back to
host. The raw signals all exist (CompileRegistry, TraceStore, workload
rollups, per-response counters) but nothing watched them between bench
rounds. This module is the always-on half of that watch:

``PerfLedger``
    One entry per plan fingerprint (the broker-tier result-cache
    fingerprint when the query computed one, a cheap crc of the SQL text
    otherwise), holding a rolling SHORT window and a decayed long-term
    REFERENCE window of: a log-bucketed latency histogram (same
    4-buckets-per-octave shape as spi/metrics.TimerHistogram), counts of
    dispatches, compiles, host crossings, bytes shuffled, result-cache
    and segment-cache outcomes, coalesce outcomes, errors and partials.
    Recording is pure counter bumps off fields the response already
    carries — zero device syncs, zero span allocations, no fingerprint
    walks (tests/test_ledger_perf_guard.py pins this). Global fallback
    events (mesh→solo, device-join→host, fused→host) are counted from
    the engine fallback paths themselves, which are rare by definition.
    The ledger is bounded (``PINOT_TPU_LEDGER_MAX`` plans, batch-evicting
    the stalest decile when full) and persists its reference windows
    through the WAL-backed PropertyStore (``/PERF/LEDGER``), so a
    restarted cluster keeps its notion of "normal".

``AlertBook``
    Structured alert records the drift detector (cluster/sentinel.py)
    fires and resolves: named anomaly types with per-(type, key)
    deduplication, exemplar trace ids appended as the broker pins them,
    and a bounded history. Served at ``GET /debug/alerts``.

Exemplar pinning closes the metrics→traces loop: when an alert fires,
the sentinel arms ``claim_exemplar`` for the next N matching queries;
the broker's sampling site checks ONE attribute (``exemplar_armed``,
False when disarmed — the same zero-cost discipline as faults.ACTIVE)
and forces head-sampling on claims, pinning the resulting trace in the
TraceStore tagged with the alert id.
"""

from __future__ import annotations

import math
import os
import threading
import time

# SHORT window length: the ledger folds the live window into the decayed
# reference once it ages past this (lazily, on the next record() or on a
# sentinel scrape). Tests and soaks call rotate_now() instead of waiting.
WINDOW_S_ENV = "PINOT_TPU_LEDGER_WINDOW_S"
# bound on distinct plan fingerprints held (fingerprint churn — e.g. a
# literal-heavy workload hashing to many SQL keys — evicts, never grows)
MAX_PLANS_ENV = "PINOT_TPU_LEDGER_MAX"
# decay applied to the reference window at every fold: ref = ref*d + cur
REF_DECAY_ENV = "PINOT_TPU_LEDGER_REF_DECAY"

LEDGER_PATH = "/PERF/LEDGER"

# SLO objectives (env defaults; per-table override via table config keys
# sloLatencyMs / sloErrorRate / sloPartialRate, folded in by the
# sentinel). Latency objective reads "this fraction of queries finishes
# under sloLatencyMs"; its error budget is 1 - pct.
SLO_LATENCY_MS_ENV = "PINOT_TPU_SLO_LATENCY_MS"
SLO_LATENCY_PCT_ENV = "PINOT_TPU_SLO_LATENCY_PCT"
SLO_ERROR_RATE_ENV = "PINOT_TPU_SLO_ERROR_RATE"
SLO_PARTIAL_RATE_ENV = "PINOT_TPU_SLO_PARTIAL_RATE"
SLO_FAST_WINDOW_S_ENV = "PINOT_TPU_SLO_FAST_WINDOW_S"
SLO_SLOW_WINDOW_S_ENV = "PINOT_TPU_SLO_SLOW_WINDOW_S"

# same histogram resolution as spi/metrics.TimerHistogram: 4 buckets per
# power of two -> worst-case quantile error 2**0.25 - 1 ~= 19%
_BUCKETS_PER_OCTAVE = 4

_COUNTER_KEYS = (
    "queries", "errors", "partials", "dispatches", "compiles",
    "hostCrossings", "bytesShuffled", "cacheHits", "cacheMisses",
    "cacheBypass", "segCacheHits", "segCacheMisses", "coalesced",
    "latencySumMs",
)

# monotonic clock hook — tests freeze/advance it to drive window math
# deterministically without sleeping
_mono = time.monotonic


def _bucket_index(ms: float) -> int:
    if ms <= 0:
        return -64
    return math.ceil(math.log2(ms) * _BUCKETS_PER_OCTAVE)


def _bucket_upper_ms(idx: int) -> float:
    return 2.0 ** (idx / _BUCKETS_PER_OCTAVE)


def bucket_quantile(buckets: dict, q: float) -> float:
    """Quantile estimate (upper bucket bound, ms) from a log-bucketed
    histogram whose counts may be decayed floats."""
    total = sum(buckets.values())
    if total <= 0:
        return 0.0
    target = q * total
    acc = 0.0
    for idx in sorted(buckets):
        acc += buckets[idx]
        if acc >= target:
            return _bucket_upper_ms(idx)
    return _bucket_upper_ms(max(buckets))


def _fresh_window() -> dict:
    w = dict.fromkeys(_COUNTER_KEYS, 0)
    w["latBuckets"] = {}
    return w


def _fold(ref: dict, cur: dict, decay: float) -> None:
    for k in _COUNTER_KEYS:
        ref[k] = ref[k] * decay + cur[k]
    rb = ref["latBuckets"]
    for idx in rb:
        rb[idx] *= decay
    for idx, n in cur["latBuckets"].items():
        rb[idx] = rb.get(idx, 0.0) + n


class _Plan:
    """One fingerprint's rolling state. All mutation happens under the
    ledger lock; no per-plan locks."""

    __slots__ = ("key", "table", "sql", "first_seen", "last_update",
                 "cur", "cur_start", "ref", "ref_weight", "tot")

    def __init__(self, key: str, table: str, sql: str, now: float):
        self.key = key
        self.table = table
        self.sql = sql
        self.first_seen = time.time()
        self.last_update = now
        self.cur = _fresh_window()
        self.cur_start = now
        self.ref = _fresh_window()
        self.ref["latBuckets"] = {}
        self.ref_weight = 0.0
        self.tot = dict.fromkeys(
            ("queries", "errors", "partials", "compiles"), 0)


class _TableSlo:
    """Per-table SLO time series: small fixed-duration buckets pruned past
    the slow burn window, each counting queries / errors / partials /
    latency-objective breaches."""

    __slots__ = ("buckets",)

    def __init__(self):
        self.buckets: list = []  # [bucket_id, q, err, part, lat_breach]

    def bump(self, bucket_id: int, error: bool, partial: bool,
             lat_breach: bool, keep: int) -> None:
        b = self.buckets
        if not b or b[-1][0] != bucket_id:
            b.append([bucket_id, 0, 0, 0, 0])
            if len(b) > keep:
                del b[:len(b) - keep]
        row = b[-1]
        row[1] += 1
        row[2] += int(error)
        row[3] += int(partial)
        row[4] += int(lat_breach)

    def window(self, bucket_id: int, n_buckets: int) -> tuple:
        lo = bucket_id - n_buckets
        q = err = part = lat = 0
        for row in reversed(self.buckets):
            if row[0] <= lo:
                break
            q += row[1]
            err += row[2]
            part += row[3]
            lat += row[4]
        return q, err, part, lat


class PerfLedger:
    def __init__(self, window_s: float = None, max_plans: int = None,
                 ref_decay: float = None):
        self.window_s = float(
            os.environ.get(WINDOW_S_ENV, 60.0)
            if window_s is None else window_s)
        self.max_plans = int(
            os.environ.get(MAX_PLANS_ENV, 512)
            if max_plans is None else max_plans)
        self.ref_decay = float(
            os.environ.get(REF_DECAY_ENV, 0.8)
            if ref_decay is None else ref_decay)
        self._lock = threading.Lock()
        self._plans: dict[str, _Plan] = {}
        self._tables: dict[str, _TableSlo] = {}
        self._slo_overrides: dict[str, dict] = {}
        self._slo_cache: dict[str, dict] = {}
        # global fallback-event windows (mesh-solo / device-join-host /
        # fused-host / ...): cur + decayed ref, same fold cycle as plans
        self._ev_cur: dict[str, int] = {}
        self._ev_start = _mono()
        self._ev_ref: dict[str, float] = {}
        self._ev_ref_weight = 0.0
        self._ev_tot: dict[str, int] = {}
        self._evictions = 0
        # exemplar arming: False is the entire disarmed hot-path cost
        # (one attribute read at the broker sampling site)
        self.exemplar_armed = False
        self._exemplar_targets: dict = {}  # ("plan"|"table", key) -> [id, n]

    # -- SLO objectives ------------------------------------------------------

    def slo_for(self, table: str) -> dict:
        slo = self._slo_cache.get(table)
        if slo is None:
            slo = {
                "latencyMs": float(
                    os.environ.get(SLO_LATENCY_MS_ENV, 1000.0)),
                "latencyPct": float(
                    os.environ.get(SLO_LATENCY_PCT_ENV, 0.99)),
                "errorRate": float(
                    os.environ.get(SLO_ERROR_RATE_ENV, 0.01)),
                "partialRate": float(
                    os.environ.get(SLO_PARTIAL_RATE_ENV, 0.05)),
                "fastWindowS": float(
                    os.environ.get(SLO_FAST_WINDOW_S_ENV, 60.0)),
                "slowWindowS": float(
                    os.environ.get(SLO_SLOW_WINDOW_S_ENV, 600.0)),
            }
            slo.update(self._slo_overrides.get(table, {}))
            self._slo_cache[table] = slo
        return slo

    def set_slo_override(self, table: str, override: dict) -> None:
        """Table-config SLO override (sentinel folds these in from
        /CONFIGS/TABLE/* keys sloLatencyMs/sloErrorRate/sloPartialRate)."""
        with self._lock:
            self._slo_overrides[table] = dict(override)
            self._slo_cache.pop(table, None)

    def _slo_bucket_s(self, slo: dict) -> float:
        # ≥6 buckets across the fast window keeps the burn rate readable
        return max(slo["fastWindowS"] / 6.0, 0.05)

    # -- recording (broker funnel: pure counter bumps) -----------------------

    def record(self, key: str, *, table: str = "", time_ms: float = 0.0,
               error: bool = False, partial: bool = False,
               dispatches: int = 0, compiles: int = 0,
               cache_outcome: str = "", seg_cache_hits: int = 0,
               seg_cache_misses: int = 0, coalesced: int = 0,
               host_crossings: int = 0, bytes_shuffled: int = 0,
               sql: str = "") -> None:
        now = _mono()
        bidx = _bucket_index(time_ms)
        with self._lock:
            plan = self._plans.get(key)
            if plan is None:
                if len(self._plans) >= self.max_plans:
                    self._evict_locked()
                plan = _Plan(key, table, sql[:120], now)
                self._plans[key] = plan
            elif now - plan.cur_start >= self.window_s:
                self._rotate_plan_locked(plan, now)
            plan.last_update = now
            cur = plan.cur
            cur["queries"] += 1
            cur["latencySumMs"] += time_ms
            cur["latBuckets"][bidx] = cur["latBuckets"].get(bidx, 0) + 1
            cur["dispatches"] += dispatches
            cur["compiles"] += compiles
            cur["hostCrossings"] += host_crossings
            cur["bytesShuffled"] += bytes_shuffled
            cur["segCacheHits"] += seg_cache_hits
            cur["segCacheMisses"] += seg_cache_misses
            cur["coalesced"] += coalesced
            if error:
                cur["errors"] += 1
            if partial:
                cur["partials"] += 1
            if cache_outcome == "hit":
                cur["cacheHits"] += 1
            elif cache_outcome == "miss":
                cur["cacheMisses"] += 1
            elif cache_outcome:
                cur["cacheBypass"] += 1
            tot = plan.tot
            tot["queries"] += 1
            tot["compiles"] += compiles
            if error:
                tot["errors"] += 1
            if partial:
                tot["partials"] += 1
            if table:
                slo = self.slo_for(table)
                bucket_s = self._slo_bucket_s(slo)
                keep = int(slo["slowWindowS"] / bucket_s) + 2
                ts = self._tables.get(table)
                if ts is None:
                    ts = self._tables[table] = _TableSlo()
                ts.bump(int(now / bucket_s), error, partial,
                        time_ms > slo["latencyMs"], keep)

    def note_event(self, kind: str) -> None:
        """Count one engine fallback event (e.g. ``mesh-solo``,
        ``device-join-host``, ``fused-host``). Called from the fallback
        paths themselves — rare by definition, so a lock is fine."""
        with self._lock:
            self._ev_cur[kind] = self._ev_cur.get(kind, 0) + 1
            self._ev_tot[kind] = self._ev_tot.get(kind, 0) + 1

    # -- window rotation -----------------------------------------------------

    def _rotate_plan_locked(self, plan: _Plan, now: float) -> None:
        if plan.cur["queries"]:
            _fold(plan.ref, plan.cur, self.ref_decay)
            plan.ref_weight = plan.ref_weight * self.ref_decay + 1.0
            plan.cur = _fresh_window()
        plan.cur_start = now

    def _rotate_events_locked(self, now: float) -> None:
        if self._ev_cur:
            for k, n in self._ev_cur.items():
                self._ev_ref[k] = self._ev_ref.get(k, 0.0) \
                    * self.ref_decay + n
            self._ev_cur = {}
        self._ev_ref_weight = self._ev_ref_weight * self.ref_decay + 1.0
        self._ev_start = now

    def maybe_rotate(self) -> None:
        """Fold any aged-out short windows into their references (the
        sentinel calls this at every scrape so idle plans still age)."""
        now = _mono()
        with self._lock:
            for plan in self._plans.values():
                if now - plan.cur_start >= self.window_s:
                    self._rotate_plan_locked(plan, now)
            if now - self._ev_start >= self.window_s:
                self._rotate_events_locked(now)

    def rotate_now(self) -> None:
        """Force-fold every live short window into its reference — the
        deterministic handle tests and soaks use to establish a baseline
        without waiting out a wall-clock window."""
        now = _mono()
        with self._lock:
            for plan in self._plans.values():
                self._rotate_plan_locked(plan, now)
            self._rotate_events_locked(now)

    def _evict_locked(self) -> None:
        # batch-evict the stalest ~10% so fingerprint churn amortizes to
        # one scan per max_plans/10 inserts instead of one per insert
        n = max(1, self.max_plans // 10)
        stalest = sorted(self._plans.values(),
                         key=lambda p: p.last_update)[:n]
        for plan in stalest:
            del self._plans[plan.key]
        self._evictions += len(stalest)

    # -- exemplar arming -----------------------------------------------------

    def arm_exemplars(self, alert_id: str, *, plan_key: str = "",
                      table: str = "", count: int = 3) -> None:
        with self._lock:
            if plan_key:
                self._exemplar_targets[("plan", plan_key)] = \
                    [alert_id, count]
            elif table:
                self._exemplar_targets[("table", table)] = [alert_id, count]
            else:
                return
            self.exemplar_armed = True

    def claim_exemplar(self, plan_key: str, table: str):
        """Armed-path half of exemplar pinning: returns the alert id to
        tag the forced sample with, or None. Callers gate on the
        ``exemplar_armed`` attribute first — disarmed queries never take
        this lock."""
        with self._lock:
            for tkey in (("plan", plan_key), ("table", table)):
                tgt = self._exemplar_targets.get(tkey)
                if tgt is not None and tgt[1] > 0:
                    tgt[1] -= 1
                    if tgt[1] <= 0:
                        del self._exemplar_targets[tkey]
                        if not self._exemplar_targets:
                            self.exemplar_armed = False
                    return tgt[0]
        return None

    def disarm_exemplars(self, alert_id: str = "") -> None:
        with self._lock:
            if alert_id:
                self._exemplar_targets = {
                    k: v for k, v in self._exemplar_targets.items()
                    if v[0] != alert_id}
            else:
                self._exemplar_targets = {}
            self.exemplar_armed = bool(self._exemplar_targets)

    # -- introspection -------------------------------------------------------

    def __len__(self) -> int:
        return len(self._plans)

    def plan_windows(self, key: str):
        """(cur, ref, ref_weight, table) snapshot for one plan — the
        sentinel's drift-rule input. Returns None when unseen."""
        with self._lock:
            plan = self._plans.get(key)
            if plan is None:
                return None
            return (dict(plan.cur, latBuckets=dict(plan.cur["latBuckets"])),
                    dict(plan.ref, latBuckets=dict(plan.ref["latBuckets"])),
                    plan.ref_weight, plan.table)

    def keys(self) -> list:
        with self._lock:
            return list(self._plans)

    def tables(self) -> list:
        with self._lock:
            return list(self._tables)

    def burn_rates(self, table: str) -> dict:
        """Multi-window SLO burn rates for one table: consumption rate of
        each error budget over the fast and slow windows (burn 1.0 =
        exactly on budget; the sentinel alerts when BOTH windows burn hot,
        the Google-SRE multiwindow rule that makes one noisy minute
        unable to page)."""
        slo = self.slo_for(table)
        bucket_s = self._slo_bucket_s(slo)
        now_b = int(_mono() / bucket_s)
        with self._lock:
            ts = self._tables.get(table)
            if ts is None:
                return {}
            out = {}
            for label, win_s in (("fast", slo["fastWindowS"]),
                                 ("slow", slo["slowWindowS"])):
                q, err, part, lat = ts.window(
                    now_b + 1, max(1, int(win_s / bucket_s)))
                if q == 0:
                    out[label] = {"queries": 0}
                    continue
                lat_budget = max(1e-9, 1.0 - slo["latencyPct"])
                out[label] = {
                    "queries": q,
                    "errorBurn": (err / q) / max(1e-9, slo["errorRate"]),
                    "partialBurn": (part / q) / max(1e-9,
                                                    slo["partialRate"]),
                    "latencyBurn": (lat / q) / lat_budget,
                }
            out["slo"] = slo
            return out

    def events_windows(self) -> tuple:
        with self._lock:
            return (dict(self._ev_cur), dict(self._ev_ref),
                    self._ev_ref_weight, dict(self._ev_tot))

    def snapshot(self) -> dict:
        """GET /debug/ledger payload: per-plan window summaries plus the
        global fallback-event windows."""
        with self._lock:
            plans = []
            for plan in self._plans.values():
                cur, ref = plan.cur, plan.ref
                plans.append({
                    "fingerprint": plan.key,
                    "table": plan.table,
                    "sql": plan.sql,
                    "firstSeen": plan.first_seen,
                    "totals": dict(plan.tot),
                    "short": {k: cur[k] for k in _COUNTER_KEYS},
                    "shortP50Ms": round(
                        bucket_quantile(cur["latBuckets"], 0.5), 3),
                    "shortP99Ms": round(
                        bucket_quantile(cur["latBuckets"], 0.99), 3),
                    "refWeight": round(plan.ref_weight, 3),
                    "refP50Ms": round(
                        bucket_quantile(ref["latBuckets"], 0.5), 3),
                    "refQueries": round(ref["queries"], 2),
                    "refCompiles": round(ref["compiles"], 2),
                })
            plans.sort(key=lambda p: -p["totals"]["queries"])
            return {
                "windowS": self.window_s,
                "maxPlans": self.max_plans,
                "numPlans": len(self._plans),
                "evictions": self._evictions,
                "plans": plans,
                "fallbackEvents": {
                    "short": dict(self._ev_cur),
                    "ref": {k: round(v, 2)
                            for k, v in self._ev_ref.items()},
                    "total": dict(self._ev_tot),
                },
            }

    # -- persistence (WAL store) ---------------------------------------------

    def persist(self, store) -> None:
        """Snapshot the reference windows into the PropertyStore (one
        ``set`` on LEDGER_PATH — WAL-journaled, so it survives a store
        restart). Called from the sentinel's periodic scrape, NEVER from
        the query path: the store perf guard pins zero journal appends
        per query."""
        with self._lock:
            plans = {}
            # persist the busiest plans first; cap keeps the journal entry
            # bounded no matter how churned the ledger got
            ranked = sorted(self._plans.values(),
                            key=lambda p: -p.tot["queries"])[:256]
            for plan in ranked:
                ref = dict(plan.ref)
                ref["latBuckets"] = {str(k): v for k, v
                                     in plan.ref["latBuckets"].items()}
                plans[plan.key] = {
                    "table": plan.table, "sql": plan.sql,
                    "firstSeen": plan.first_seen,
                    "ref": ref, "refWeight": plan.ref_weight,
                    "totals": dict(plan.tot),
                }
            payload = {
                "version": 1,
                "savedAtMs": int(time.time() * 1000),
                "plans": plans,
                "events": {"ref": dict(self._ev_ref),
                           "refWeight": self._ev_ref_weight,
                           "total": dict(self._ev_tot)},
            }
        store.set(LEDGER_PATH, payload)

    def restore(self, store) -> int:
        """Load persisted reference windows for plans this process has not
        seen yet (live state always wins). Returns the number of plans
        restored."""
        payload = store.get(LEDGER_PATH)
        if not isinstance(payload, dict) or payload.get("version") != 1:
            return 0
        now = _mono()
        restored = 0
        with self._lock:
            for key, rec in (payload.get("plans") or {}).items():
                if key in self._plans:
                    continue
                if len(self._plans) >= self.max_plans:
                    break
                plan = _Plan(key, rec.get("table", ""),
                             rec.get("sql", ""), now)
                plan.first_seen = rec.get("firstSeen", plan.first_seen)
                ref = dict(_fresh_window())
                ref.update({k: v for k, v in (rec.get("ref") or {}).items()
                            if k in _COUNTER_KEYS})
                ref["latBuckets"] = {
                    int(k): float(v) for k, v in
                    ((rec.get("ref") or {}).get("latBuckets") or {}).items()}
                plan.ref = ref
                plan.ref_weight = float(rec.get("refWeight", 0.0))
                plan.tot.update(rec.get("totals") or {})
                self._plans[key] = plan
                restored += 1
            ev = payload.get("events") or {}
            for k, v in (ev.get("ref") or {}).items():
                self._ev_ref.setdefault(k, float(v))
            self._ev_ref_weight = max(self._ev_ref_weight,
                                      float(ev.get("refWeight", 0.0)))
            for k, v in (ev.get("total") or {}).items():
                self._ev_tot[k] = self._ev_tot.get(k, 0) + int(v)
        return restored

    def clear(self) -> None:
        with self._lock:
            self._plans.clear()
            self._tables.clear()
            self._slo_cache.clear()
            self._slo_overrides.clear()
            self._ev_cur, self._ev_ref, self._ev_tot = {}, {}, {}
            self._ev_ref_weight = 0.0
            self._evictions = 0
            self._exemplar_targets = {}
            self.exemplar_armed = False


class AlertBook:
    """Structured alerts keyed by (type, scope key): the sentinel fires
    and resolves; the broker appends exemplar trace ids; the query log
    and REST layer read. Bounded history, newest-first snapshots."""

    def __init__(self, max_history: int = 256):
        self.max_history = max_history
        self._lock = threading.Lock()
        self._alerts: dict[str, dict] = {}  # id -> record
        self._active: dict[tuple, str] = {}  # (type, key) -> id
        self._seq = 0
        self.active_count = 0  # cheap cross-thread read (GIL-atomic int)

    def fire(self, type_: str, key: str, table: str, summary: str,
             details: dict = None) -> tuple:
        """Fire or refresh the (type, key) alert. Returns (id, new)."""
        now_ms = int(time.time() * 1000)
        with self._lock:
            aid = self._active.get((type_, key))
            if aid is not None:
                rec = self._alerts[aid]
                rec["lastUpdateMs"] = now_ms
                rec["fireCount"] += 1
                if summary:
                    rec["summary"] = summary
                if details:
                    rec["details"] = details
                return aid, False
            self._seq += 1
            aid = f"{type_}-{self._seq:04d}"
            self._alerts[aid] = {
                "id": aid, "type": type_, "key": key, "table": table,
                "state": "firing", "summary": summary,
                "details": details or {}, "firstFiredMs": now_ms,
                "lastUpdateMs": now_ms, "fireCount": 1,
                "exemplarTraceIds": [],
            }
            self._active[(type_, key)] = aid
            self.active_count = len(self._active)
            if len(self._alerts) > self.max_history:
                for old in sorted(
                        (a for a in self._alerts.values()
                         if a["state"] != "firing"),
                        key=lambda a: a["lastUpdateMs"])[
                            :len(self._alerts) - self.max_history]:
                    del self._alerts[old["id"]]
            return aid, True

    def resolve(self, type_: str, key: str, reason: str = "recovered"):
        with self._lock:
            aid = self._active.pop((type_, key), None)
            self.active_count = len(self._active)
            if aid is None:
                return None
            rec = self._alerts[aid]
            rec["state"] = "cleared"
            rec["clearedMs"] = int(time.time() * 1000)
            rec["clearReason"] = reason
            return aid

    def note_exemplar(self, alert_id: str, trace_id: str) -> None:
        with self._lock:
            rec = self._alerts.get(alert_id)
            if rec is not None and trace_id not in rec["exemplarTraceIds"]:
                rec["exemplarTraceIds"].append(trace_id)

    def exemplars_pinned(self) -> int:
        with self._lock:
            return sum(len(a["exemplarTraceIds"])
                       for a in self._alerts.values())

    def active_ids_for(self, key: str, table: str) -> list:
        """Active alert ids whose scope matches a plan key or table —
        the querylog cross-link. Only consulted off the hot path (slow
        queries, REST), and only when ``active_count`` is nonzero."""
        with self._lock:
            out = []
            for (typ, k), aid in self._active.items():
                rec = self._alerts[aid]
                if k == key or (table and rec.get("table") == table):
                    out.append(aid)
            return out

    def get(self, alert_id: str):
        with self._lock:
            rec = self._alerts.get(alert_id)
            return dict(rec) if rec is not None else None

    def active(self) -> list:
        with self._lock:
            out = [dict(self._alerts[aid]) for aid in self._active.values()]
            out.sort(key=lambda a: -a["lastUpdateMs"])
            return out

    def snapshot(self) -> dict:
        with self._lock:
            alerts = sorted((dict(a) for a in self._alerts.values()),
                            key=lambda a: -a["lastUpdateMs"])
            return {"active": sum(1 for a in alerts
                                  if a["state"] == "firing"),
                    "alerts": alerts}

    def clear(self) -> None:
        with self._lock:
            self._alerts.clear()
            self._active.clear()
            self._seq = 0
            self.active_count = 0


PERF_LEDGER = PerfLedger()
ALERTS = AlertBook()
