"""Per-segment query planner: QueryContext + segment → kernel Program.

Reference: pinot-core/.../plan/maker/InstancePlanMakerImplV2.java:275
(makeSegmentPlanNode dispatches on query shape) plus the predicate-evaluator
layer (pinot-core/.../operator/filter/predicate/PredicateEvaluatorProvider) —
there, predicates resolve against dictionaries at planning time; here that
resolution produces *device kernel parameters*: sorted dictionaries turn
value predicates into dict-id intervals or boolean LUTs, so the kernel never
touches a string.

Unsupported shapes raise UnsupportedQueryError and the caller falls back to
the host (numpy) engine — mirroring how the reference keeps the scalar path
as default (BASELINE.json north star).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..query.context import QueryContext
from ..query.expressions import ExpressionContext, is_aggregation
from ..query.filter import FilterContext, FilterNodeType, Predicate, PredicateType
from ..query.transforms import IRBuilder, eval_expr_np, get_transform
from ..segment.device_cache import SegmentDeviceView
from ..segment.loader import ImmutableSegment
from ..spi.data_types import DataType
from . import ir
from .aggregation import (DENSE_GROUP_LIMIT, AggPlanContext, LoweredAgg,
                          UnsupportedQueryError, lower_aggregation)

# DENSE_GROUP_LIMIT (re-exported from .aggregation): dense segment_sum
# HBM ceiling shared with the approximate-agg occupancy gate
SPARSE_KEY_LIMIT = ir.SPARSE_KEY_SPACE  # keys stay below the kernel sentinel
SPARSE_GROUPS_LIMIT = 1 << 25  # cap on sparse output table slots (~256MB/agg)
DEFAULT_NUM_GROUPS_LIMIT = 100_000  # reference InstancePlanMakerImplV2 default
_SPARSE_AGG_KINDS = {"count", "sum", "sumsq", "min", "max"}


def _vexpr_uses_slots(ve, slots: set) -> bool:
    """True when a value expression reads any of the given array slots."""
    if ve is None:
        return False
    if isinstance(ve, (ir.Col, ir.IdsCol)):
        return ve.slot in slots
    if isinstance(ve, ir.DictGather):
        return ve.ids_slot in slots or ve.dict_slot in slots
    if isinstance(ve, ir.MvLutReduce):
        return True  # always reads an MV matrix
    if isinstance(ve, ir.ParamGather):
        return _vexpr_uses_slots(ve.ids, slots)
    if isinstance(ve, ir.Bin):
        return _vexpr_uses_slots(ve.a, slots) or _vexpr_uses_slots(ve.b, slots)
    if isinstance(ve, ir.Un):
        return _vexpr_uses_slots(ve.a, slots)
    if isinstance(ve, ir.Cast):
        return _vexpr_uses_slots(ve.a, slots)
    if isinstance(ve, ir.Where):
        return (_vexpr_uses_slots(ve.cond, slots)
                or _vexpr_uses_slots(ve.a, slots)
                or _vexpr_uses_slots(ve.b, slots))
    if isinstance(ve, ir.FilterVal):
        return _filter_uses_slots(ve.filter, slots)
    return False


def _filter_uses_slots(f, slots: set) -> bool:
    if isinstance(f, (ir.FAnd, ir.FOr)):
        return any(_filter_uses_slots(c, slots) for c in f.children)
    if isinstance(f, ir.FNot):
        return _filter_uses_slots(f.child, slots)
    if isinstance(f, ir.Lut):
        return f.ids_slot in slots
    if isinstance(f, ir.Null):
        return f.null_slot in slots
    if isinstance(f, ir.Interval):
        return _vexpr_uses_slots(f.vexpr, slots)
    if isinstance(f, ir.Isin):
        return _vexpr_uses_slots(f.vexpr, slots)
    return False


def _orderby_prefix_trim(q) -> "int | None":
    """offset+limit when ORDER BY is ALL the group-by keys, in stride
    order, all ASC with default null ordering and no HAVING — the shape
    where a per-segment keep-smallest-L composite trim cannot change the
    final result. The cover must be FULL: with a shorter prefix, a group
    trimmed in one segment but kept in another could be selected on a
    prefix tie with an incomplete aggregate unless the broker reduce
    tie-broke on the remaining keys (it doesn't on the dict-merge path)."""
    if q.having_filter is not None or not q.order_by_expressions:
        return None
    gb = q.group_by_expressions
    if q.distinct and not q.is_aggregation_query:
        gb = q.select_expressions
    obs = q.order_by_expressions
    if not gb or len(obs) != len(gb):
        return None
    for ob, ge in zip(obs, gb):
        if not ob.ascending or ob.nulls_last is not None \
                or str(ob.expression) != str(ge):
            return None
    return int(q.offset) + int(q.limit)


@dataclass
class GroupDim:
    column: str
    cardinality: int
    dictionary: object  # segment Dictionary (host) — decodes ids at combine


@dataclass
class DerivedDictionary:
    """Group-index → value table for a derived (expression) dimension."""

    values: np.ndarray


def collect_identifiers(e: ExpressionContext) -> set:
    out = set()
    if e.is_identifier:
        out.add(e.identifier)
    elif e.is_function:
        for a in e.function.arguments:
            out |= collect_identifiers(a)
    return out


def _coerce_like(vals: np.ndarray, v):
    """Coerce a predicate literal to the transformed-value dtype."""
    if vals.dtype.kind in "if":
        if isinstance(v, bool):
            return int(v)
        if isinstance(v, str):
            try:
                return float(v) if vals.dtype.kind == "f" else int(float(v))
            except ValueError:
                return v
        return v
    return str(v)


@dataclass
class SegmentPlan:
    program: ir.Program
    slots: list  # (column, kind); kind ∈ ids|mvids|raw|rawf32r|dict|null
    params: list  # host param values in order (np scalars / arrays)
    lowered_aggs: list[LoweredAgg] = field(default_factory=list)
    group_dims: list[GroupDim] = field(default_factory=list)
    selection_columns: list[str] = field(default_factory=list)
    selection_exprs: dict = field(default_factory=dict)  # label → transform expr
    # per-query kill switch for the single-pass fused kernel
    # (SET useFusedKernel = false — reference pattern: per-query engine
    # toggles like useStarTree applied by the plan maker)
    fused_ok: bool = True

    def gather_arrays(self, view: SegmentDeviceView) -> tuple:
        return self.gather_arrays_packed(view, allow_packed=False)[0]

    def gather_arrays_packed(self, view: SegmentDeviceView,
                             allow_packed: bool = True):
        """(arrays, packed) where packed lists (slot, bits) for id planes
        kept packed in HBM — decoded in-kernel (ops/kernels._apply_packed)."""
        out = []
        packed = []
        for i, (column, kind) in enumerate(self.slots):
            if kind == "ids":
                if allow_packed:
                    plane, bits = view.dict_ids_packed(column)
                    out.append(plane)
                    if bits:
                        packed.append((i, bits))
                else:
                    out.append(view.dict_ids(column))
            elif kind == "mvids":
                out.append(view.mv_dict_ids(column))
            elif kind == "raw":
                out.append(view.raw(column))
            elif kind == "rawf32r":
                out.append(view.raw_f32_rebased(column))
            elif kind == "dict":
                out.append(view.dict_values(column))
            elif kind == "null":
                out.append(view.null_plane(column))
            else:  # pragma: no cover
                raise ValueError(kind)
        return tuple(out), tuple(packed)


class SegmentPlanner(AggPlanContext):
    # realtime/device_plane.py's planner subclass lifts this: a pinned
    # MutableSegmentView exposes enough immutable state (snapshot dict,
    # pinned metadata, pinned validity) to lower device plans safely
    allow_mutable = False

    def __init__(self, query: QueryContext, segment: ImmutableSegment):
        super().__init__()
        if not self.allow_mutable and getattr(segment, "is_mutable", False):
            raise UnsupportedQueryError(
                "consuming (mutable) segments execute on the host engine")
        self.query = query
        self.segment = segment
        self._slots: list[tuple[str, str]] = []
        self._slot_index: dict[tuple[str, str], int] = {}
        self._params: list = []
        # advanced null handling: see QueryContext.null_handling
        self.null_handling = query.null_handling

    # -- slot/param bookkeeping -------------------------------------------
    def slot(self, column: str, kind: str) -> int:
        key = (column, kind)
        if key not in self._slot_index:
            self._slot_index[key] = len(self._slots)
            self._slots.append(key)
        return self._slot_index[key]

    def param(self, value) -> int:
        self._params.append(value)
        return len(self._params) - 1

    # -- column helpers ----------------------------------------------------
    def _meta(self, column: str):
        if not self.segment.has_column(column):
            raise UnsupportedQueryError(f"unknown column {column}")
        return self.segment.column_metadata(column)

    def dict_info(self, e: ExpressionContext, sv_only: bool = False):
        if not e.is_identifier or e.identifier == "*":
            return None
        m = self._meta(e.identifier)
        if m.encoding != "DICT":
            return None
        if sv_only and not m.single_value:
            return None
        kind = "ids" if m.single_value else "mvids"
        return self.slot(e.identifier, kind), m.cardinality, self.segment.get_dictionary(e.identifier)

    def _null_cond_for(self, e: ExpressionContext):
        """Boolean ValueExpr true where any column referenced by e is null
        (a transform over a null input is null — reference semantics), or
        None when advanced null handling is off / no referenced column is
        nullable."""
        if not self.null_handling:
            return None
        cond = None
        for c in sorted(e.columns()):
            if c == "*" or not self.segment.has_column(c) \
                    or not self._meta(c).has_nulls:
                continue
            nc = ir.NullCol(self.slot(c, "null"))
            cond = nc if cond is None else ir.Bin("or", cond, nc)
        return cond

    def agg_operand(self, e: ExpressionContext, identity):
        """value_expr wrapped so null rows contribute the agg identity
        (advanced null handling). identity: 0 | "inf" | "-inf"."""
        ve = self.value_expr(e)
        cond = self._null_cond_for(e)
        if cond is None:
            return ve
        if identity in ("inf", "-inf"):
            # min/max compare in f64 so ±inf identities exist for any dtype
            ve = ir.Cast(ve, "DOUBLE")
            ident = ir.ConstParam(self.param(
                np.float64(np.inf if identity == "inf" else -np.inf)))
        else:
            ident = ir.ConstParam(self.param(np.int64(identity)))
        return ir.Where(cond, ident, ve)

    def nonnull_count_op(self, e: ExpressionContext) -> int:
        """Kernel output index holding the per-group NON-NULL count of e;
        0 (the group doc count) when nulls cannot occur."""
        cond = self._null_cond_for(e)
        if cond is None:
            return 0
        one = ir.ConstParam(self.param(np.int32(1)))
        zero = ir.ConstParam(self.param(np.int32(0)))
        return self.add_op(ir.AggOp(
            "sum", vexpr=ir.Where(cond, zero, one), vmin=0, vmax=1))

    def mv_reduce_expr(self, e: ExpressionContext, op: str):
        """(vexpr, vmin, vmax) per-doc reduce of a numeric MV dict column
        (for SUMMV-family aggs): lut[id] over the (docs, max_mv) id matrix
        with the pad sentinel's lut slot holding the op identity, so
        row-reduces need no mask. op="count" is a param-free non-sentinel
        count. vmin/vmax bound the per-doc result when known (lets integer
        sums take the exact kernel paths). None → host fallback
        (raw/var-width/non-numeric MV)."""
        if not e.is_identifier:
            return None
        m = self._meta(e.identifier)
        if m.single_value or m.encoding != "DICT":
            return None
        slot, card, d = self.dict_info(e)
        max_mv = max(1, m.max_number_of_multi_values)
        if op == "count":
            return ir.MvLutReduce(slot, None, "count", card=card), 0, max_mv
        vals = np.asarray(d.values)
        if vals.dtype.kind not in "iuf" or not len(vals):
            return None  # non-numeric, or every row empty (no dictionary)
        if op == "sum" and vals.dtype.kind in "iu":
            # int64 entries and int64 row-sums: exact, like the host's
            # np.sum over the flattened int column
            lut = np.concatenate([vals.astype(np.int64),
                                  np.zeros(1, np.int64)])
            vmin = min(0, max_mv * int(vals[0]))
            vmax = max(0, max_mv * int(vals[-1]))
            return ir.MvLutReduce(slot, self.param(lut), "sum"), vmin, vmax
        ident = {"sum": 0.0, "min": np.inf, "max": -np.inf}[op]
        lut = np.concatenate([vals.astype(np.float64), [ident]])
        return ir.MvLutReduce(slot, self.param(lut), op), None, None

    def col_meta(self, e: ExpressionContext):
        if not e.is_identifier:
            return None
        return self._meta(e.identifier)

    def _fused_ok(self) -> bool:
        # case-insensitive off-spellings: options arrive as raw strings
        # through the distributed request path (mse/runtime._null_handling
        # normalizes the same way)
        opt = self.query.query_options.get("useFusedKernel")
        return str(opt).lower() not in ("false", "0", "off")

    def col_minmax(self, e: ExpressionContext):
        """(min, max) stats for a plain numeric column, else None — feeds
        fixed-bin device histograms (percentile approx on raw columns)."""
        if not e.is_identifier:
            return None
        m = self._meta(e.identifier)
        if m.min_value is None or m.max_value is None:
            return None
        if not DataType(m.data_type).is_numeric:
            return None
        return m.min_value, m.max_value

    # -- value expressions (device transform functions) --------------------
    def value_expr(self, e: ExpressionContext) -> ir.ValueExpr:
        if e.is_literal:
            v = e.literal
            if isinstance(v, bool):
                v = int(v)
            if not isinstance(v, (int, float)):
                raise UnsupportedQueryError(f"non-numeric literal in value context: {v!r}")
            return ir.ConstParam(self.param(np.float64(v) if isinstance(v, float) else np.int64(v)))
        if e.is_identifier:
            m = self._meta(e.identifier)
            if not m.single_value:
                raise UnsupportedQueryError(f"MV column {e.identifier} in value context")
            dt = DataType(m.data_type)
            if not dt.is_fixed_width:
                raise UnsupportedQueryError(f"var-width column {e.identifier} in value context")
            if m.encoding == "RAW":
                return ir.Col(self.slot(e.identifier, "raw"))
            return ir.DictGather(self.slot(e.identifier, "ids"), self.slot(e.identifier, "dict"))
        fn = e.function
        name, args = fn.name, fn.arguments
        if name in _BIN_FN:
            return ir.Bin(_BIN_FN[name], self.value_expr(args[0]), self.value_expr(args[1]))
        if name in _UN_FN:
            return ir.Un(_UN_FN[name], self.value_expr(args[0]))
        if name == "cast":
            return ir.Cast(self.value_expr(args[0]), str(args[1].literal).upper())
        if name == "case":
            # case(c1,v1,c2,v2,...,else) → nested Where
            pairs = args[:-1]
            out = self.value_expr(args[-1])
            for i in range(len(pairs) - 2, -1, -2):
                out = ir.Where(self.value_expr(pairs[i]), self.value_expr(pairs[i + 1]), out)
            return out
        if name == "coalesce" and args and args[0].is_identifier:
            m = self._meta(args[0].identifier)
            base = self.value_expr(args[0])
            if not m.has_nulls or len(args) < 2:
                return base
            null_slot = self.slot(args[0].identifier, "null")
            return ir.Where(ir.Un("not", ir.Col(null_slot)), base, self.value_expr(args[1]))
        td = get_transform(name)
        if td is not None and td.lower is not None:
            try:
                return td.lower(IRBuilder(self), list(args))
            except (UnsupportedQueryError, ValueError, KeyError):
                pass
        ve = self._dict_transform_expr(e)
        if ve is not None:
            return ve
        raise UnsupportedQueryError(f"transform function {name} not lowered to device")

    DICT_TRANSFORM_LIMIT = 1 << 18  # max cartesian LUT size for 2-col transforms
    # single-column LUTs scale linearly with cardinality (no cartesian
    # blowup): allow dimension-scale columns (LOOKUP joins over ~1M-row
    # dim tables ride a fk-cardinality LUT)
    DICT_TRANSFORM_LIMIT_1COL = 1 << 21

    def _dict_transform_expr(self, e: ExpressionContext) -> Optional[ir.ValueExpr]:
        """Numeric-valued transform over dict-encoded SV columns → evaluate
        over the DICTIONARY (or the cartesian product of two dictionaries) on
        host, ship the result as a LUT param, gather by (joint) dict id on
        device (ir.ParamGather)."""
        prep = self._dict_transform_values(e)
        if prep is None:
            return None
        index_vexpr, out = prep
        if out.dtype.kind == "b":
            out = out.astype(np.int64)
        if out.dtype.kind not in "if":
            return None  # string-valued: usable for predicates/group-by only
        return ir.ParamGather(index_vexpr, self.param(out))

    def _dict_transform_values(self, e: ExpressionContext):
        """(joint-id ValueExpr, transform(dictionary values)) when e is a
        function of 1-2 dict-encoded SV columns, else None. For two columns
        the LUT covers the cardinality cartesian product and the joint id is
        id_a * card_b + id_b — same arithmetic as the dense group key."""
        cols = sorted(collect_identifiers(e))
        if not 1 <= len(cols) <= 2:
            return None
        infos = []
        product = 1
        for c in cols:
            if not self.segment.has_column(c):
                return None
            m = self.segment.column_metadata(c)
            if m.encoding != "DICT" or not m.single_value:
                return None
            vals = np.asarray(self.segment.get_dictionary(c).values)
            infos.append((c, len(vals), vals))
            product *= len(vals)
        limit = (self.DICT_TRANSFORM_LIMIT_1COL if len(infos) == 1
                 else self.DICT_TRANSFORM_LIMIT)
        if product > limit:
            return None
        if len(infos) == 1:
            c, _, vals = infos[0]
            grids = {c: vals}
            index_vexpr: ir.ValueExpr = ir.IdsCol(self.slot(c, "ids"))
        else:
            (c1, k1, v1), (c2, k2, v2) = infos
            grids = {c1: np.repeat(v1, k2), c2: np.tile(v2, k1)}
            index_vexpr = ir.Bin(
                "add",
                ir.Bin("mul", ir.IdsCol(self.slot(c1, "ids")),
                       ir.ConstParam(self.param(np.int32(k2)))),
                ir.IdsCol(self.slot(c2, "ids")))
        try:
            out = eval_expr_np(e, lambda name: grids[name])
        except (UnsupportedQueryError, ValueError, KeyError, TypeError):
            return None
        out = np.asarray(out)
        if out.shape != (product,):
            out = np.broadcast_to(out, (product,)).copy()
        return index_vexpr, out

    def _derived_dim(self, ge: ExpressionContext):
        """Group-by key = transform of one dict column: transform the
        dictionary on host, unique the results, remap dict ids → dense group
        ids through a LUT gather. Covers GROUP BY year(ts), upper(name),
        substr(c,0,3)... with the same dense segment_sum fast path."""
        prep = self._dict_transform_values(ge)
        if prep is None:
            return None
        index_vexpr, out = prep
        uniq, inv = np.unique(out, return_inverse=True)
        vexpr = ir.ParamGather(index_vexpr, self.param(inv.astype(np.int32)))
        return vexpr, len(uniq), DerivedDictionary(uniq)

    # -- filter lowering ---------------------------------------------------
    def lower_filter(self, f: Optional[FilterContext]) -> Optional[ir.FilterNode]:
        if f is None:
            return None
        if self.null_handling:
            true_node, _unknown = self._lower_filter3(f)
            return true_node
        return self._lower_filter(f)

    def _lower_filter(self, f: FilterContext) -> ir.FilterNode:
        if f.type == FilterNodeType.AND:
            return ir.FAnd(tuple(self._lower_filter(c) for c in f.children))
        if f.type == FilterNodeType.OR:
            return ir.FOr(tuple(self._lower_filter(c) for c in f.children))
        if f.type == FilterNodeType.NOT:
            return ir.FNot(self._lower_filter(f.children[0]))
        if f.type == FilterNodeType.CONSTANT:
            return ir.FConst(f.constant_value)
        return self._lower_predicate(f.predicate)

    # -- 3-valued lowering (advanced null handling) ------------------------
    def _lower_filter3(self, f: FilterContext):
        """Kleene logic as a (definitely-true, unknown) node pair — NOT of
        unknown stays unknown (excluded), but a child whose truth is
        DEFINED for null rows (IS NULL, constants, an OR with a true arm)
        keeps them. The final filter is the definitely-true mask."""
        FALSE = ir.FConst(False)

        def is_false(n):
            return isinstance(n, ir.FConst) and not n.value

        if f.type == FilterNodeType.AND:
            ts, us = zip(*(self._lower_filter3(c) for c in f.children))
            t = ir.FAnd(tuple(ts))
            if all(is_false(u) for u in us):
                return t, FALSE
            # unknown: every child true-or-unknown, not all definitely true
            tu = ir.FAnd(tuple(ti if is_false(ui) else ir.FOr((ti, ui))
                               for ti, ui in zip(ts, us)))
            return t, ir.FAnd((tu, ir.FNot(t)))
        if f.type == FilterNodeType.OR:
            ts, us = zip(*(self._lower_filter3(c) for c in f.children))
            t = ir.FOr(tuple(ts))
            if all(is_false(u) for u in us):
                return t, FALSE
            return t, ir.FAnd((ir.FOr(tuple(u for u in us if not is_false(u))),
                               ir.FNot(t)))
        if f.type == FilterNodeType.NOT:
            ct, cu = self._lower_filter3(f.children[0])
            if is_false(cu):
                return ir.FNot(ct), FALSE
            # true ↔ child definitely false; unknown unchanged
            return ir.FAnd((ir.FNot(ct), ir.FNot(cu))), cu
        if f.type == FilterNodeType.CONSTANT:
            return ir.FConst(f.constant_value), FALSE
        p = f.predicate
        node = self._lower_predicate(p)
        if p.type in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
            return node, FALSE  # defined for every row
        unknown = None
        for c in sorted(p.lhs.columns()):
            if self.segment.has_column(c) and self._meta(c).has_nulls:
                nc = ir.Null(self.slot(c, "null"))
                unknown = nc if unknown is None else ir.FOr((unknown, nc))
        if unknown is None:
            return node, FALSE
        return ir.FAnd((node, ir.FNot(unknown))), unknown

    def _lower_predicate(self, p: Predicate) -> ir.FilterNode:
        lhs = p.lhs
        if p.type in (PredicateType.JSON_MATCH, PredicateType.TEXT_MATCH,
                      PredicateType.VECTOR_SIMILARITY):
            return self._lower_host_mask(p)
        if p.type in (PredicateType.IS_NULL, PredicateType.IS_NOT_NULL):
            if not lhs.is_identifier:
                raise UnsupportedQueryError("IS NULL on expressions unsupported")
            m = self._meta(lhs.identifier)
            if not m.has_nulls:
                node = ir.FConst(False)
            else:
                node = ir.Null(self.slot(lhs.identifier, "null"))
            return ir.FNot(node) if p.type == PredicateType.IS_NOT_NULL else node

        info = self.dict_info(lhs) if lhs.is_identifier else None
        if info is not None:
            return self._lower_dict_predicate(p, lhs, info)
        if lhs.is_function:
            # mapvalue(col,'key') over a map index: dense-plane compare on
            # host → mask param (the map-index analogue of _lower_host_mask)
            from .host_executor import eval_map_index_predicate

            mm = eval_map_index_predicate(p, self.segment)
            if mm is not None:
                return self._mask_param(mm)
            try:
                return self._lower_value_predicate(p)
            except UnsupportedQueryError:
                node = self._lower_fn_dict_predicate(p)
                if node is not None:
                    return node
                raise
        return self._lower_value_predicate(p)

    def _lower_fn_dict_predicate(self, p: Predicate) -> Optional[ir.FilterNode]:
        """Predicate over a (possibly string-valued) transform of one dict
        column: evaluate transform + predicate against the dictionary on host
        → boolean LUT over dict ids (e.g. WHERE upper(name) = 'BOS')."""
        prep = self._dict_transform_values(p.lhs)
        if prep is None:
            return None
        index_vexpr, vals = prep
        card = len(vals)
        m = np.zeros(card, dtype=bool)
        if p.type in (PredicateType.EQ, PredicateType.NOT_EQ):
            m = vals == _coerce_like(vals, p.values[0])
            if p.type == PredicateType.NOT_EQ:
                m = ~m
        elif p.type in (PredicateType.IN, PredicateType.NOT_IN):
            for v in p.values:
                m |= vals == _coerce_like(vals, v)
            if p.type == PredicateType.NOT_IN:
                m = ~m
        elif p.type == PredicateType.RANGE:
            m = np.ones(card, dtype=bool)
            if p.lower is not None:
                lo = _coerce_like(vals, p.lower)
                m &= (vals >= lo) if p.lower_inclusive else (vals > lo)
            if p.upper is not None:
                hi = _coerce_like(vals, p.upper)
                m &= (vals <= hi) if p.upper_inclusive else (vals < hi)
        elif p.type in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
            regex = (like_to_regex(p.values[0]) if p.type == PredicateType.LIKE
                     else re.compile(str(p.values[0])))
            m = np.asarray([regex.search(str(x)) is not None for x in vals], dtype=bool)
        else:
            return None
        if isinstance(index_vexpr, ir.IdsCol):
            lut = np.zeros(card + 1, dtype=bool)
            lut[:card] = m
            return ir.Lut(index_vexpr.slot, self.param(lut), mv=False)
        # joint-id LUT: gather 0/1 then compare (ids never exceed the product)
        pi = self.param(np.int32(1))
        return ir.Interval(
            ir.ParamGather(index_vexpr, self.param(m.astype(np.int32))),
            lo_param=pi, hi_param=pi)

    def _lower_dict_predicate(self, p: Predicate, lhs, info) -> ir.FilterNode:
        ids_slot, card, d = info
        m = self._meta(lhs.identifier)
        mv = not m.single_value
        dt = DataType(m.data_type)

        def coerce(v):
            if dt.is_numeric and isinstance(v, bool):
                return int(v)
            return v

        if p.type in (PredicateType.EQ, PredicateType.NOT_EQ):
            did = d.index_of(coerce(p.values[0]))
            if mv:
                # MV predicate semantics are per-VALUE ("any value matches"),
                # so NOT_EQ needs an inverted LUT, not a document-level NOT
                lut = np.zeros(card + 1, dtype=bool)
                if did >= 0:
                    lut[did] = True
                if p.type == PredicateType.NOT_EQ:
                    lut[:card] = ~lut[:card]
                return ir.Lut(ids_slot, self.param(lut), mv=True)
            if did < 0:
                node = ir.FConst(False)
            else:
                node = self._id_interval(ids_slot, did, did, mv, card)
            return ir.FNot(node) if p.type == PredicateType.NOT_EQ else node

        if p.type == PredicateType.RANGE:
            lo_id = 0
            hi_id = card - 1
            if p.lower is not None:
                lo_id = d.insertion_index(coerce(p.lower), "left" if p.lower_inclusive else "right")
            if p.upper is not None:
                hi_id = d.insertion_index(coerce(p.upper), "right" if p.upper_inclusive else "left") - 1
            if lo_id > hi_id:
                return ir.FConst(False)
            if lo_id <= 0 and hi_id >= card - 1 and not mv:
                return ir.FConst(True)
            return self._id_interval(ids_slot, lo_id, hi_id, mv, card)

        if p.type in (PredicateType.IN, PredicateType.NOT_IN):
            lut = np.zeros(card + 1, dtype=bool)
            for v in p.values:
                did = d.index_of(coerce(v))
                if did >= 0:
                    lut[did] = True
            if p.type == PredicateType.NOT_IN:
                lut[:card] = ~lut[:card]
            return ir.Lut(ids_slot, self.param(lut), mv=mv)

        if p.type in (PredicateType.LIKE, PredicateType.REGEXP_LIKE):
            pattern = p.values[0]
            regex = like_to_regex(pattern) if p.type == PredicateType.LIKE else re.compile(str(pattern))
            lut = np.zeros(card + 1, dtype=bool)
            for i, v in enumerate(d.values):
                if regex.search(str(v)) is not None:
                    lut[i] = True
            return ir.Lut(ids_slot, self.param(lut), mv=mv)

        raise UnsupportedQueryError(f"predicate {p.type} not lowered")

    def _lower_host_mask(self, p: Predicate) -> ir.FilterNode:
        """Index-backed predicates without a vector form (JSON_MATCH /
        TEXT_MATCH / VECTOR_SIMILARITY) evaluate on host via their index
        into a doc mask shipped as a kernel param plane."""
        from .host_executor import eval_host_mask

        if not p.lhs.is_identifier:
            raise UnsupportedQueryError(f"{p.type} needs a column lhs")
        return self._mask_param(eval_host_mask(p, self.segment))

    def _mask_param(self, mask: np.ndarray) -> ir.MaskParam:
        """Host-computed doc mask → padded boolean param plane."""
        from ..segment.device_cache import pad_bucket

        padded = np.zeros(pad_bucket(max(1, self.segment.num_docs)), dtype=bool)
        padded[: len(mask)] = mask
        return ir.MaskParam(self.param(padded))

    def _and_valid_docs(self, filt: Optional[ir.FilterNode]) -> Optional[ir.FilterNode]:
        """Upsert tables AND the segment's validity plane into the fused
        filter (reference: FilterPlanNode wraps the filter with the
        validDocIds bitmap for upsert-enabled tables); shipped as a param
        plane so the compiled program is reused as validity evolves."""
        vd = getattr(self.segment, "valid_doc_ids", None)
        if vd is None:
            return filt
        node = self._mask_param(vd.mask(self.segment.num_docs))
        return node if filt is None else ir.FAnd((filt, node))

    def _id_interval(self, ids_slot, lo_id, hi_id, mv, card) -> ir.FilterNode:
        if mv:
            lut = np.zeros(card + 1, dtype=bool)
            lut[lo_id : hi_id + 1] = True
            return ir.Lut(ids_slot, self.param(lut), mv=True)
        return ir.Interval(
            ir.IdsCol(ids_slot),
            lo_param=self.param(np.int32(lo_id)),
            hi_param=self.param(np.int32(hi_id)),
        )

    def _lower_value_predicate(self, p: Predicate) -> ir.FilterNode:
        ve = self.value_expr(p.lhs)
        if p.type in (PredicateType.EQ, PredicateType.NOT_EQ):
            v = p.values[0]
            if isinstance(v, bool):
                v = int(v)
            if isinstance(v, str):
                raise UnsupportedQueryError("string compare on raw column")
            pi = self.param(np.float64(v) if isinstance(v, float) else np.int64(v))
            node = ir.Interval(ve, lo_param=pi, hi_param=pi)
            return ir.FNot(node) if p.type == PredicateType.NOT_EQ else node
        if p.type == PredicateType.RANGE:
            lo = None if p.lower is None else self.param(_num(p.lower))
            hi = None if p.upper is None else self.param(_num(p.upper))
            return ir.Interval(ve, lo_param=lo, hi_param=hi,
                               lo_inclusive=p.lower_inclusive, hi_inclusive=p.upper_inclusive)
        if p.type in (PredicateType.IN, PredicateType.NOT_IN):
            vals = np.asarray([_num(v) for v in p.values])
            node = ir.Isin(ve, self.param(vals))
            return ir.FNot(node) if p.type == PredicateType.NOT_IN else node
        raise UnsupportedQueryError(f"predicate {p.type} on raw column not lowered")

    # -- top-level plan ----------------------------------------------------
    def plan(self) -> SegmentPlan:
        q = self.query
        filt = self.lower_filter(q.filter)
        filt = self._and_valid_docs(filt)

        if q.is_aggregation_query or q.distinct or q.is_group_by:
            group_dims: list[GroupDim] = []
            group_exprs = list(q.group_by_expressions)
            if q.distinct and not q.is_aggregation_query:
                group_exprs = [e for e in q.select_expressions]
            group_slots = []
            group_vexprs = []
            cards = []
            any_derived = False
            mv_group_slot = mv_group_card = None
            for ge in group_exprs:
                if ge.is_identifier:
                    info = self.dict_info(ge)
                    if info is None:
                        raise UnsupportedQueryError(f"group-by on non-dict column {ge}")
                    m = self._meta(ge.identifier)
                    slot, card, d = info
                    if not m.single_value:
                        # ONE MV dim: the kernel expands (doc × mv-slot)
                        # pairs; a second would need a per-doc cross
                        # product (host path handles it)
                        if mv_group_slot is not None:
                            raise UnsupportedQueryError(
                                "group-by on two MV columns needs host path")
                        mv_group_slot, mv_group_card = slot, card
                    group_slots.append(slot)
                    group_vexprs.append(ir.IdsCol(slot))
                    cards.append(card)
                    group_dims.append(GroupDim(ge.identifier, card, d))
                else:
                    derived = self._derived_dim(ge)
                    if derived is None:
                        raise UnsupportedQueryError(f"group-by on expression {ge} needs host path")
                    vexpr, card, dd = derived
                    any_derived = True
                    group_vexprs.append(vexpr)
                    cards.append(card)
                    group_dims.append(GroupDim(str(ge), card, dd))
            num_groups = 1
            for c in cards:
                num_groups *= c
            if num_groups >= SPARSE_KEY_LIMIT:
                raise UnsupportedQueryError(
                    f"group cardinality product {num_groups} exceeds the "
                    "int64 composite-key space")
            # row-major strides (reference DictionaryBasedGroupKeyGenerator:119-137)
            strides = [1] * len(cards)
            for i in range(len(cards) - 2, -1, -1):
                strides[i] = strides[i + 1] * cards[i + 1]

            # lets approximate aggs size their occupancy matrices: e.g. the
            # tdigest family picks exact value-hist vs fixed-bin by whether
            # groups × dict-card fits the dense table
            self.group_card_hint = num_groups
            lowered = [lower_aggregation(self, a) for a in q.aggregations]
            if mv_group_slot is not None:
                if any_derived:
                    raise UnsupportedQueryError(
                        "MV group-by with expression keys needs host path")
                # expansion rewires every 1-D plane: aggs referencing MV
                # matrices (another MV column, or MvLutReduce of this one)
                # would see the wrong shape — host path handles the combo
                mv_slots = {i for i, (_c, k) in enumerate(self._slots)
                            if k == "mvids"}
                for op in self.ops:
                    if (op.ids_slot in mv_slots
                            or _vexpr_uses_slots(op.vexpr, mv_slots)):
                        raise UnsupportedQueryError(
                            "MV aggregation with MV group-by needs host path")
            # mode selection: dense when the key product AND every matrix
            # occupancy fit the segment_sum table; otherwise the sort-based
            # sparse path when every op supports it (scalar reductions +
            # distinct via pair dedup); otherwise host
            dense_ok = num_groups <= DENSE_GROUP_LIMIT
            dense_reason = f"group cardinality product {num_groups}"
            for op in self.ops:
                width = op.card if op.kind in ("distinct_bitmap", "value_hist") else (
                    op.bins if op.kind in ("hist_fixed", "hist_adaptive")
                    else None)
                if width is not None and num_groups * width > DENSE_GROUP_LIMIT:
                    dense_ok = False
                    dense_reason = f"{op.kind} occupancy {num_groups}x{width}"
            sparse = not dense_ok
            if not sparse and group_exprs and self.query.query_options.get(
                    "sparseGroupBy") in (True, "true", 1):
                # per-query escape hatch (SET sparseGroupBy = true): route a
                # dense-eligible group-by through the sparse kernel — lets
                # tests and benchmarks exercise the sort/presorted/device-
                # combine machinery without multi-million-cardinality data
                sparse = True
                dense_reason = "sparseGroupBy=true"
            if sparse:
                n_distinct = sum(1 for op in self.ops
                                 if op.kind == "distinct_bitmap")
                if n_distinct > 1:
                    # one DISTINCT column rides the sort as the secondary
                    # key; a second would need its own n-length sort
                    raise UnsupportedQueryError(
                        "sparse group-by supports one DISTINCT column "
                        "(host path handles more)")
                for op in self.ops:
                    if op.kind == "distinct_bitmap":
                        # the sparse kernel ships per-slot dict-id bitmaps
                        # (ceil(card/32) words/slot) — bound the width
                        if op.card > 1024:
                            raise UnsupportedQueryError(
                                f"sparse DISTINCTCOUNT bitmap over card "
                                f"{op.card} > 1024 runs on the host engine")
                        continue
                    if op.kind not in _SPARSE_AGG_KINDS:
                        raise UnsupportedQueryError(
                            f"{dense_reason} exceeds the dense limit and "
                            f"{op.kind} is unsupported in sparse "
                            "(sort-based) group-by")
            if sparse and not group_exprs:
                # un-grouped aggregation with an oversized occupancy matrix
                # (e.g. DISTINCTCOUNT of a multi-million-card column): the
                # sort kernel needs group keys; host handles this shape
                raise UnsupportedQueryError(
                    f"{dense_reason} exceeds the dense limit for an "
                    "un-grouped aggregation")
            exact_trim = False
            keys_presorted = False
            if (sparse and group_exprs and not any_derived
                    and mv_group_slot is None
                    and all(e.is_identifier for e in group_exprs)):
                # sorted-key fast path: group keys whose COMPOSITE id
                # Σ id_i·stride_i is nondecreasing in doc order need NO
                # sort at all; the kernel reads group edges off the id
                # planes (reference SortedGroupByOperator).
                #   single key  — the column's own dict-id plane is
                #     nondecreasing (sorted ingestion, ColumnMetadata
                #     .is_sorted);
                #   composite — the keys are, IN ORDER, a prefix of the
                #     segment's lexicographic co-sort chain
                #     (SegmentMetadata.sort_order: leading key globally
                #     sorted, later keys sorted within runs of the
                #     prefix). Row-major strides make lexicographic
                #     nondecreasing ids ⇒ nondecreasing composite.
                metas = [self._meta(e.identifier) for e in group_exprs]
                if all(m.single_value for m in metas):
                    if len(group_exprs) == 1:
                        keys_presorted = bool(
                            getattr(metas[0], "is_sorted", False))
                    else:
                        so = list(getattr(
                            getattr(self.segment, "metadata", None),
                            "sort_order", None) or [])
                        cols = [e.identifier for e in group_exprs]
                        keys_presorted = so[:len(cols)] == cols
            if sparse and group_exprs:
                # output capacity = numGroupsLimit: groups beyond it are
                # trimmed on device (reference InstancePlanMakerImplV2:245-270)
                limit = int(q.query_options.get(
                    "numGroupsLimit", DEFAULT_NUM_GROUPS_LIMIT))
                # ORDER-BY pushdown: when the query orders by an ASC prefix
                # of the group keys, the kernel's keep-smallest-L trim is
                # EXACT (sorted dictionaries make composite order =
                # lexicographic value order, and a segment's L smallest keys
                # contain every globally-L-smallest key it holds) — the
                # device then ships L slots instead of millions (reference:
                # ordering-aware server trim, TableResizer/minServerGroupTrimSize)
                trim = None if any_derived else _orderby_prefix_trim(q)
                if trim is not None and trim <= limit:
                    limit = trim
                    exact_trim = True
                mode = "group_by_sparse"
                out_groups = min(num_groups, max(1, limit))
                if out_groups > SPARSE_GROUPS_LIMIT:
                    # bound device output allocation the same way the dense
                    # path bounds its table
                    raise UnsupportedQueryError(
                        f"numGroupsLimit {out_groups} exceeds sparse output "
                        f"cap {SPARSE_GROUPS_LIMIT}")
            else:
                mode = "group_by" if group_exprs else "aggregation"
                out_groups = num_groups
            program = ir.Program(
                mode=mode,
                filter=filt,
                aggs=tuple(self.ops),
                group_slots=() if any_derived else tuple(group_slots),
                group_strides=tuple(strides),
                num_groups=out_groups,
                group_vexprs=tuple(group_vexprs) if any_derived else (),
                key_space=num_groups if mode == "group_by_sparse" else 0,
                exact_trim=exact_trim,
                keys_presorted=(keys_presorted
                                and mode == "group_by_sparse"),
                mv_group_slot=mv_group_slot if mode != "aggregation" else None,
                mv_group_card=mv_group_card if mode != "aggregation" else None,
                mv_doc_slots=tuple(
                    i for i, (_c, k) in enumerate(self._slots)
                    if k in ("ids", "raw", "rawf32r", "null"))
                if mv_group_slot is not None else (),
            )
            return SegmentPlan(program, self._slots, self._params,
                               lowered, group_dims,
                               fused_ok=self._fused_ok())

        # selection: kernel computes the mask; host materializes rows.
        # Transform select/order expressions evaluate host-side over the
        # already-filtered doc ids only — the device's job here is the filter.
        from .selection import selection_columns_for

        sel_cols, sel_exprs = selection_columns_for(q, self.segment)
        for c in sel_cols:
            if c not in sel_exprs:
                self._meta(c)
        program = ir.Program(mode="selection", filter=filt)
        return SegmentPlan(program, self._slots, self._params,
                           selection_columns=sel_cols, selection_exprs=sel_exprs)


_BIN_FN = {
    "plus": "add", "minus": "sub", "times": "mul", "divide": "div", "mod": "mod",
    "pow": "pow", "power": "pow",
    "equals": "eq", "notequals": "ne", "lessthan": "lt", "lessthanorequal": "le",
    "greaterthan": "gt", "greaterthanorequal": "ge",
    "and": "and", "or": "or", "least": "min", "greatest": "max",
}

_UN_FN = {
    "neg": "neg", "abs": "abs", "not": "not", "exp": "exp", "ln": "ln",
    "log10": "log10", "log2": "log2", "sqrt": "sqrt", "ceiling": "ceil",
    "ceil": "ceil", "floor": "floor", "sign": "sign",
}


def _num(v):
    if isinstance(v, bool):
        return np.int64(int(v))
    if isinstance(v, int):
        return np.int64(v)
    if isinstance(v, float):
        return np.float64(v)
    raise UnsupportedQueryError(f"non-numeric literal {v!r} on raw column")


def like_to_regex(pattern: str):
    """SQL LIKE → compiled regex (reference RegexpPatternConverterUtils:
    % → .*, _ → ., everything else escaped)."""
    out = []
    for ch in str(pattern):
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$")
