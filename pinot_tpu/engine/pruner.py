"""Server-side segment pruning: skip segments that cannot match the filter.

Reference: pinot-core/.../query/pruner/ — SegmentPrunerService runs
ColumnValueSegmentPruner (min/max + partition metadata) and
BloomFilterSegmentPruner before planning. Pruning is the highest-leverage
index use in the TPU design: a pruned segment costs zero device dispatches
(vs. the reference where it saves a thread-pool task).

Conservative semantics: return False ("prune") only when the segment
PROVABLY has no matching row. Any uncertainty (expressions over multiple
columns, OR branches we can't bound, missing metadata) keeps the segment.
"""

from __future__ import annotations

from typing import Optional

from ..query.context import QueryContext
from ..query.filter import FilterContext, FilterNodeType, Predicate, PredicateType
from ..segment.loader import ImmutableSegment
from ..spi.partition import get_partition_function


class SegmentPrunerService:
    def prune(self, query: QueryContext, segments: list[ImmutableSegment]):
        """→ (kept_segments, num_pruned)."""
        f = query.filter
        if f is None:
            return list(segments), 0
        kept = [s for s in segments if self._may_match(f, s)]
        return kept, len(segments) - len(kept)

    def _may_match(self, f: FilterContext, seg: ImmutableSegment) -> bool:
        if f.type == FilterNodeType.AND:
            return all(self._may_match(c, seg) for c in f.children)
        if f.type == FilterNodeType.OR:
            return any(self._may_match(c, seg) for c in f.children)
        if f.type == FilterNodeType.NOT:
            return True  # NOT(no-match) proves nothing cheaply
        if f.type == FilterNodeType.CONSTANT:
            return f.constant_value
        return self._predicate_may_match(f.predicate, seg)

    def _predicate_may_match(self, p: Predicate, seg: ImmutableSegment) -> bool:
        lhs = p.lhs
        if not lhs.is_identifier or not seg.has_column(lhs.identifier):
            return True
        col = lhs.identifier
        m = seg.column_metadata(col)
        lo, hi = m.min_value, m.max_value
        if p.type == PredicateType.EQ:
            v = p.values[0]
            if _outside(v, lo, hi):
                return False
            if _partition_excludes(m, v):
                return False
            bf = seg.get_bloom_filter(col)
            if bf is not None and not bf.might_contain(v):
                return False
            return True
        if p.type == PredicateType.IN:
            bf = seg.get_bloom_filter(col)
            for v in p.values:
                if _outside(v, lo, hi):
                    continue
                if _partition_excludes(m, v):
                    continue
                if bf is not None and not bf.might_contain(v):
                    continue
                return True
            return False
        if p.type == PredicateType.RANGE:
            if lo is None or hi is None:
                return True
            try:
                if p.lower is not None:
                    if (hi < p.lower) or (hi == p.lower and not p.lower_inclusive):
                        return False
                if p.upper is not None:
                    if (lo > p.upper) or (lo == p.upper and not p.upper_inclusive):
                        return False
            except TypeError:
                return True  # incomparable types: keep
            return True
        return True


def _partition_excludes(m, v) -> bool:
    """True when stamped partition metadata PROVES the value's partition is
    absent from this segment (reference ColumnValueSegmentPruner's
    partition-metadata branch)."""
    if not m.partition_function or m.partitions is None or m.num_partitions is None:
        return False
    try:
        fn = get_partition_function(m.partition_function, m.num_partitions)
        return fn.partition(v) not in m.partitions
    except (ValueError, TypeError):
        return False  # unknown function / unpartitionable value: keep


def _outside(v, lo, hi) -> bool:
    if lo is None or hi is None:
        return False
    try:
        return v < lo or v > hi
    except TypeError:
        return False


def prune_by_time(
    segments: list[ImmutableSegment],
    time_column: Optional[str],
    start: Optional[int],
    end: Optional[int],
) -> list[ImmutableSegment]:
    """Broker-style time pruning off segment metadata start/end times
    (reference TimeSegmentPruner, pinot-broker/.../routing/segmentpruner/)."""
    if time_column is None or (start is None and end is None):
        return list(segments)
    out = []
    for s in segments:
        s0, s1 = s.metadata.start_time, s.metadata.end_time
        if s0 is None or s1 is None:
            out.append(s)
            continue
        if (end is not None and s0 > end) or (start is not None and s1 < start):
            continue
        out.append(s)
    return out
